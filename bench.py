"""Benchmark: batched Ed25519 verification + notarisation round trip.

Run on whatever JAX backend is live (the real TPU chip under the driver; CPU
elsewhere). Prints ONE JSON line:

  {"metric": "verified_sigs_per_sec", "value": N, "unit": "sigs/sec",
   "vs_baseline": N, ...}

vs_baseline is value / 50_000 — the BASELINE.md north-star target
(>= 50k verified sigs/sec on one TPU v5e-1 chip).  The workload mirrors the
reference's raft-notary-demo driven through NotaryFlow (reference:
samples/raft-notary-demo/src/main/kotlin/net/corda/notarydemo/NotaryDemo.kt:
14-29, core/.../flows/NotaryFlow.kt:96-147): every signature rides the batch
axis of the JAX verify kernel instead of the reference's sequential
EdDSAEngine loop (core/.../transactions/SignedTransaction.kt:83-87).

Measurements:
  kernel_sigs_per_sec[bucket]  device graph only (arrays resident, jit warm)
  e2e_sigs_per_sec[bucket]     host packing (SHA-512 challenge, bit unpack,
                               transfer) + kernel + readback
  sha256_hashes_per_sec        batched 64-byte Merkle-node hashing kernel
  notary_roundtrip             MockNetwork notarisation flows with the
                               JaxVerifier: tx/sec and per-flow p50/p99
  cpu_oracle_sigs_per_sec      the pure-Python conformance oracle, for scale
"""

from __future__ import annotations

import json
import statistics
import time

import numpy as np


BASELINE_SIGS_PER_SEC = 50_000.0
BUCKETS = (4096, 16384, 65536)
N_DISTINCT = 64  # distinct (pk, msg, sig) tuples, tiled to bucket size


def make_corpus(n_distinct: int = N_DISTINCT):
    """n distinct signatures, 1 in 8 corrupted (notaries see mostly-valid)."""
    from corda_tpu.crypto import ref_ed25519 as ref

    pks, msgs, sigs, valid = [], [], [], []
    for i in range(n_distinct):
        sk = bytes([(i % 255) + 1]) * 32
        pk = ref.public_key(sk)
        m = (b"bench-tx-id-%06d" % i).ljust(32, b".")  # tx ids are 32 bytes
        s = ref.sign(sk, m)
        ok = i % 8 != 7
        if not ok:
            s = s[:10] + bytes([s[10] ^ 0x40]) + s[11:]
        pks.append(pk)
        msgs.append(m)
        sigs.append(s)
        valid.append(ok)
    return pks, msgs, sigs, valid


def tile(xs, n):
    return [xs[i % len(xs)] for i in range(n)]


def _time_median(fn, repeats=5):
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def warm_buckets(pks, msgs, sigs, fallback_budget_s=600.0):
    """Compile the PRIMARY verify backends (Pallas kernel + device-hash
    route) at every bucket, then the plain-XLA fallback graphs, outside any
    timed region. Two reasons: (a) a mid-timing Pallas transient must fall
    back to an ALREADY-COMPILED XLA graph, not pay a multi-minute compile
    inside the measurement (that pollution is what round 3's 6.9k "XLA"
    numbers were); (b) the persistent compile cache gets populated so later
    runs start warm.

    The fallback graphs are warmed under a time budget: on a cold compile
    cache each one costs minutes (r03: 3-5 min/bucket) and they serve ONLY
    the failure path — the watchdog must not be eaten by insurance."""
    import sys
    import time as _time

    import jax

    from corda_tpu.ops import ed25519_jax

    staged = []
    for bucket in BUCKETS:
        bp, bm, bs = tile(pks, bucket), tile(msgs, bucket), tile(sigs, bucket)
        arrays, _ = ed25519_jax.precompute_batch(bp, bm, bs, bucket=bucket)
        arrays = jax.device_put(arrays)
        ed25519_jax.verify_arrays_auto(*arrays).block_until_ready()
        darrays, _ = ed25519_jax.precompute_batch_device(bp, bm, bs,
                                                         bucket=bucket)
        np.asarray(ed25519_jax.verify_arrays_hashed(*darrays))
        del darrays
        staged.append((bucket, arrays))
    # Largest bucket first: the budget buys the most expensive insurance
    # (and the headline 64k measurement's fallback) before the cheap ones.
    t0 = _time.monotonic()
    for bucket, arrays in reversed(staged):
        if _time.monotonic() - t0 > fallback_budget_s:
            print(f"warm_buckets: fallback warm budget exhausted before "
                  f"bucket {bucket}; a mid-run Pallas failure there would "
                  f"pay its XLA compile in-measurement", file=sys.stderr)
            break
        ed25519_jax.verify_arrays(*arrays).block_until_ready()  # XLA graph
    staged.clear()


def bench_kernel(pks, msgs, sigs, valid):
    """Device-only and end-to-end verify throughput per bucket size.
    Returns (kernel, e2e, devhash, backends) — backends records which
    backend (pallas/xla) produced each timed number."""
    import jax

    from corda_tpu.ops import ed25519_jax

    kernel, e2e, devhash = {}, {}, {}
    backends = {"kernel": {}, "e2e": {}, "e2e_devhash": {}}
    for bucket in BUCKETS:
        bp = tile(pks, bucket)
        bm = tile(msgs, bucket)
        bs = tile(sigs, bucket)
        arrays, _ = ed25519_jax.precompute_batch(bp, bm, bs, bucket=bucket)
        arrays = jax.device_put(arrays)

        def run_kernel():
            ed25519_jax.verify_arrays_auto(*arrays).block_until_ready()

        run_kernel()  # compile
        out = np.asarray(ed25519_jax.verify_arrays_auto(*arrays))
        expect = tile(valid, bucket)
        assert out.tolist() == expect, "kernel diverged from oracle expectation"
        kernel[bucket] = bucket / _time_median(run_kernel)
        backends["kernel"][bucket] = ed25519_jax.last_backend()

        def run_e2e():
            a, _ = ed25519_jax.precompute_batch(bp, bm, bs, bucket=bucket)
            np.asarray(ed25519_jax.verify_arrays_auto(*a))

        run_e2e()
        e2e[bucket] = bucket / _time_median(run_e2e, repeats=3)
        backends["e2e"][bucket] = ed25519_jax.last_backend()
        del arrays  # cap device residency before the next phase

        def run_devhash():
            a, _ = ed25519_jax.precompute_batch_device(bp, bm, bs,
                                                       bucket=bucket)
            np.asarray(ed25519_jax.verify_arrays_hashed(*a))

        run_devhash()  # compile
        out = np.asarray(ed25519_jax.verify_arrays_hashed(
            *ed25519_jax.precompute_batch_device(bp, bm, bs,
                                                 bucket=bucket)[0]))
        assert out.tolist() == expect, "device-hash path diverged from oracle"
        devhash[bucket] = bucket / _time_median(run_devhash, repeats=3)
        backends["e2e_devhash"][bucket] = ed25519_jax.last_backend()
    return kernel, e2e, devhash, backends


def bench_stream(pks, msgs, sigs, valid, bucket=65536, batches=5,
                 repeats=3):
    """Sustained throughput with the depth-2 stream pipeline: host packing
    and transfer of the next batches overlap device execution of the
    current one (the notary-pump steady state).

    Best of `repeats` timed passes, with every pass reported: the phase is
    transfer-bound, and the tunnel's host<->device bandwidth varies
    run-to-run by >2x (artifacts/BENCH_r05_local_{a,b}.json: 217k vs 92k
    sigs/s an hour apart, same code, kernel-only simultaneously 372k vs
    413k). The best pass is the honest capability number — the spread is
    link weather, not framework behaviour — and reporting all passes keeps
    the variance visible instead of laundered."""
    from corda_tpu.ops import ed25519_jax

    bp, bm, bs = tile(pks, bucket), tile(msgs, bucket), tile(sigs, bucket)
    expect = tile(valid, bucket)

    def gen(k):
        for _ in range(k):
            yield bp, bm, bs

    for out in ed25519_jax.verify_stream(gen(2), bucket=bucket):  # warm
        assert out.tolist() == expect, "stream diverged from oracle"
    rates = []
    backends_per_pass = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        consumed = 0
        for out in ed25519_jax.verify_stream(gen(batches), bucket=bucket):
            consumed += len(out)
        dt = time.perf_counter() - t0
        assert consumed == batches * bucket
        rates.append(consumed / dt)
        # Stamp per pass: a mid-repeats Pallas trip must not attribute the
        # winning (earlier, Pallas) pass to the XLA fallback or vice versa.
        backends_per_pass.append(ed25519_jax.last_backend())
    best = max(range(repeats), key=lambda i: rates[i])
    return (rates[best], [round(r, 1) for r in rates],
            backends_per_pass[best])


def bench_sha256(n=16384):
    """Batched Merkle-node (64-byte) hashing throughput."""
    import jax

    from corda_tpu.ops import sha256_jax

    msgs = np.arange(n * 64, dtype=np.uint64).view(np.uint8)[: n * 64]
    msgs = msgs.reshape(n, 64)
    blocks = jax.device_put(sha256_jax.pack_messages(msgs))

    def run():
        sha256_jax.sha256_blocks(blocks).block_until_ready()

    run()
    return n / _time_median(run)


def bench_cpu_oracle(pks, msgs, sigs, seconds=2.0):
    from corda_tpu.crypto import ref_ed25519 as ref

    count = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        i = count % len(sigs)
        ref.verify(pks[i], msgs[i], sigs[i])
        count += 1
    return count / (time.perf_counter() - t0)


def bench_notary_roundtrip(n_flows=64, verifier=None):
    """End-to-end notarisation over MockNetwork with the JAX verifier:
    issue -> move -> NotaryClientFlow per transaction, all concurrent, one
    pump; reports tx/sec and per-flow p50/p99 (the BASELINE.md latency
    metric, measured over the deterministic in-process network)."""
    from corda_tpu.crypto.provider import (
        CpuVerifier, JaxVerifier, set_verifier)
    from corda_tpu.flows.notary import NotaryClientFlow
    from corda_tpu.testing.dummies import DummyContract
    from corda_tpu.testing.mock_network import MockNetwork

    verifier = verifier or JaxVerifier()
    set_verifier(verifier)
    try:
        net = MockNetwork(verifier=verifier)
        notary = net.create_notary_node("Notary", validating=False)
        alice = net.create_node("Alice")

        stxs = []
        for i in range(n_flows):
            builder = DummyContract.generate_initial(
                alice.identity.ref(bytes([i % 256])), i, notary.identity)
            builder.sign_with(alice.key)
            issue_stx = builder.to_signed_transaction()
            alice.record_transaction(issue_stx)
            move = DummyContract.move(
                issue_stx.tx.out_ref(0), alice.identity.owning_key)
            move.sign_with(alice.key)
            stxs.append(
                move.to_signed_transaction(check_sufficient_signatures=False))

        # Warm the pump-path executable OUTSIDE the timed region (the CPU
        # verifier never touches the device — and in degraded mode any jax
        # call could hang on the wedged tunnel).
        if not isinstance(verifier, CpuVerifier):
            _warm_verify_kernel()

        t0 = time.perf_counter()
        done_at = []
        handles = []
        for stx in stxs:
            h = alice.start_flow(NotaryClientFlow(stx))
            h.result.add_done_callback(
                lambda _f: done_at.append(time.perf_counter() - t0))
            handles.append(h)
        net.run_network()
        total = time.perf_counter() - t0
        for h in handles:
            h.result.result()  # raise on any failure
        lat = sorted(done_at)
        return {
            "tx_per_sec": round(n_flows / total, 1),
            "p50_ms": round(1e3 * lat[len(lat) // 2], 2),
            "p99_ms": round(
                1e3 * lat[min(len(lat) - 1, int(len(lat) * 0.99))], 2),
            **_verifier_stamp(verifier),
        }
    finally:
        set_verifier(None)


def _verifier_stamp(verifier) -> dict:
    """Self-describing config stamp (round-4 verdict weak #4): every
    framework number records WHICH verifier produced it, and — for jax
    verifiers only — which kernel backend served the newest call (via
    ops.last_backend_if_loaded, which never imports the kernel module
    into a host-only run)."""
    from corda_tpu.ops import last_backend_if_loaded

    name = getattr(verifier, "name", type(verifier).__name__)
    backend = None
    if isinstance(name, str) and name.startswith("jax"):
        backend = last_backend_if_loaded()
    stamp = {"verifier": name, "backend": backend}
    # Size-crossover routing counters (JaxVerifier.device_min_sigs): where
    # did the batches actually go — a "jax-batch" stamp whose work all
    # routed to the host tier must say so.
    if getattr(verifier, "device_batches", None) is not None:
        stamp["device_batches"] = verifier.device_batches
        stamp["host_batches"] = verifier.host_batches
        stamp["device_min_sigs"] = verifier.device_min_sigs
        total = verifier.device_batches + verifier.host_batches
        # Occupancy at a glance: the r05 regression class (device_batches=0
        # buried in a long stamp) reads as 0.0 here instead of hiding.
        stamp["device_occupancy"] = (
            round(verifier.device_batches / total, 3) if total else 0.0)
        if verifier.device_batches == 0 and verifier.host_batches > 0:
            # The kernel backend did not produce THIS config's numbers —
            # every batch took the host tier (last_backend would report
            # whatever the warm-up compiled, a misattribution).
            stamp["backend"] = "host-routed"
    return stamp


def _warm_verify_kernel():
    """Compile the pump-path executable (device-hash route for 32-byte tx
    ids at the small bucket) outside any timed/deadlined region. Production
    nodes warm at boot the same way."""
    from corda_tpu.ops import ed25519_jax as _ej

    _ej.verify_batch([bytes(32)], [bytes(32)], [bytes(64)])


def _churn_flows():
    """Module-level (qualname-stable) flow pair for bench_flow_churn —
    flow names are registry keys, so they must not be function-local."""
    from corda_tpu.flows.api import FlowLogic, flow_registry, register_flow

    existing = flow_registry.get("ChurnPing")
    if existing is not None:
        return existing, flow_registry.get("ChurnPong")

    @register_flow(name="ChurnPing")
    class ChurnPing(FlowLogic):
        def __init__(self, other, payload):
            self.other = other
            self.payload = payload

        def call(self):
            reply = yield self.send_and_receive(self.other, self.payload)
            return reply.unwrap()

    @register_flow(name="ChurnPong")
    class ChurnPong(FlowLogic):
        def __init__(self, other):
            self.other = other

        def call(self):
            got = yield self.receive(self.other)
            yield self.send(self.other, got.unwrap() * 2)

    return ChurnPing, ChurnPong


def bench_flow_churn(n_flows=512):
    """Flow-machinery throughput: request/response flow pairs per second
    over MockNetwork, checkpointing at every suspension. The reference
    whitepaper names fiber checkpointing (stack walk + Kryo + DB write per
    suspend) as the node's main bottleneck
    (corda-technical-whitepaper.tex:1630-1638); this measures our
    replay-log checkpoint design on the same shape of workload."""
    from corda_tpu.testing.mock_network import MockNetwork

    ChurnPing, ChurnPong = _churn_flows()
    net = MockNetwork()
    try:
        a = net.create_node("ChurnA")
        b = net.create_node("ChurnB")
        b.smm.register_flow_initiator(
            "ChurnPing", lambda party: ChurnPong(party))
        # warm one round (session handshake code paths)
        h = a.start_flow(ChurnPing(b.identity, 1))
        net.run_network()
        assert h.result.result() == 2
        base = (a.smm.metrics.get("checkpointing_rate", 0)
                + b.smm.metrics.get("checkpointing_rate", 0))
        t0 = time.perf_counter()
        handles = [a.start_flow(ChurnPing(b.identity, i))
                   for i in range(n_flows)]
        net.run_network()
        dt = time.perf_counter() - t0
        for i, h in enumerate(handles):
            assert h.result.result() == 2 * i
        checkpoints = (a.smm.metrics.get("checkpointing_rate", 0)
                       + b.smm.metrics.get("checkpointing_rate", 0)) - base
        return {"flow_pairs_per_sec": round(n_flows / dt, 1),
                "checkpoints_recorded": checkpoints}
    finally:
        net.stop_nodes()


def bench_trades(n_trades=6, verifier=None):
    """BASELINE config 2 (trader-demo): DvP CommercialPaper-for-cash trades
    through the validating notary over MockNetwork. Issues happen outside
    the timed region; each timed trade is the full SellerFlow/BuyerFlow
    composition (resolution, contract verify, notarise, broadcast)."""
    from corda_tpu.contracts.structures import Issued, Timestamp, now_micros
    from corda_tpu.crypto.provider import (
        CpuVerifier, JaxVerifier, set_verifier)
    from corda_tpu.finance import Amount, Cash
    from corda_tpu.finance.commercial_paper import CommercialPaper
    from corda_tpu.finance.trade import BuyerFlow, SellerFlow
    from corda_tpu.flows.notary import NotaryClientFlow
    from corda_tpu.testing.mock_network import MockNetwork

    WEEK = 7 * 86_400 * 1_000_000
    verifier = verifier or JaxVerifier()
    set_verifier(verifier)
    try:
        # Warm the kernel FIRST: a cold jit compile mid-issue would stall
        # past the notary's timestamp tolerance window.
        if not isinstance(verifier, CpuVerifier):
            _warm_verify_kernel()
        net = MockNetwork(verifier=verifier)
        notary = net.create_notary_node("Notary", validating=True)
        seller = net.create_node("Seller")
        buyer = net.create_node("Buyer")
        papers = []
        for i in range(n_trades):
            ref = seller.identity.ref(bytes([i + 1]))
            issue = CommercialPaper.generate_issue(
                ref, Amount(900, Issued(ref, "USD")),
                now_micros() + WEEK, notary.identity)
            issue.set_time(Timestamp.around(now_micros(), 30_000_000))
            issue.sign_with(seller.key)
            stx = issue.to_signed_transaction(
                check_sufficient_signatures=False)
            h = seller.start_flow(NotaryClientFlow(stx))
            net.run_network()
            stx = stx.with_additional_signature(h.result.result())
            seller.record_transaction(stx)
            papers.append(stx.tx.out_ref(0))
            cash = Cash.generate_issue(
                Amount(800, "USD"), buyer.identity.ref(bytes([i + 1])),
                buyer.identity.owning_key, notary.identity, nonce=i)
            cash.sign_with(buyer.key)
            buyer.record_transaction(cash.to_signed_transaction())
        buyer.register_initiated_flow(
            "SellerFlow",
            lambda party: BuyerFlow(party, Amount(750, "USD"),
                                    notary.identity))
        durations = []
        t0 = time.perf_counter()
        for paper in papers:
            t1 = time.perf_counter()
            h = seller.start_flow(SellerFlow(
                buyer.identity, paper, Amount(750, "USD")))
            net.run_network()
            h.result.result()
            durations.append(time.perf_counter() - t1)
        dt = time.perf_counter() - t0
        return {"trades_per_sec": round(n_trades / dt, 2),
                "trade_median_ms": round(
                    1e3 * statistics.median(durations), 1),
                **_verifier_stamp(verifier)}
    finally:
        set_verifier(None)


def bench_multisig(n_distinct=64, tile_to=2048, verifier=None):
    """BASELINE config 4: 3-of-3 CompositeKey multi-sig fan-out — kernel
    verify of all constituent signatures plus the host-side composite
    fulfilment walk per transaction."""
    from corda_tpu.crypto.composite import CompositeKey
    from corda_tpu.crypto.keys import KeyPair
    from corda_tpu.crypto.provider import JaxVerifier, VerifyJob

    signers = [KeyPair.generate(bytes([0x31 + i]) * 32) for i in range(3)]
    composite = CompositeKey.Builder().add_keys(
        *[CompositeKey.leaf(kp.public) for kp in signers]).build(threshold=3)
    txs = []
    rng = np.random.default_rng(5)
    for i in range(n_distinct):
        msg = rng.integers(0, 256, 32, np.uint8).tobytes()
        sigs = [kp.sign(msg) for kp in signers]
        if i % 8 == 7:  # drop a signature: fulfilment must fail
            sigs = sigs[:2]
        txs.append((msg, sigs))
    txs = [txs[i % n_distinct] for i in range(tile_to)]

    verifier = verifier or JaxVerifier()
    jobs = [VerifyJob(sig.by.encoded, msg, sig.bytes)
            for msg, sigs in txs for sig in sigs]
    spans = []
    start = 0
    for msg, sigs in txs:
        spans.append((start, start + len(sigs)))
        start += len(sigs)

    def run():
        ok = verifier.verify_batch(jobs)
        fulfilled = 0
        for (msg, sigs), (lo, hi) in zip(txs, spans):
            valid = {sigs[k - lo].by for k in range(lo, hi) if ok[k]}
            if composite.is_fulfilled_by(valid):
                fulfilled += 1
        return fulfilled

    fulfilled = run()  # compile + correctness
    assert fulfilled == sum(1 for m, s in txs if len(s) == 3), fulfilled
    dt = _time_median(run, repeats=3)
    return {"sigs_per_sec": round(len(jobs) / dt, 1),
            "tx_per_sec": round(len(txs) / dt, 1),
            **_verifier_stamp(verifier)}


def bench_partial_merkle(n_cmds=8, repeats=2000):
    """BASELINE config 5 (simm-valuation shape): FilteredTransaction
    tear-off proof verification rate (host-side partial-Merkle walk, the
    oracle's per-request hot path)."""
    from corda_tpu.contracts.structures import Command
    from corda_tpu.crypto.keys import KeyPair
    from corda_tpu.crypto.party import Party
    from corda_tpu.flows.oracle import Fix, FixOf
    from corda_tpu.testing.dummies import DummyContract
    from corda_tpu.transactions.builder import TransactionBuilder
    from corda_tpu.transactions.filtered import (
        FilteredTransaction, FilterFuns)

    notary = Party.of("N", KeyPair.generate(b"\x41" * 32).public)
    party = Party.of("P", KeyPair.generate(b"\x42" * 32).public)
    builder = DummyContract.generate_initial(party.ref(b"\x01"), 1, notary)
    for i in range(n_cmds):
        builder.add_command(Command(Fix(FixOf("LIBOR", 20_000 + i, "3M"),
                                        42_500 + i),
                                    (party.owning_key,)))
    wtx = builder.to_wire_transaction()
    ftx = FilteredTransaction.build_merkle_transaction(
        wtx, FilterFuns(filter_commands=lambda c: isinstance(c.value, Fix)))
    assert ftx.verify(wtx.id)
    t0 = time.perf_counter()
    for _ in range(repeats):
        ftx.verify(wtx.id)
    dt = time.perf_counter() - t0
    return {"proofs_per_sec": round(repeats / dt, 1),
            "revealed_commands": n_cmds}


def bench_raft_cluster(n_tx=1000, width=32, verifier="cpu",
                       notary_device="cpu", notary="raft", sidecar=False,
                       sidecar_devices=0, adaptive_coalesce=False):
    """BASELINE config 1 (raft-notary-demo) at BASELINE size: a real 3-node
    Raft notary cluster, every node its OWN OS process (own GIL, TCP
    sockets, sqlite), firehosed by two client processes running the
    width-N multisig FirehoseFlow (reference: LoadTest.kt:39-144's
    remote-nodes shape + NotaryDemo.kt:14-29).

    TWO configs report:
      * raft_notary_3node — raft-SIMPLE, host crypto: the r1-r4 trend line
        (a non-validating notary verifies no signatures itself, so the
        clients' verification dominates).
      * raft_validating_3node — raft-VALIDATING, the reference demo's
        actual service type (samples/raft-notary-demo/.../Main.kt:11
        starts RaftValidatingNotaryService), with
        notary_device="accelerator": the FIRST member (the usual leader)
        owns the real device — the production topology, with the TPU
        inside the measurement. The node boot-warms the kernel behind a
        host-gate (node.py _warm_verifier_maybe) so backend init/compile
        never stalls the run loop; under backlog the leader's verify pump
        accumulates >= device_min_sigs and engages the kernel, light
        rounds route to the host tier — node_stamps + routing counters
        attribute exactly where batches went.
    loadtest_sigs_per_sec counts every pump verification across client
    AND notary processes via RPC metric deltas.

    sidecar=True spawns the host's ONE device-owning verification server
    (crypto/sidecar.py) and points every raft member at it, so verify
    micro-batches coalesce ACROSS processes — the fix for the r05 flagship
    shape where every member's batches sat below device_min_sigs and
    device_batches stayed 0. The "sidecar" field carries the server's
    stats (batch-size histogram, cross-request coalescing, device/host
    batches); device_occupancy aggregates the members' routing either way
    so host-only runs report the same schema."""
    from corda_tpu.tools.loadtest import run_loadtest_multiprocess

    res = run_loadtest_multiprocess(
        n_tx=n_tx, width=width, clients=2, notary=notary,
        verifier=verifier, client_verifier="cpu",
        notary_device=notary_device, max_seconds=420.0, sidecar=sidecar,
        sidecar_devices=sidecar_devices, adaptive_coalesce=adaptive_coalesce)
    dev_b = sum((s or {}).get("device_batches") or 0
                for s in res.node_stamps.values())
    host_b = sum((s or {}).get("host_batches") or 0
                 for s in res.node_stamps.values())
    return {"harness": "multiprocess-driver", "n_tx": n_tx, "width": width,
            "notary": notary,
            "tx_per_sec": res.tx_per_sec,
            "loadtest_sigs_per_sec": res.sigs_per_sec,
            "sigs_verified": res.sigs_verified,
            "committed": res.tx_committed,
            "p50_ms": res.p50_ms, "p99_ms": res.p99_ms,
            "verifier": verifier, "notary_device": notary_device,
            "device_warm_wait_s": res.device_warm_wait_s,
            "device_batches": dev_b,
            "host_batches": host_b,
            "device_occupancy": (round(dev_b / (dev_b + host_b), 3)
                                 if (dev_b + host_b) else 0.0),
            "sidecar": res.sidecar,
            "sidecar_devices": sidecar_devices or None,
            "adaptive_coalesce": adaptive_coalesce,
            "node_stamps": res.node_stamps}


def bench_validating_flagship(**kw):
    """The raft_validating_3node flagship, run as a STATIC/ADAPTIVE
    coalesce-window A/B (ROADMAP item 1 leftover: the adaptive controller
    shipped in PR 7 off by default — this arms it in the flagship path and
    stamps the verdict instead of leaving the flag dead). The returned
    dict IS the armed (adaptive) run, so the flagship keys keep their
    grep-able shape; the static counterpart and the verdict ride under
    "adaptive_coalesce_ab"."""
    kw.setdefault("n_tx", 400)
    kw.setdefault("notary", "raft-validating")
    kw.setdefault("sidecar", True)
    before = bench_raft_cluster(adaptive_coalesce=False, **kw)
    after = bench_raft_cluster(adaptive_coalesce=True, **kw)

    def _hoist(run):
        return {k: run.get(k) for k in (
            "tx_per_sec", "p50_ms", "p99_ms", "loadtest_sigs_per_sec")}

    b_tx, a_tx = before.get("tx_per_sec") or 0.0, after.get("tx_per_sec") or 0.0
    b_p99, a_p99 = before.get("p99_ms") or 0.0, after.get("p99_ms") or 0.0
    after["adaptive_coalesce_ab"] = {
        "static": _hoist(before),
        "adaptive": _hoist(after),
        "static_sidecar": before.get("sidecar"),
        "tx_per_sec_ratio": round(a_tx / b_tx, 3) if b_tx else None,
        "p99_ratio": round(a_p99 / b_p99, 3) if b_p99 else None,
        # The arming bar: adaptive must not cost meaningful throughput
        # (>= 95% of static) nor blow the tail (<= 120% of static p99) —
        # the controller's job is to EARN its shorter windows under gaps.
        "adaptive_no_worse": bool(
            b_tx and a_tx >= 0.95 * b_tx
            and (not b_p99 or a_p99 <= 1.2 * b_p99)),
    }
    return after


def bench_resolve_ids(n_tx=2048, outputs_per_tx=8, host_only=False):
    """Resolve-path id recomputation (reference hot spot:
    MerkleTransaction.kt:26-38 driven by ResolveTransactionsFlow): a wave of
    downloaded transactions has every component leaf hashed in bulk via
    SignedTransaction.prime_ids. Measures the SAME work on the host
    (hashlib) and device (sha256_jax) backends; hash_many_auto's crossover
    constant decides which serves production traffic."""
    from corda_tpu.crypto.keys import KeyPair
    from corda_tpu.crypto.party import Party
    from corda_tpu.serialization.codec import deserialize, serialize
    from corda_tpu.testing.dummies import DummyContract, DummySingleOwnerState
    from corda_tpu.transactions.signed import SignedTransaction

    notary = Party.of("N", KeyPair.generate(b"\x61" * 32).public)
    party = Party.of("P", KeyPair.generate(b"\x62" * 32).public)
    key = KeyPair.generate(b"\x62" * 32)
    blobs = []
    n_leaves = 0
    for i in range(n_tx):
        b = DummyContract.generate_initial(
            party.ref(i.to_bytes(4, "big")), i, notary)
        for j in range(outputs_per_tx - 1):
            b.add_output_state(DummySingleOwnerState(
                i * 1000 + j, party.owning_key))
        b.sign_with(key)
        stx = b.to_signed_transaction(check_sufficient_signatures=False)
        n_leaves += len(stx.tx.all_leaves_hashes)
        blobs.append(serialize(stx).bytes)

    out = {"n_tx": n_tx, "leaves": n_leaves}
    backends = ((("host", 1 << 62),) if host_only
                else (("host", 1 << 62), ("device", 0)))
    for label, device_min in backends:
        batch = [deserialize(raw) for raw in blobs]  # cold caches
        t0 = time.perf_counter()
        backend = SignedTransaction.prime_ids(batch, device_min=device_min)
        dt = time.perf_counter() - t0
        assert backend == label, backend
        out[f"{label}_leaves_per_sec"] = round(n_leaves / dt, 1)
        out[f"{label}_tx_per_sec"] = round(n_tx / dt, 1)
    from corda_tpu.ops.sha256_jax import DEVICE_MIN_HASHES_DEFAULT

    out["auto_crossover_hashes"] = DEVICE_MIN_HASHES_DEFAULT
    return out


def bench_open_loop_latency():
    """Open-loop tail latency at stated offered loads (BASELINE metric 2 is
    p99 notarise latency): the firehose paced by rate_tx_s, per-tx latency
    measured from scheduled submission. Two max_wait_ms settings show the
    micro-batch knob's latency/throughput trade."""
    from corda_tpu.tools.loadtest import run_latency_sweep

    out = {}
    # Round-15 ladder: the vectorized ingest plane (columnar build +
    # native batch sign) moved the per-client pacing ceiling from ~150
    # tx/s to the multi-thousand range, so the old (30, 90, 150) rungs
    # all sat under the knee — 720 offered now reaches it.
    for max_wait in (2.0, 20.0):
        sweep = run_latency_sweep(rates=(60.0, 240.0, 720.0), n_tx=250,
                                  max_wait_ms=max_wait)
        out[f"max_wait_{max_wait:g}ms"] = {
            f"{rate:g}_tx_s": {
                "p50_ms": r.p50_ms, "p90_ms": r.p90_ms, "p99_ms": r.p99_ms,
                "tx_per_sec": r.tx_per_sec, "committed": r.committed}
            for rate, r in sweep.items()}
    return out


def bench_raft_open_loop(rates=(60.0, 240.0, 720.0, 1800.0), n_tx=200,
                         verifier="cpu", notary_device="cpu",
                         sidecar=False, clients=3):
    """Open-loop tail latency for the FLAGSHIP config: the 3-member raft
    cluster through real OS processes, firehose paced at stated offered
    loads (round-4 VERDICT item 4 — BASELINE metric 2, p99 notarise
    latency, was only ever measured closed-loop for raft, which reports
    pure queueing delay instead of latency at load). Same width/rates as
    the simple-notary sweep so the two configs compare directly.
    node_stamps attribute each member's verify routing for the sweep —
    device_batches, pipeline depth, overlap ratio (the async-pipeline
    numbers the flagship config is judged on) — plus the commit-pipeline
    stamps, summarised once under "replication" from the leader's view:
    entries_per_batch, replication RTT, reply-coalesce ratio, and the
    transport burst sizes (ARCHITECTURE.md "Commit pipeline").

    The sweep runs with the tracing subsystem armed (corda_tpu/obs/) and
    emits stage_breakdown: p50/p99/mean per notarise stage (queue_wait,
    verify_wait, device_verify, raft_append, fsync, replication, reply)
    across every traced transaction — WHERE the p99 lives, not just what
    it is. stage_sum_over_e2e near 1.0 certifies the stages account for
    the measured end-to-end latency."""
    from corda_tpu.obs import collect as obs_collect
    from corda_tpu.tools.loadtest import run_latency_sweep

    # clients=3 splits each offered rate across three generator processes.
    # Round 15 retired the old ~150 tx/s per-client GIL ceiling: prepare
    # is columnar (build_chunk_columnar + the native batch signer), so a
    # single client builds thousands of tx/s and the drive loop paces far
    # past the old 360 ceiling. The ladder now matches the simple-notary
    # sweep's rungs (60/240/720) plus an 1800 saturation rung — every
    # rung past the cluster's measured committed rate (~40 tx/s at
    # host parity) measures the NOTARY, which is the point; the ingest
    # plane's own capability is measured separately by bench_ingest_sweep.
    sweep = run_latency_sweep(rates=rates, n_tx=n_tx, width=4,
                              clients=clients,
                              notary="raft-validating", coalesce_ms=10.0,
                              verifier=verifier, notary_device=notary_device,
                              trace=True, sidecar=sidecar)
    try:
        breakdown = obs_collect.stage_breakdown(sweep.trace_snapshots)
    except Exception as e:  # a malformed snapshot costs the breakdown only
        breakdown = {"error": f"{type(e).__name__}: {e}"}
    dev_b = sum((s or {}).get("device_batches") or 0
                for s in sweep.node_stamps.values())
    host_b = sum((s or {}).get("host_batches") or 0
                 for s in sweep.node_stamps.values())
    return {"harness": "multiprocess-driver", "width": 4, "n_tx": n_tx,
            "clients": clients,
            "notary": "raft-validating", "verifier": verifier,
            "notary_device": notary_device,
            "coalesce_ms": 10.0,
            "device_batches": dev_b,
            "host_batches": host_b,
            "device_occupancy": (round(dev_b / (dev_b + host_b), 3)
                                 if (dev_b + host_b) else 0.0),
            "sidecar": sweep.sidecar,
            "node_stamps": sweep.node_stamps,
            "replication": _replication_summary(sweep.node_stamps),
            "stage_breakdown": breakdown,
            "rates": {
                f"{rate:g}_tx_s": {
                    "p50_ms": r.p50_ms, "p90_ms": r.p90_ms,
                    "p99_ms": r.p99_ms, "tx_per_sec": r.tx_per_sec,
                    "committed": r.committed}
                for rate, r in sweep.items()}}


def _replication_summary(node_stamps):
    """One commit-pipeline summary from the member that actually drove
    replication: prefer the stamp whose raft role is "leader", fall back
    to the member with the most append frames (a leader change mid-sweep
    leaves two partial leader views; the busier one wrote the batches).
    Returns None when no member carries a raft stamp — the guard test and
    the bench contract both treat that as "replication stamps missing"."""
    best_name, best, best_frames = None, None, -1
    for name, stamp in (node_stamps or {}).items():
        raft = (stamp or {}).get("raft") or {}
        if not raft:
            continue
        frames = raft.get("append_frames") or 0
        lead = raft.get("role") == "leader"
        if best is None or (lead and best.get("role") != "leader") \
                or (lead == (best.get("role") == "leader")
                    and frames > best_frames):
            best_name, best, best_frames = name, raft, frames
    if best is None:
        return None
    transport = (node_stamps.get(best_name) or {}).get("transport") or {}
    return {"member": best_name,
            "role": best.get("role"),
            "group_commit": best.get("group_commit"),
            "group_commits": best.get("group_commits"),
            "entries_per_batch": best.get("entries_per_batch"),
            "append_frames": best.get("append_frames"),
            "append_entries_sent": best.get("append_entries_sent"),
            "replication_rtt_ms_avg": best.get("replication_rtt_ms_avg"),
            "reply_coalesce_ratio": best.get("reply_coalesce_ratio"),
            "outbox_burst_avg": transport.get("outbox_burst_avg"),
            "bridge_flush_avg": transport.get("bridge_flush_avg")}


def bench_slo_sweep(rates=(120.0, 240.0, 480.0), n_tx=240, width=4,
                    clients=2, interactive_frac=0.25, slo_ms=250.0,
                    queue_watermark=48, flagship_tx_s=40.0,
                    notary="simple", verifier="cpu", notary_device="cpu",
                    sidecar=False, flight_dir=None):
    """The QoS plane's SLO section (round 12, ROADMAP open item 4): the
    mixed-lane offered-load sweep run TWICE over the same rates — once
    with the plane armed ([qos] enabled on every node: lane-ordered SMM
    scheduling, deadline early-flush at the three batching points, bulk
    watermark shedding at the notarise entry) and once with qos=false,
    which is bit-identical to the pre-QoS tree. At each offered load every
    client process drives an interactive firehose (interactive_frac of the
    rate, deadline = slo_ms per tx) and a bulk firehose (the remainder)
    CONCURRENTLY, so the lanes contend at the notary.

    The verdict is the explicit SLO line: at the top offered rate —
    chosen ≥ 5× the flagship cluster's measured committed rate
    (~40 tx/s host-parity, see raft_validating_3node), i.e. well past
    saturation — armed interactive p99 must stay within slo_ms while bulk
    absorbs the overload as admission sheds; the no-QoS baseline shows
    both lanes collapsing together. slo_ms defaults to 250 ms: the
    1-core driver host's simple-notary p99 at mid load is ~50 ms, so
    250 ms is "flat through saturation", not "fast" — the claim under
    test is the SHAPE (flat vs collapsing), the bound makes it
    falsifiable on this hardware."""
    from corda_tpu.tools.loadtest import run_slo_sweep

    def _lane_stats(sweep):
        return {f"{rate:g}_tx_s": {
                    lane: {"p50_ms": r.p50_ms, "p90_ms": r.p90_ms,
                           "p99_ms": r.p99_ms, "tx_per_sec": r.tx_per_sec,
                           "requested": r.requested,
                           "committed": r.committed, "shed": r.shed}
                    for lane, r in by_lane.items()}
                for rate, by_lane in sweep.items()}

    out = {"harness": "multiprocess-driver", "notary": notary,
           "width": width, "n_tx": n_tx, "clients": clients,
           "interactive_frac": interactive_frac, "slo_ms": slo_ms,
           "queue_watermark": queue_watermark,
           "verifier": verifier, "notary_device": notary_device,
           "rates_tx_s": list(rates)}
    # Flight recorder (obs/telemetry.py): the armed sweep runs with the
    # driver-side recorder on — if any rung breaches the interactive SLO
    # the breaching window dumps exactly one artifact here, and the
    # report says where. (The baseline sweep runs unarmed: it EXISTS to
    # collapse, dumping its expected breach would be noise.)
    import tempfile as _tempfile

    if flight_dir is None:
        flight_dir = _tempfile.mkdtemp(prefix="corda-tpu-flight-")
    armed = run_slo_sweep(
        rates=rates, n_tx=n_tx, width=width, clients=clients,
        interactive_frac=interactive_frac, slo_ms=slo_ms,
        queue_watermark=queue_watermark, notary=notary, verifier=verifier,
        notary_device=notary_device, sidecar=sidecar, qos=True,
        flight_dir=flight_dir)
    out["qos"] = _lane_stats(armed)
    out["member_qos"] = armed.qos
    out["sidecar"] = armed.sidecar
    out["flight"] = {"dir": flight_dir,
                     "artifacts": getattr(armed, "flight", None) or []}
    # Cluster telemetry fold (obs/export.collect_cluster): the merged
    # per-phase counters across members — round_breakdown at sweep scope.
    out["cluster_telemetry"] = (getattr(armed, "telemetry", None)
                                or {}).get("merged")
    baseline = run_slo_sweep(
        rates=rates, n_tx=n_tx, width=width, clients=clients,
        interactive_frac=interactive_frac, slo_ms=slo_ms,
        queue_watermark=queue_watermark, notary=notary, verifier=verifier,
        notary_device=notary_device, sidecar=sidecar, qos=False)
    out["no_qos_baseline"] = _lane_stats(baseline)
    top = max(rates)
    a_int, a_bulk = armed[top]["interactive"], armed[top]["bulk"]
    b_int = baseline[top]["interactive"]
    within = a_int.p99_ms <= slo_ms
    shed = a_bulk.shed > 0
    out["verdict"] = {
        "offered_top_tx_s": top,
        "flagship_committed_tx_s": flagship_tx_s,
        "offered_over_flagship": round(top / flagship_tx_s, 1),
        "interactive_p99_ms": a_int.p99_ms,
        "interactive_p99_within_slo": within,
        "bulk_shed": a_bulk.shed,
        "bulk_shed_nonzero": shed,
        "baseline_interactive_p99_ms": b_int.p99_ms,
        "interactive_vs_baseline": (round(b_int.p99_ms / a_int.p99_ms, 2)
                                    if a_int.p99_ms else None),
        "slo_met": bool(within and shed),
    }
    # Measured-saturation admission: derive the per-lane rates the static
    # TOML used to guess from THIS armed sweep (qos/calibrate.py). Stamped
    # beside the sweep so the knobs always travel with the observations
    # that produced them; apply_calibration pushes them into a live
    # controller. Round 15 raised the default ladder (vectorized ingest
    # paces it now), so the calibration provenance is re-derived from the
    # new, deeper-saturation rungs on every run.
    try:
        from corda_tpu.qos import calibrate_admission

        out["calibration"] = calibrate_admission(
            {rate: by_lane for rate, by_lane in armed.items()},
            slo_ms=slo_ms)
    except Exception as e:
        out["calibration"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def bench_telemetry(n_tx=80):
    """The always-on telemetry plane's own section (round 16): run the
    in-process loadtest against a FRESH registry and report what the
    plane measured about it — the round profiler's phase breakdown (the
    block that decomposes the ingest sweep's ``first_bottleneck =
    "rounds"`` verdict into poll/verify_wait/seal/replicate/apply/reply
    shares), plus a self-check that the Prometheus exposition the node
    and sidecar endpoints serve round-trips through the parser with
    every registered metric present. Host-only safe by construction:
    nothing here touches a device — which is exactly the claim
    ("always-on" must mean on THIS path too)."""
    from corda_tpu.obs import telemetry as _tm
    from corda_tpu.obs.export import parse_prometheus, render_prometheus
    from corda_tpu.tools.loadtest import run_loadtest

    reg = _tm.ACTIVE if _tm.ACTIVE is not None else _tm.arm()
    reg.reset()
    res = run_loadtest(n_tx=n_tx, notary="simple")
    c = reg.snapshot()["counters"]
    rounds = int(c["rounds_total"])
    wall = c["round_wall_seconds_total"]
    rp = {p: c[f"round_phase_{p}_seconds_total"] for p in _tm.ROUND_PHASES}
    breakdown = _tm.format_breakdown(rp | {"wall": wall, "rounds": rounds})
    coverage = (breakdown or {}).get("coverage")
    text = render_prometheus(reg)
    parsed = parse_prometheus(text)
    return {
        "harness": "in-process",
        "n_tx": n_tx,
        "committed": res.tx_committed,
        "tx_per_sec": res.tx_per_sec,
        # The acceptance bound: named sub-phases must attribute >= 90%
        # of measured round wall time (measured here across BOTH
        # in-process nodes — client and notary share the registry).
        "round_breakdown": breakdown,
        "breakdown_ok": bool(coverage is not None and coverage >= 0.9),
        # /metrics validity: every registered series present and parseable.
        "prometheus_bytes": len(text),
        "prometheus_ok": bool(
            set(parsed["counters"]) == set(_tm.COUNTER_NAMES)
            and set(parsed["histograms"]) == set(_tm.HISTOGRAM_NAMES)),
        "flows_started": int(c["flows_started_total"]),
        "flows_completed": int(c["flows_completed_total"]),
        "verify_batches": int(c["verify_batches_total"]),
        "verify_sigs": int(c["verify_sigs_total"]),
    }


def bench_doctor(report):
    """The performance doctor's section (round 17): diagnose THIS report
    and stamp the verdict into it — the roofline (committed/e2e rates vs
    the measured kernel-stream ceiling, gap factored per layer) and the
    evidence-ranked ``bottlenecks`` list with a suggested next experiment
    per entry (obs/doctor). Then feed the trajectory store: normalize the
    report into one schema-versioned record, compare it against the last
    record of its kind (delta + regression gate under the default
    tolerance policy), and append it to ``artifacts/TRAJECTORY.jsonl``
    (``CORDA_TPU_TRAJECTORY`` overrides the path; append is best-effort —
    a read-only checkout costs the append, never the verdict).

    Runs LAST on both phase paths on purpose: the verdict must see every
    section the run managed to produce, including the host-only path's
    ``cpu_oracle_sigs_per_sec`` ceiling fallback."""
    import os as _os

    from corda_tpu.obs import doctor as _doctor
    from corda_tpu.obs import telemetry as _tm

    _tm.inc("doctor_runs_total")
    verdict = _doctor.diagnose(_doctor.extract_signals(report))
    record = _doctor.normalize_record(report, source="bench_run")
    path = _os.environ.get("CORDA_TPU_TRAJECTORY") or _os.path.join(
        _os.path.dirname(_os.path.abspath(__file__)),
        "artifacts", "TRAJECTORY.jsonl")
    out = {"verdict": verdict, "record": record,
           "trajectory": {"path": path}}
    try:
        prior = _doctor.load_trajectory(path)
        out["trajectory"]["delta"] = _doctor.trajectory_delta(prior, record)
        gate = _doctor.gate(prior + [record])
        out["trajectory"]["gate"] = gate
        if not gate["ok"]:
            _tm.inc("doctor_gate_regressions_total",
                    len(gate["regressions"]))
        _doctor.append_trajectory(path, record)
        out["trajectory"]["appended"] = True
    except (OSError, ValueError) as e:
        out["trajectory"]["error"] = f"{type(e).__name__}: {e}"
        out["trajectory"]["appended"] = False
    return out


def bench_autotune(rate_tx_s=2400.0, n_tx=400, workers=2, budget=3,
                   seed=7):
    """The autotune plane's section (round 21): the closed loop finding
    a config that beats the hand-tuned default. One baseline ingest run
    at defaults produces a REAL doctor verdict (stamp_attribution over
    the member stamps); the controller maps its top bottleneck's
    structured experiment spec to a sweep, evaluates ``budget`` gated
    candidates through the same multiprocess harness (config knobs ride
    CORDA_TPU_CONFIG_OVERLAY to every spawned node), and commits the
    winner as a TOML overlay. The headline is best_value vs
    baseline_value on the swept metric — ">= the hand-tuned default" by
    construction, because a loop that finds nothing better commits
    nothing and the incumbent stands.

    The search is replayable: the stamped seed + decision_sequence
    replay the identical decisions against the same measurements. The
    run's ``autotune`` provenance record (verdict consumed, every
    candidate's values/metrics/gate outcome) appends to the trajectory
    store exactly like bench_doctor's (CORDA_TPU_TRAJECTORY overrides
    the path; append is best-effort — a read-only checkout costs the
    append, never the section)."""
    import os as _os

    from corda_tpu.autotune import controller as _ctl
    from corda_tpu.obs import doctor as _doctor
    from corda_tpu.tools.loadtest import run_ingest_sweep

    sweep = run_ingest_sweep(rates=(rate_tx_s,), n_tx=n_tx, width=1,
                             workers=workers, max_seconds=240.0)
    rows = [r for r in sweep.results.values()
            if isinstance(r, dict) and "error" not in r]
    if not rows:
        return {"error": "baseline ingest run failed every rate",
                "rates": {f"{k:g}_tx_s": v
                          for k, v in sweep.results.items()}}
    peak = max(rows, key=lambda r: r.get("achieved_tx_s") or 0.0)
    baseline_metrics = {
        "peak_achieved_tx_s": peak.get("achieved_tx_s"),
        "p99_ms": peak.get("p99_ms"),
        "exactly_once_all": all(bool(r.get("exactly_once"))
                                for r in rows),
    }
    verdict = sweep.doctor or {}
    try:
        spec = _ctl.spec_from_verdict(verdict)
    except ValueError:
        # The short baseline abstained (or implicated an un-sweepable
        # experiment): sweep the default exploratory knobs instead of
        # producing no section.
        spec = _ctl.exploratory_spec()
    runner = _ctl.make_ingest_runner(rates=(rate_tx_s,), n_tx=n_tx,
                                     workers=workers, max_seconds=240.0)
    result = _ctl.run_autotune(
        spec, runner, budget=budget, seed=seed,
        baseline_metrics=baseline_metrics,
        verdict_consumed={
            "source": "bench_autotune_baseline",
            "first_bottleneck": verdict.get("first_bottleneck"),
            "experiment_id": spec.experiment_id,
        })
    section = {
        "harness": "multiprocess-driver",
        "rate_tx_s": rate_tx_s, "n_tx": n_tx, "workers": workers,
        "seed": seed, "budget": budget,
        "experiment_id": result["experiment_id"],
        "cause": result["cause"],
        "knobs": result["knobs"],
        "metric": result["metric"],
        "first_bottleneck": verdict.get("first_bottleneck"),
        "baseline_value": result["baseline_value"],
        "best_value": result["best_value"],
        "improved": result["improved"],
        "improvement_pct": result["improvement_pct"],
        "candidates_evaluated": result["candidates_evaluated"],
        "gate_rejections": result["gate_rejections"],
        "decision_sequence": result["decision_sequence"],
        "committed_values": (result["overlay"] or {}).get("values"),
        "committed_overlay": (result["overlay"] or {}).get("toml"),
        "candidates": result["candidates"],
        "doctor": verdict,
    }
    record = _doctor.normalize_record(result, source="bench_autotune")
    path = _os.environ.get("CORDA_TPU_TRAJECTORY") or _os.path.join(
        _os.path.dirname(_os.path.abspath(__file__)),
        "artifacts", "TRAJECTORY.jsonl")
    section["trajectory"] = {"path": path}
    try:
        _doctor.append_trajectory(path, record)
        section["trajectory"]["appended"] = True
    except (OSError, ValueError) as e:
        section["trajectory"]["error"] = f"{type(e).__name__}: {e}"
        section["trajectory"]["appended"] = False
    return section


def bench_vault_scaling(sizes=(10_000, 100_000, 1_000_000), queries=48,
                        selections=48, boot_batch=2048, parity_n=300):
    """The indexed vault plane's scale proof (round 22): coin selection,
    pushdown queries and balances against stores of 10k/100k/1M
    unconsumed states, all host-path in-process (the claim is index
    behaviour, not crypto).

    Per size the section seeds a fresh sqlite vault (vault.seed_states —
    the bank-day bulk path), then measures keyset-paginated VaultQuery
    pages, soft-locked select_coins walks (reservations released after
    each round so the store is identical for every sample) and the O(1)
    balances aggregate. The headline
    ``vault_coin_selection_p99_ratio`` is the largest store's selection
    p99 over the smallest's — sublinear_ok pins it within 10x across a
    100x size spread, the difference between an index walk and the scan
    the in-memory engine would do.

    A boot leg replays the same ledger twice: a fresh in-memory engine
    streaming every transaction (what legacy boot does) vs a restarted
    indexed engine whose persisted watermark says the store is current —
    ``vault_boot_speedup`` is full-replay over incremental, the round-22
    restart claim.

    A parity leg drives one issue+spend stream through both engines and
    pins identical unconsumed refs, blobs and balances
    (``vault_parity_ok`` — perfdoctor gates it as a hard flag)."""
    import os
    import tempfile

    from corda_tpu.contracts.structures import (
        Issued,
        StateAndRef,
        StateRef,
        TransactionState,
    )
    from corda_tpu.crypto.hashes import SecureHash
    from corda_tpu.crypto.party import PartyAndReference
    from corda_tpu.finance.amount import Amount
    from corda_tpu.finance.cash import CashState
    from corda_tpu.node.services.inmemory import NodeVaultService
    from corda_tpu.node.services.persistence import NodeDatabase
    from corda_tpu.node.services.vault import (
        IndexedVaultService,
        VaultQuery,
        seed_states,
    )
    from corda_tpu.serialization.codec import serialize
    from corda_tpu.testing.identities import ALICE, DUMMY_NOTARY, MEGA_CORP
    from corda_tpu.utils.bytes import OpaqueBytes

    token = Issued(PartyAndReference(MEGA_CORP, OpaqueBytes(b"\x01")),
                   "USD")
    notary = DUMMY_NOTARY

    def our_keys():
        return set(ALICE.owning_key.keys)

    def tx_hash(i: int) -> SecureHash:
        # Unique 32 bytes without a sha256 per row (million-row seeds).
        return SecureHash(i.to_bytes(16, "big") + b"vault-bench-pad!")

    def state_at(i: int) -> TransactionState:
        # LCG amounts: deterministic spread so the amount index is real.
        qty = 1 + (i * 6364136223846793005 + 1442695040888963407) % 9973
        return TransactionState(CashState(Amount(int(qty), token),
                                          ALICE.owning_key), notary)

    def p99_ms(lat: list) -> float:
        lat = sorted(lat)
        return round(1e3 * lat[min(len(lat) - 1, int(len(lat) * 0.99))], 4)

    class _SeedTx:
        """Signed-tx shim: .tx/.id/inputs/outputs/out_ref — everything
        notify_all touches, none of the Merkle cost."""

        __slots__ = ("id", "inputs", "outputs")

        def __init__(self, id, outputs, inputs=()):
            self.id = id
            self.outputs = tuple(outputs)
            self.inputs = tuple(inputs)

        @property
        def tx(self):
            return self

        def out_ref(self, i):
            return StateAndRef(self.outputs[i], StateRef(self.id, i))

    class _SeedStorage:
        """stream_since twin over an in-memory tx list whose position
        mirrors the transactions-table rowid (rows inserted in order)."""

        def __init__(self, txs):
            self._txs = list(txs)

        def stream_since(self, after_rowid=0, batch=512):
            start = int(after_rowid)
            for i, stx in enumerate(self._txs[start:], start=start + 1):
                yield i, stx

    per_size = {}
    select_p99 = {}
    query_p99 = {}
    for n in sizes:
        with tempfile.TemporaryDirectory() as tmp:
            db = NodeDatabase(os.path.join(tmp, "vault.db"))
            vault = IndexedVaultService(db, our_keys)
            t0 = time.perf_counter()
            seed_states(vault, (
                StateAndRef(state_at(i), StateRef(tx_hash(i), 0))
                for i in range(n)))
            seed_s = time.perf_counter() - t0
            q_lat, cursor = [], None
            for _ in range(queries):
                t = time.perf_counter()
                page = vault.query(VaultQuery(currency="USD",
                                              after=cursor, page_size=256))
                q_lat.append(time.perf_counter() - t)
                cursor = page.next_cursor
            s_lat = []
            for _ in range(selections):
                t = time.perf_counter()
                coins = vault.select_coins("USD", 25_000, holder=b"bench")
                s_lat.append(time.perf_counter() - t)
                vault.release_coins([c.ref for c in coins],
                                    holder=b"bench")
            t = time.perf_counter()
            balances = vault.balances()
            balance_ms = round(1e3 * (time.perf_counter() - t), 4)
            db.close()
        select_p99[n] = p99_ms(s_lat)
        query_p99[n] = p99_ms(q_lat)
        per_size[f"{n}_states"] = {
            "states": n, "seed_s": round(seed_s, 2),
            "query_p99_ms": query_p99[n],
            "select_p99_ms": select_p99[n],
            "balance_ms": balance_ms,
            "balance_usd": balances.get("USD"),
        }

    lo, hi = min(sizes), max(sizes)
    ratio = round(select_p99[hi] / max(select_p99[lo], 1e-4), 2)

    # Boot leg: full replay vs watermark-incremental on the middle store.
    boot_n = sorted(sizes)[1] if len(sizes) > 1 else sizes[0]
    txs = [_SeedTx(tx_hash(i), (state_at(i),)) for i in range(boot_n)]
    storage = _SeedStorage(txs)
    with tempfile.TemporaryDirectory() as tmp:
        db = NodeDatabase(os.path.join(tmp, "boot.db"))
        with db.lock:
            db.conn.executemany(
                "INSERT INTO transactions (tx_id, blob) VALUES (?, ?)",
                ((stx.id.bytes, b"") for stx in txs))
            db.commit()
        vault = IndexedVaultService(db, our_keys)
        vault.rebuild_from(storage, batch=boot_batch)  # initial build
        t0 = time.perf_counter()
        legacy = NodeVaultService(our_keys)
        chunk = []
        for _rowid, stx in storage.stream_since(0, batch=boot_batch):
            chunk.append(stx)
            if len(chunk) >= boot_batch:
                legacy.notify_all(chunk)
                chunk = []
        if chunk:
            legacy.notify_all(chunk)
        full_replay_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        reopened = IndexedVaultService(db, our_keys)  # "restart"
        replayed = reopened.rebuild_from(storage, batch=boot_batch)
        incremental_s = time.perf_counter() - t0
        watermark = reopened.watermark
        db.close()
    boot_speedup = round(full_replay_s / max(incremental_s, 1e-6), 1)

    # Parity leg: one issue+spend stream, both engines, identical sets.
    par_txs = [_SeedTx(tx_hash(i), (state_at(i),)) for i in range(parity_n)]
    spends = [
        _SeedTx(tx_hash(parity_n + k), (state_at(parity_n + k),),
                inputs=(StateRef(tx_hash(i), 0),))
        for k, i in enumerate(range(0, parity_n, 3))]
    mem = NodeVaultService(our_keys)
    with tempfile.TemporaryDirectory() as tmp:
        db = NodeDatabase(os.path.join(tmp, "parity.db"))
        idx = IndexedVaultService(db, our_keys)
        for engine in (mem, idx):
            engine.notify_all(par_txs)
            engine.notify_all(spends)

        def snapshot(engine):
            return sorted(
                ((s.ref.txhash.bytes, s.ref.index,
                  serialize(s.state).bytes)
                 for s in engine.iter_unconsumed()))

        parity_ok = (snapshot(mem) == snapshot(idx)
                     and mem.balances() == idx.balances())
        db.close()

    return {
        "harness": "in-process",
        "sizes": list(sizes),
        "per_size": per_size,
        "vault_query_p99_ms": query_p99[hi],
        "vault_coin_selection_p99_ratio": ratio,
        "sublinear_ok": ratio <= 10.0,
        "boot": {
            "states": boot_n,
            "full_replay_s": round(full_replay_s, 3),
            "incremental_s": round(incremental_s, 4),
            "replayed_on_reopen": replayed,
            "watermark": watermark,
        },
        "vault_boot_speedup": boot_speedup,
        "vault_parity_ok": bool(parity_ok),
    }


def bench_ingest_sweep(rates=(1200.0, 3600.0, 10000.0), n_tx=2000,
                       width=1, workers=3, chaos_rate=1200.0,
                       chaos_n_tx=600, pipeline_rate=2400.0,
                       pipeline_n_tx=600):
    """The vectorized ingest plane's capability section (round 15, ROADMAP
    item 2): ONE builder process columnar-builds + batch-signs + serializes
    the whole corpus (loadgen.IngestBuildFlow -> a CTI1 multi-tx frame),
    then `workers` replay processes drive disjoint slices open-loop at the
    stated offered rates — no per-tx Python rebuild anywhere in the driven
    path, so the offered ladder reaches 10k where the PR 9 generator
    ceiling was ~360 tx/s.

    Per rate the row reports offered vs achieved tx/s, latency
    percentiles, frames-per-tx (the send_many amortization, from worker
    transport deltas), the builder's ingest attribution block
    (tx_built_per_s / sigs_signed_per_s / serialize_ms / client cpu_s) and
    the exactly-once audit. first_bottleneck is the top of the perf
    doctor's evidence-ranked attribution over the member stamps
    (obs/doctor.stamp_attribution; the full ranked list rides under
    "doctor") — at offered rates the client plane can now pace, the
    residual ceiling is SERVER-side and this says where.

    A separate chaos leg re-runs one mid-ladder rate under the lossy plan
    (transport.send drop p=0.05, armed in members + workers): the durable
    outbox's fallback re-poll redelivers, so the audit must stay
    exactly-once — loss costs latency, never transactions.

    A pipeline-delta leg (round 18) runs the SAME raft workload twice —
    serial reference ([raft] pipeline=false) vs pipelined commit plane —
    and stamps committed-tx/s for both plus their ratio as
    pipeline_speedup, which perfdoctor --gate bands (higher-is-better):
    a regression that silently flattens the overlap win fails CI even
    when the simple-notary ladder above still looks healthy."""
    from corda_tpu.obs import doctor as _doctor
    from corda_tpu.tools.loadtest import run_ingest_sweep

    def _rows(sweep):
        return {f"{rate:g}_tx_s": r for rate, r in sweep.items()}

    sweep = run_ingest_sweep(rates=rates, n_tx=n_tx, width=width,
                             workers=workers)
    # Sweeps stamp their own doctor attribution; a monkeypatched/legacy
    # SweepResult without one gets attributed here from its stamps.
    attribution = (getattr(sweep, "doctor", None)
                   or _doctor.stamp_attribution(sweep.node_stamps))
    ok = [r for r in sweep.results.values() if "error" not in r]
    out = {"harness": "multiprocess-driver", "notary": "simple",
           "n_tx": n_tx, "width": width, "workers": workers,
           # The offered ladder in sweep order: the report contract checks
           # this trend is monotonic (the sweep is a ladder, not a bag).
           "offered_rates_tx_s": list(rates),
           "rates": _rows(sweep),
           "peak_offered_tx_s": max(
               (r["offered_tx_s"] for r in ok), default=None),
           "peak_achieved_tx_s": max(
               (r["achieved_tx_s"] for r in ok), default=None),
           "exactly_once_all": (bool(ok) and len(ok) == len(sweep.results)
                                and all(r["exactly_once"] for r in ok)),
           "first_bottleneck": attribution.get("first_bottleneck"),
           "doctor": attribution,
           "node_stamps": sweep.node_stamps}
    try:
        chaos = run_ingest_sweep(rates=(chaos_rate,), n_tx=chaos_n_tx,
                                 width=width, workers=workers,
                                 chaos="lossy")
        crow = chaos.results.get(chaos_rate) or {}
        out["chaos"] = {"plan": "lossy", "rate_tx_s": chaos_rate,
                        "n_tx": chaos_n_tx,
                        "exactly_once": crow.get("exactly_once", False),
                        "row": _rows(chaos)}
    except Exception as e:
        out["chaos"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        legs = {}
        for label, piped in (("serial", False), ("pipelined", True)):
            leg = run_ingest_sweep(
                rates=(pipeline_rate,), n_tx=pipeline_n_tx, width=width,
                workers=workers, notary="raft", pipeline=piped)
            legs[label] = leg.results.get(pipeline_rate) or {}
        s = legs["serial"].get("achieved_tx_s")
        p = legs["pipelined"].get("achieved_tx_s")
        out["pipeline_delta"] = {
            "notary": "raft", "rate_tx_s": pipeline_rate,
            "n_tx": pipeline_n_tx,
            "committed_tx_s_serial": s,
            "committed_tx_s_pipelined": p,
            "pipeline_speedup": (round(p / s, 3) if s and p else None),
            "exactly_once_both": bool(
                legs["serial"].get("exactly_once")
                and legs["pipelined"].get("exactly_once"))}
    except Exception as e:
        out["pipeline_delta"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def bench_shard_scaling(shard_counts=(1, 2, 4), n_tx=240, width=4,
                        verifier="cpu", notary_device="cpu"):
    """Sharded-notary scaling (round 9): committed tx/s and tail latency
    vs the number of StateRef-partitioned raft groups, real OS-process
    nodes throughout (node/services/sharding.py). Two sections:

    * shards — the single-shard-dominant mix (cross_frac=0, every move
      routes straight to its owning group's leader: the fast path whose
      semantics match the unsharded notary). One-member groups keep the
      per-group replication cost constant so the trend isolates the
      partitioning win; the acceptance bar is tx/s monotonically
      non-decreasing 1 -> 2 -> 4.
    * cross_shard_mix — the adversarial mix: half the moves consume
      inputs owned by TWO different groups, forcing the reserve/commit
      two-phase path under contention. The headline here is not
      throughput but the ledger audit: committed_states rows across all
      groups must equal committed + cross_committed (each two-input move
      spends one extra ref) with zero reservation rows leaked —
      exactly_once=True or the section fails its contract."""
    from corda_tpu.tools.loadtest import run_loadtest_multiprocess

    out = {"harness": "multiprocess-driver", "width": width, "n_tx": n_tx,
           "cluster_size_per_group": 1,
           "mix": "single-shard-dominant (cross_frac=0)", "shards": {}}
    for count in shard_counts:
        r = run_loadtest_multiprocess(
            n_tx=n_tx, width=width, clients=2, notary="raft",
            cluster_size=1, verifier=verifier, notary_device=notary_device,
            inflight=32, shards=count)
        out["shards"][str(count)] = {
            "tx_per_sec": r.tx_per_sec, "p50_ms": r.p50_ms,
            "p99_ms": r.p99_ms, "committed": r.tx_committed,
            "rejected": r.tx_rejected,
            "per_group_committed": r.per_group_committed,
            "exactly_once": r.exactly_once}
    r = run_loadtest_multiprocess(
        n_tx=120, width=width, clients=2, notary="raft", cluster_size=1,
        verifier=verifier, notary_device=notary_device, inflight=16,
        shards=2, cross_frac=0.5)
    out["cross_shard_mix"] = {
        "shards": 2, "cross_frac": 0.5,
        "cross_requested": r.cross_requested,
        "cross_committed": r.cross_committed,
        "tx_per_sec": r.tx_per_sec, "p99_ms": r.p99_ms,
        "committed": r.tx_committed, "rejected": r.tx_rejected,
        "ledger_committed": r.ledger_committed,
        "ledger_expected": r.ledger_expected,
        "reserved_leaked": r.reserved_leaked,
        "exactly_once": r.exactly_once}
    return out


def _mesh_sidecar_round(devices, n_sigs=4096, rounds=5,
                        notary_device="cpu", warm_timeout_s=240.0):
    """ONE multichip_scaling config: spawn a sidecar owning a
    `devices`-wide mesh (the real accelerator slice when
    notary_device="accelerator"; a VIRTUAL host mesh via
    --xla_force_host_platform_device_count otherwise), firehose it with
    tiled make_corpus batches through the real wire client
    (node/verify_client.py), parity-check EVERY verdict against the
    corpus truth, and report aggregate sigs/s + per-round latency plus
    the server's own pad/occupancy attribution.

    Warm-up is untimed on purpose: the first dispatch at a bucket pays
    the sharded executable's compile (amortised by the persistent cache
    across runs but not across mesh widths), and the timed rounds must
    measure the steady-state mesh, not a compile. A mesh the host cannot
    build (fewer local devices than asked) leaves the server's gate
    closed — the rounds then measure the oracle-exact host tier and the
    section says so via warm_error/mesh_devices, never a wrong answer."""
    import tempfile
    from pathlib import Path

    from corda_tpu.crypto.provider import VerifyJob
    from corda_tpu.node.verify_client import (SidecarVerifier,
                                              fetch_sidecar_stats)
    from corda_tpu.testing.driver import driver

    pks, msgs, sigs, valid = make_corpus()
    jobs = [VerifyJob(pk, m, s) for pk, m, s in
            zip(tile(pks, n_sigs), tile(msgs, n_sigs), tile(sigs, n_sigs))]
    expected = np.asarray(tile(valid, n_sigs), bool)
    with tempfile.TemporaryDirectory(prefix="bench-mesh-") as td:
        with driver(Path(td)) as d:
            side = d.start_sidecar(
                name=f"mesh{devices}", verifier="jax",
                device=("accelerator" if notary_device == "accelerator"
                        else "cpu"),
                coalesce_us=200, max_sigs=max(n_sigs, 4096),
                devices=devices)
            client = SidecarVerifier(
                side.address, deadline_ms=warm_timeout_s * 1e3,
                device_min_sigs=0, devices=devices)
            # Wait out the boot-warm gate (mesh build happens in the
            # server's warm thread); warm_error set = mesh unbuildable,
            # proceed and measure the host-tier degrade honestly.
            deadline = time.monotonic() + warm_timeout_s
            snap = {}
            while time.monotonic() < deadline:
                try:
                    snap = fetch_sidecar_stats(side.address)
                except Exception:
                    snap = {}
                if snap.get("device_ready") or snap.get("warm_error"):
                    break
                time.sleep(0.25)
            # Untimed warm dispatch: pays the per-bucket mesh compile.
            warm_ok = client.verify_batch(jobs)
            parity_ok = bool(np.array_equal(np.asarray(warm_ok, bool),
                                            expected))
            times = []
            t_all = time.perf_counter()
            for _ in range(rounds):
                t0 = time.perf_counter()
                ok = client.verify_batch(jobs)
                times.append(time.perf_counter() - t0)
                parity_ok = parity_ok and bool(
                    np.array_equal(np.asarray(ok, bool), expected))
            wall = time.perf_counter() - t_all
            try:
                snap = fetch_sidecar_stats(side.address)
            except Exception:
                pass
            times.sort()
            return {
                "devices": devices, "n_sigs": n_sigs, "rounds": rounds,
                "sigs_per_sec": round(rounds * n_sigs / wall, 1),
                "p50_ms": round(times[len(times) // 2] * 1e3, 2),
                "p99_ms": round(times[min(len(times) - 1,
                                          int(len(times) * 0.99))] * 1e3, 2),
                "parity_ok": parity_ok,
                "client_fallbacks": client.fallbacks,
                "mesh_devices": snap.get("mesh_devices"),
                "warm_error": snap.get("warm_error"),
                "verifier": snap.get("verifier"),
                "device_batches": snap.get("device_batches"),
                "host_batches": snap.get("host_batches"),
                "packed_batches": snap.get("packed_batches"),
                "pack_s_total": snap.get("pack_s_total"),
                "pad_fraction": snap.get("pad_fraction"),
                "per_device_occupancy": snap.get("per_device_occupancy"),
                "per_device_batch_sigs_hist":
                    snap.get("per_device_batch_sigs_hist"),
            }


def bench_multichip_scaling(device_counts=(1, 2, 4, 8), n_sigs=4096,
                            rounds=5, notary_device="cpu", flagship=False):
    """Data-parallel verify-plane scaling (round 10): aggregate sigs/s and
    tail latency vs the mesh width the sidecar owns, 1 -> 2 -> 4 -> 8
    devices, every verdict parity-checked against the corpus truth. Two
    harness shapes share the schema:

    * notary_device="accelerator" — the real multi-chip slice: near-linear
      scaling 1 -> 8 is the acceptance bar (>= 6x aggregate at 8), and
      flagship=True adds the production topology (raft-validating cluster,
      every member feeding ONE mesh-owning sidecar).
    * notary_device="cpu" (host-only bench) — a VIRTUAL host mesh
      (xla_force_host_platform_device_count): sigs/s is NOT expected to
      scale (the "devices" share one CPU) but the parity + pad/occupancy
      contract is exercised end to end, so the section proves the mesh
      code path works on any harness.

    sigs_per_sec_by_devices is hoisted flat for the monotonicity guard in
    tests/test_bench_report.py (mirrors shard_scaling's contract)."""
    mesh_kind = ("device" if notary_device == "accelerator"
                 else "virtual-cpu")
    out = {"harness": "multiprocess-driver", "mesh": mesh_kind,
           "n_sigs": n_sigs, "rounds": rounds, "devices": {}}
    trend = {}
    for count in device_counts:
        try:
            r = _mesh_sidecar_round(count, n_sigs=n_sigs, rounds=rounds,
                                    notary_device=notary_device)
            out["devices"][str(count)] = r
            if "sigs_per_sec" in r:
                trend[str(count)] = r["sigs_per_sec"]
        except BenchTimeout:
            raise
        except Exception as e:
            out["devices"][str(count)] = {
                "error": f"{type(e).__name__}: {e}"}
    out["sigs_per_sec_by_devices"] = trend
    lo, hi = str(min(device_counts)), str(max(device_counts))
    if lo in trend and hi in trend and trend[lo]:
        out["scaling_1_to_max"] = round(trend[hi] / trend[lo], 2)
    if flagship:
        try:
            out["flagship_mesh_sidecar"] = bench_raft_cluster(
                n_tx=400, notary="raft-validating", verifier="jax",
                notary_device=notary_device, sidecar=True,
                sidecar_devices=max(device_counts))
        except BenchTimeout:
            raise
        except Exception as e:
            out["flagship_mesh_sidecar"] = {
                "error": f"{type(e).__name__}: {e}"}
    return out


def _federation_round(hosts, n_sigs=16, seconds=3.0, workers=None,
                      coalesce_us=120000, kill_after_s=None):
    """ONE multihost_scaling config: spawn `hosts` sidecar servers as
    simulated hosts (Driver.start_federation), route tiled make_corpus
    batches through the real FederatedVerifier from `workers` concurrent
    feeder threads, parity-check EVERY verdict against the corpus truth,
    and report aggregate sigs/s + per-batch latency plus the router's own
    routing-share/hedge/degrade attribution.

    The scaling mechanism is LATENCY HIDING, not CPU parallelism: each
    host channel serialises one framed round trip, and a single host's
    throughput is bounded by its coalesce window (cycle ~ window +
    verify); K channels overlap K windows, so aggregate sigs/s grows
    ~K-fold until the one real CPU saturates. The sidecars verify on the
    native host tier (verifier="cpu" — GIL-released libcrypto), which is
    what keeps K windows' worth of verify work under one core.

    workers=None scales the feed with capacity (2 per host) so every
    width runs the identical per-host load and the trend isolates the
    width axis. The defaults keep the verify burst (~0.8 ms/sig native)
    well under window/K so the K bursts interleave on one core.

    kill_after_s kills host 0 mid-measure (SIGKILL, no restart): the
    exactly-once audit then requires every submitted batch to answer
    exactly once and parity-clean — via the survivors or the oracle-exact
    local host tier — and the report carries the survivors' post-kill
    routing share."""
    import tempfile
    import threading
    from pathlib import Path

    from corda_tpu.crypto.federation import FederatedVerifier
    from corda_tpu.crypto.provider import VerifyJob
    from corda_tpu.testing.driver import driver

    if workers is None:
        workers = 2 * hosts
    pks, msgs, sigs, valid = make_corpus()
    jobs = [VerifyJob(pk, m, s) for pk, m, s in
            zip(tile(pks, n_sigs), tile(msgs, n_sigs), tile(sigs, n_sigs))]
    expected = np.asarray(tile(valid, n_sigs), bool)
    with tempfile.TemporaryDirectory(prefix="bench-fed-") as td:
        with driver(Path(td)) as d:
            handles = d.start_federation(
                count=hosts, verifier="cpu", coalesce_us=coalesce_us,
                max_sigs=max(n_sigs * workers, 4096))
            fed = FederatedVerifier([h.address for h in handles],
                                    device_min_sigs=0)
            fed.warm()
            agg_lock = threading.Lock()
            agg = {"batches": 0, "sigs": 0, "parity_ok": True}
            times = []
            stop = threading.Event()

            def feeder(offset_s):
                # Staggered start: feeders launched in phase would open
                # every host's coalesce window simultaneously, piling K
                # verify bursts onto the same instant of the shared CPU.
                # The cycle-locked feed preserves the initial phase, so
                # spreading the K first-wave workers coalesce/K apart
                # keeps the verify bursts disjoint for the whole run —
                # and every LATER wave must launch after all K hosts are
                # busy, or least-depth routing would aim it at a host
                # whose window was deliberately not anchored yet and
                # re-synchronise the phases it exists to spread.
                if stop.wait(offset_s):
                    return
                while not stop.is_set():
                    t0 = time.perf_counter()
                    ok = fed.verify_batch(jobs)
                    dt = time.perf_counter() - t0
                    good = bool(np.array_equal(np.asarray(ok, bool),
                                               expected))
                    with agg_lock:
                        agg["batches"] += 1
                        agg["sigs"] += len(jobs)
                        agg["parity_ok"] = agg["parity_ok"] and good
                        times.append(dt)

            threads = [threading.Thread(
                target=feeder,
                args=((i % hosts) * coalesce_us / 1e6 / hosts
                      + (i // hosts) * coalesce_us / 1e6,),
                daemon=True, name=f"fed-feed{i}")
                       for i in range(workers)]
            t_all = time.perf_counter()
            for t in threads:
                t.start()
            kill_info = None
            if kill_after_s is not None and hosts >= 2:
                time.sleep(kill_after_s)
                at_kill = [c.dispatches for c in fed.channels]
                handles[0].kill()
                kill_info = {"killed_host": handles[0].address,
                             "at_kill_dispatches": at_kill}
            time.sleep(max(0.0, seconds - (kill_after_s or 0.0)))
            stop.set()
            for t in threads:
                t.join(timeout=30.0)
            wall = time.perf_counter() - t_all
            out = {
                "hosts": hosts, "n_sigs": n_sigs, "workers": workers,
                "coalesce_us": coalesce_us,
                "batches": agg["batches"],
                "sigs_per_sec": round(agg["sigs"] / wall, 1),
                "parity_ok": agg["parity_ok"],
                "fallbacks": fed.fallbacks,
                "hedges": fed.hedges,
                "host_degraded": fed.host_degraded,
                "federation": fed.federation_stats(),
            }
            if times:
                times.sort()
                out["p50_ms"] = round(times[len(times) // 2] * 1e3, 2)
                out["p99_ms"] = round(
                    times[min(len(times) - 1,
                              int(len(times) * 0.99))] * 1e3, 2)
            if kill_info is not None:
                post = [c.dispatches - k for c, k in
                        zip(fed.channels, kill_info["at_kill_dispatches"])]
                total_post = sum(post)
                out["host_kill"] = {
                    "killed_host": kill_info["killed_host"],
                    # Every submission answered exactly once (each
                    # verify_batch returned one verdict array) and every
                    # verdict matched the corpus truth — across the kill.
                    "exactly_once": agg["parity_ok"],
                    "answered_batches": agg["batches"],
                    "post_kill_dispatches_by_host": post,
                    "survivor_share_post_kill": (
                        round(sum(post[1:]) / total_post, 4)
                        if total_post else None),
                    "host_degraded": fed.host_degraded,
                    "local_fallbacks": fed.fallbacks,
                }
            return out


def bench_multihost_scaling(host_counts=(1, 2, 4), n_sigs=16,
                            seconds=3.0, workers=None, coalesce_us=120000,
                            kill_leg=True):
    """Federated verify-plane scaling (round 19): aggregate cross-host
    sigs/s vs the number of per-host sidecars the federation router
    (crypto/federation.py) feeds, 1 -> 2 -> 4 simulated hosts, every
    verdict parity-checked against the corpus truth. The hosts are
    SIMULATED — sidecar processes on one box (mesh label "virtual-cpu"),
    so the section proves the routing/latency-hiding contract, not
    multi-machine bandwidth: near-linear scaling comes from overlapping
    K coalesce windows (see _federation_round), with the acceptance bar
    >= 1.7x aggregate at 2 hosts and >= 3x at 4.

    kill_leg adds a 2-host run that SIGKILLs one host mid-measure and
    audits the exactly-once + survivor-absorption contract.

    sigs_per_sec_by_hosts is hoisted flat for the monotonicity guard in
    tests/test_bench_report.py (mirrors multichip_scaling's contract)."""
    out = {"harness": "multiprocess-driver", "mesh": "virtual-cpu",
           "simulated_hosts": True, "n_sigs": n_sigs,
           "workers": workers or "2x-hosts",
           "coalesce_us": coalesce_us, "seconds": seconds, "hosts": {}}
    trend = {}
    for count in host_counts:
        try:
            r = _federation_round(count, n_sigs=n_sigs, seconds=seconds,
                                  workers=workers, coalesce_us=coalesce_us)
            out["hosts"][str(count)] = r
            if "sigs_per_sec" in r:
                trend[str(count)] = r["sigs_per_sec"]
        except BenchTimeout:
            raise
        except Exception as e:
            out["hosts"][str(count)] = {"error": f"{type(e).__name__}: {e}"}
    out["sigs_per_sec_by_hosts"] = trend
    lo, hi = str(min(host_counts)), str(max(host_counts))
    if lo in trend and hi in trend and trend[lo]:
        out["scaling_1_to_max"] = round(trend[hi] / trend[lo], 2)
    if kill_leg:
        try:
            out["host_kill"] = _federation_round(
                2, n_sigs=n_sigs, seconds=seconds, workers=workers,
                coalesce_us=coalesce_us,
                kill_after_s=seconds * 0.4)["host_kill"]
        except BenchTimeout:
            raise
        except Exception as e:
            out["host_kill"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def bench_chaos(n_tx=60, cluster_size=3, rate_tx_s=120.0):
    """Chaos section (round 7): measured recovery under deterministic fault
    injection. Two runs over the in-process raft cluster (real TCP +
    sqlite), clients notarising through the deadline-bounded retry flow:

    * leader_kill — the raft LEADER is killed mid-burst and rebuilt from
      disk; recovery is the gap from the kill to the first completion
      after it, and the exactly-once audit (client outcomes AND the
      cluster's committed_states row count) must hold across the change.
    * lossy_open_loop — the builtin "lossy" plan (seeded 5% transport.send
      drop) armed, open-loop paced; p99 shows what redelivery costs.

    Headline keys are hoisted to the section top so the bench contract
    (leader_kill_recovery_s, faults_injected, lossy p99) greps flat."""
    from corda_tpu.tools.loadtest import run_chaos_loadtest

    out = {}
    kill = run_chaos_loadtest(n_tx=n_tx, cluster_size=cluster_size,
                              kill_leader=True, rate_tx_s=rate_tx_s)
    out["leader_kill"] = {
        "exactly_once": kill.exactly_once,
        "tx_committed": kill.tx_committed,
        "tx_rejected": kill.tx_rejected,
        "tx_unresolved": kill.tx_unresolved,
        "cluster_committed": kill.cluster_committed,
        "recovery_s": kill.leader_kill_recovery_s,
        "p99_ms": kill.p99_ms,
        "disruptions": kill.disruptions,
    }
    lossy = run_chaos_loadtest(plan="lossy", n_tx=n_tx,
                               cluster_size=cluster_size,
                               rate_tx_s=rate_tx_s)
    out["lossy_open_loop"] = {
        "exactly_once": lossy.exactly_once,
        "tx_committed": lossy.tx_committed,
        "rate_tx_s": rate_tx_s,
        "p50_ms": lossy.p50_ms,
        "p99_ms": lossy.p99_ms,
    }
    out["leader_kill_recovery_s"] = kill.leader_kill_recovery_s
    out["faults_injected"] = lossy.faults_injected
    out["lossy_open_loop_p99_ms"] = lossy.p99_ms
    return out


def bench_reshard(n_tx=200, rate_tx_s=80.0, shards=2, to_shards=4,
                  cross_frac=0.2):
    """Elastic resharding section (round 13): the group count DOUBLES
    mid-sweep — a live split under open-loop load with the builtin
    "reshard" chaos plan armed (lossy transport + dropped handoff frames
    + stale netmap refreshes) — and then halves back in a clean merge run.
    The claim under test is a p99 blip, not an outage: the split must
    complete with exactly_once=true (every tx committed exactly once,
    ledger rows across the NEW groups totalling exactly the consumed
    refs, zero leaked reservations), client retries bounded (the
    wrong_epoch bounce count), and the latency windows split at the
    plan-publish / cutover marks showing where the tail went.

    Headline keys hoisted flat for the bench contract: exactly_once,
    wrong_epoch_bounces, reshard_window_s, p99_before/during/after_ms."""
    from corda_tpu.tools.loadtest import run_reshard_loadtest

    out = {"harness": "inproc-reshard", "n_tx": n_tx,
           "rate_tx_s": rate_tx_s, "plan": "reshard"}
    split = run_reshard_loadtest(
        plan="reshard", n_tx=n_tx, shards=shards, to_shards=to_shards,
        rate_tx_s=rate_tx_s, cross_frac=cross_frac)
    out["split"] = dict(split.__dict__)
    merge = run_reshard_loadtest(
        plan=None, n_tx=max(40, n_tx // 2), shards=to_shards,
        to_shards=shards, rate_tx_s=rate_tx_s)
    out["merge"] = dict(merge.__dict__)
    out["exactly_once"] = bool(split.exactly_once and merge.exactly_once)
    out["wrong_epoch_bounces"] = split.wrong_epoch_bounces
    out["handoff_frames"] = split.handoff_frames
    out["faults_injected"] = split.faults_injected
    out["reshard_window_s"] = (
        round(split.reshard_completed_s - split.reshard_started_s, 3)
        if (split.reshard_completed_s is not None
            and split.reshard_started_s is not None) else None)
    out["p99_before_ms"] = split.p99_before_ms
    out["p99_during_ms"] = split.p99_during_ms
    out["p99_after_ms"] = split.p99_after_ms
    return out


def bench_durability(n_tx=60, cluster_size=3, rate_tx_s=120.0,
                     micro_rows=2000):
    """Durability section (round 14): storage-corruption detection and
    self-healing repair, measured. Two sub-runs, error-isolated so a
    failure in one still reports the other:

    * bitrot_chaos — the builtin "bitrot" plan (seeded read-path bit-flips
      on the raft log + injected disk-full write failures) armed over the
      in-process 3-member cluster. The claim: corruption is DETECTED
      (integrity_errors > 0), healed through consensus (truncate +
      re-replicate), and the exactly-once ledger audit still holds; the
      post-run fsck gate proves the stored bytes stayed clean.
    * detect_repair_micro — a cold store with `micro_rows` framed raft
      rows, one corrupted on disk; measures fsck detection latency over
      the whole store (detect_ms) and the truncate-style repair
      (repair_s), then verifies the repaired store scans clean.

    Headline keys hoisted flat for the bench contract: exactly_once,
    integrity_errors, detect_ms, repair_s, fsck_clean."""
    out = {"plan": "bitrot", "n_tx": n_tx}
    try:
        from corda_tpu.tools.loadtest import run_chaos_loadtest

        chaos = run_chaos_loadtest(plan="bitrot", n_tx=n_tx,
                                   cluster_size=cluster_size,
                                   rate_tx_s=rate_tx_s)
        out["bitrot_chaos"] = {
            "exactly_once": chaos.exactly_once,
            "tx_committed": chaos.tx_committed,
            "integrity_errors": chaos.integrity_errors,
            "fsck_clean": chaos.fsck_clean,
            "faults_injected": chaos.faults_injected,
            "p99_ms": chaos.p99_ms,
        }
        out["exactly_once"] = chaos.exactly_once
        out["integrity_errors"] = chaos.integrity_errors
        out["fsck_clean"] = chaos.fsck_clean
    except BenchTimeout:
        raise
    except Exception as e:
        out["bitrot_chaos"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        import sqlite3
        import tempfile
        from pathlib import Path

        from corda_tpu.node.services import integrity as _integrity
        from corda_tpu.node.services.persistence import NodeDatabase
        from corda_tpu.tools.fsck import fsck_db

        tmp = Path(tempfile.mkdtemp(prefix="corda-tpu-durab-"))
        db = NodeDatabase(tmp / "node.db")
        with db.lock:
            db.conn.executescript(
                "CREATE TABLE IF NOT EXISTS raft_log ("
                "idx INTEGER PRIMARY KEY, term INTEGER, blob BLOB, "
                "crc INTEGER)")
            rows = [(i, 1, b"entry-%08d" % i) for i in range(1, micro_rows)]
            db.conn.executemany(
                "INSERT INTO raft_log (idx, term, blob, crc) "
                "VALUES (?, ?, ?, ?)",
                [(i, t, b, _integrity.log_crc(i, t, b))
                 for i, t, b in rows])
            db.set_setting("raft_last_applied", str(micro_rows // 2))
            db.commit()
        db.close()
        # One bit of on-disk damage past the applied prefix.
        conn = sqlite3.connect(str(tmp / "node.db"))
        victim = micro_rows // 2 + 10
        conn.execute("UPDATE raft_log SET blob = ? WHERE idx = ?",
                     (b"damaged!", victim))
        conn.commit()
        conn.close()
        t0 = time.monotonic()
        detect = fsck_db(tmp / "node.db")
        detect_ms = round(1e3 * (time.monotonic() - t0), 3)
        t0 = time.monotonic()
        fsck_db(tmp / "node.db", repair=True)
        repair_s = round(time.monotonic() - t0, 6)
        verify = fsck_db(tmp / "node.db")
        out["detect_repair_micro"] = {
            "rows": micro_rows,
            "corrupt_found": detect["corrupt"],
            "detect_ms": detect_ms,
            "repair_s": repair_s,
            "clean_after_repair": verify["clean"],
        }
        out["detect_ms"] = detect_ms
        out["repair_s"] = repair_s
    except BenchTimeout:
        raise
    except Exception as e:
        out["detect_repair_micro"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def bench_partition_chaos(n_tx=36, cluster_size=3, cut_hold_s=4.0):
    """Partition section (round 20): deterministic split-brain over the
    in-process TCP cluster, audited by the history checker
    (testing/history.py). Three error-isolated legs:

    * split_leader — leader isolated, prevote ON: check-quorum must cede
      the quorumless leadership, the majority keeps committing, and the
      heal-to-first-commit recovery is measured (recovery_s).
    * split_follower_prevote / split_follower_noprevote — a follower
      isolated, prevote ON vs OFF: the A/B for term inflation. With
      pre-vote the cut-off member canvasses without persisting a term
      (bounded inflation); without it every futile timeout inflates the
      term and the rejoiner disrupts the healthy side at heal.

    Headline keys hoisted flat for the bench contract: recovery_s,
    max_term_inflation (prevote on) vs max_term_inflation_noprevote,
    history_linearizable (AND over every leg), minority_commits,
    lost_acks, partition_cuts, checkquorum_stepdowns."""
    out = {"plan": "split-hold", "n_tx": n_tx}
    legs = (
        ("split_leader", "leader", True),
        ("split_follower_prevote", "follower", True),
        ("split_follower_noprevote", "follower", False),
    )
    linearizable = True
    for key, isolate, prevote in legs:
        try:
            from corda_tpu.tools.loadtest import run_partition_loadtest

            r = run_partition_loadtest(
                n_tx=n_tx, cluster_size=cluster_size, prevote=prevote,
                isolate=isolate, cut_hold_s=cut_hold_s)
            out[key] = {
                "prevote": r.prevote,
                "isolate": r.isolate,
                "tx_committed": r.tx_committed,
                "tx_unresolved": r.tx_unresolved,
                "recovery_s": r.recovery_s,
                "max_term_inflation": r.max_term_inflation,
                "minority_commits_during_cut": r.minority_commits_during_cut,
                "checkquorum_stepdowns": r.checkquorum_stepdowns,
                "prevotes": r.prevotes,
                "prevote_rejections": r.prevote_rejections,
                "partition_cuts": r.partition_cuts,
                "partition_drops": r.partition_drops,
                "history_linearizable": r.history_linearizable,
                "lost_acks": r.lost_acks,
                "double_spends": r.double_spends,
            }
            linearizable = linearizable and r.history_linearizable
        except BenchTimeout:
            raise
        except Exception as e:
            out[key] = {"error": f"{type(e).__name__}: {e}"}
            linearizable = False
    lead = out.get("split_leader", {})
    on = out.get("split_follower_prevote", {})
    off = out.get("split_follower_noprevote", {})
    out["recovery_s"] = lead.get("recovery_s")
    out["checkquorum_stepdowns"] = lead.get("checkquorum_stepdowns")
    out["max_term_inflation"] = on.get("max_term_inflation")
    out["max_term_inflation_noprevote"] = off.get("max_term_inflation")
    out["history_linearizable"] = linearizable
    out["minority_commits"] = sum(
        leg.get("minority_commits_during_cut", 0) for leg in
        (lead, on, off))
    out["lost_acks"] = sum(
        leg.get("lost_acks", 0) for leg in (lead, on, off))
    out["partition_cuts"] = sum(
        leg.get("partition_cuts", 0) for leg in (lead, on, off))
    return out


class BenchTimeout(Exception):
    pass


def _install_watchdog(seconds: int, report: dict):
    """A wedged accelerator tunnel must not turn the whole bench into a
    silent hang (observed 2026-07-30: the axon relay stopped answering and
    a device-init call blocked indefinitely). Two layers:

    * SIGALRM raises BenchTimeout in the main thread — the graceful path,
      when the stuck call is interruptible.
    * A HARD backstop thread: the observed wedge blocks the main thread
      inside a C sigsuspend loop that never returns to the interpreter, so
      the Python-level SIGALRM handler can never run. At deadline+60s the
      thread prints the partial report itself and os._exit(1)s — one JSON
      line beats an infinite hang, always.
    """
    import os
    import signal
    import threading

    def on_alarm(signum, frame):
        raise BenchTimeout(f"bench watchdog fired after {seconds}s")

    prev_handler = None
    armed = False
    try:
        prev_handler = signal.signal(signal.SIGALRM, on_alarm)
        signal.alarm(seconds)
        armed = True
    except (ValueError, OSError):
        pass  # non-main thread / platform without SIGALRM

    def cancel():
        """Disarm the soft watchdog once the run finished: an embedding
        process that lives past the deadline must not take a stale
        BenchTimeout in unrelated code. (The backstop thread self-gates on
        _printed / generation.)"""
        if not armed:
            return
        try:
            signal.alarm(0)
            # prev_handler is None when the prior handler was installed
            # from C — on_alarm must still come OFF; SIG_DFL is the least
            # surprising stand-in we can restore.
            signal.signal(signal.SIGALRM,
                          prev_handler if prev_handler is not None
                          else signal.SIG_DFL)
        except (ValueError, OSError):
            pass

    generation = _run_generation

    def backstop():
        time.sleep(seconds + 60)
        if _printed or generation != _run_generation:
            # Run completed (or a NEWER run owns the process): a stale
            # backstop must never kill a healthy host process post-hoc.
            return
        hard = (f"bench hard-watchdog: unresponsive after {seconds + 60}s "
                f"(uninterruptible hang)")
        # Snapshot under the print lock; a concurrently-mutating report can
        # make dict iteration raise, so retry once after a beat.
        for attempt in range(2):
            try:
                snap = dict(report)
                break
            except RuntimeError:
                time.sleep(1.0)
        else:  # pragma: no cover - pathological mutation storm
            snap = {"metric": "verified_sigs_per_sec", "value": 0.0,
                    "unit": "sigs/sec", "vs_baseline": 0.0}
        prior = snap.get("error")
        snap["error"] = f"{prior}; {hard}" if prior else hard
        snap["error_phase"] = snap.get("phase")
        # The wedged phase's wall time is the whole point of the phase
        # clock in this scenario — flush it, and keep the internal marker
        # out of the driver-contract JSON.
        _flush_inflight_phase(snap)
        snap.pop("phase", None)
        _print_report_once(snap)
        os._exit(1)

    threading.Thread(target=backstop, daemon=True,
                     name="bench-hard-watchdog").start()
    return cancel


import threading as _threading

_print_lock = _threading.Lock()
_printed = False
_run_generation = 0  # incremented per main(); stale backstops check it


def _print_report_once(report: dict) -> None:
    """Exactly ONE JSON line ever reaches stdout (the driver's contract),
    whether the graceful path or the hard backstop gets there first."""
    global _printed
    with _print_lock:
        if _printed:
            return
        _printed = True
        print(json.dumps(report), flush=True)


def _device_init_with_timeout(timeout_s: float = 300.0) -> str | None:
    """jax.devices() in a worker thread with a join timeout: the observed
    tunnel wedge blocks uninterruptibly, so the main thread must be able
    to WALK AWAY (the stuck daemon thread is leaked deliberately) and run
    the host-only phases instead."""
    import queue
    import threading

    result: queue.Queue = queue.Queue()

    def init():
        try:
            import jax

            result.put(("ok", str(jax.devices()[0])))
        except Exception as e:  # pragma: no cover - backend specific
            result.put(("err", f"{type(e).__name__}: {e}"))

    t = threading.Thread(target=init, daemon=True, name="device-init")
    t.start()
    t.join(timeout=timeout_s)
    try:
        kind, value = result.get_nowait()
    except queue.Empty:
        return None  # still hanging
    return value if kind == "ok" else None


class _PhaseClock:
    """Per-phase wall clocks riding the report: the watchdog budget
    (default 2700 s) is shared by a dozen phases, and an overrun must be
    attributable from the JSON alone — including the phase that was IN
    FLIGHT when the watchdog fired (main() flushes it via report
    ["_phase_started"]) and degraded host-only runs."""

    def __init__(self, report: dict, first: str = "device_init"):
        self.seconds = report.setdefault("phase_seconds", {})
        self.report = report
        self._t = time.monotonic()
        self._name = first
        report["phase"] = first
        report["_phase_started"] = self._t

    def set(self, name: str) -> None:
        now = time.monotonic()
        self.seconds[self._name] = round(
            self.seconds.get(self._name, 0.0) + (now - self._t), 1)
        self._t, self._name = now, name
        self.report["phase"] = name
        self.report["_phase_started"] = now


def _flush_inflight_phase(report: dict) -> None:
    """Attribute the phase that was running when the run aborted."""
    started = report.pop("_phase_started", None)
    phase = report.get("phase")
    if started is not None and phase is not None:
        seconds = report.setdefault("phase_seconds", {})
        seconds[phase] = round(
            seconds.get(phase, 0.0) + (time.monotonic() - started), 1)


def main():
    import os

    global _printed, _run_generation
    _printed = False  # one line per RUN (tests invoke main() repeatedly)
    _run_generation += 1
    # The report is built PROGRESSIVELY so the watchdog can still print one
    # honest JSON line carrying everything that finished before a wedge.
    report = {
        "metric": "verified_sigs_per_sec",
        "value": 0.0,
        "unit": "sigs/sec",
        "vs_baseline": 0.0,
    }
    # Invariant-analyzer stamp: live finding count over the shipped tree
    # (0 == every machine-checked contract holds for the code this run
    # measured). Advisory in the report — a broken analyzer must never
    # cost a bench line, so any failure stamps -1 instead of raising.
    try:
        from corda_tpu.analysis import analyze_paths

        report["analysis_findings"] = len(analyze_paths(
            [os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "corda_tpu")]).findings)
    except Exception:  # noqa: BLE001 - the one-line contract wins
        report["analysis_findings"] = -1
    cancel_watchdog = _install_watchdog(
        int(os.environ.get("CORDA_TPU_BENCH_TIMEOUT", "2700")), report) \
        or (lambda: None)  # tests stub the installer out
    try:
        try:
            _run_phases(report)
        except BenchTimeout as e:
            # Append rather than overwrite: degraded mode may already carry
            # the root-cause attribution (accelerator unreachable).
            prior = report.get("error")
            report["error"] = f"{prior}; {e}" if prior else str(e)
            report["error_phase"] = report.get("phase")
            _flush_inflight_phase(report)
        except Exception as e:  # noqa: BLE001 - the one-line contract
            # ANY crash must still print the one honest JSON line (the
            # driver records rc + stdout; a raw traceback with no line
            # loses the whole run). Observed trigger: the tunnel flapping
            # mid-phase surfaces as jax.errors.JaxRuntimeError UNAVAILABLE.
            import traceback

            traceback.print_exc()
            prior = report.get("error")
            crash = f"crash in {report.get('phase')}: {type(e).__name__}: {e}"
            report["error"] = f"{prior}; {crash}" if prior else crash
            report["error_phase"] = report.get("phase")
            _flush_inflight_phase(report)
        report.pop("phase", None)
        report.pop("_phase_started", None)
        _print_report_once(report)
    finally:
        cancel_watchdog()


def _run_host_only_phases(report: dict,
                          clock: "_PhaseClock | None" = None) -> None:
    """Degraded mode: the accelerator is unreachable, but the framework
    configs are host-side — measure everything that can be measured
    honestly (CPU verifier, host hashing) instead of producing nothing."""
    from corda_tpu.crypto.provider import CpuVerifier

    clock = clock or _PhaseClock(report)
    set_phase = clock.set
    # Keep the real device id when init succeeded and a later fault
    # degraded the run — "which device answered and then faulted" is the
    # attribution; only an init failure leaves it genuinely unknown.
    report.setdefault("device", "unavailable")
    if not report.get("error"):
        # Default attribution (init timeout). A caller that degraded for
        # a different reason (e.g. a device fault during warm-up) already
        # recorded the real one — never overwrite it.
        report["error"] = (
            "accelerator unreachable (device init timed out); "
            "kernel/stream phases skipped, framework configs "
            "measured on the host crypto path")
    set_phase("notary_roundtrip")
    try:
        report["notary_roundtrip"] = bench_notary_roundtrip(
            verifier=CpuVerifier())
    except BenchTimeout:
        raise
    except Exception as e:
        report["notary_roundtrip_error"] = f"{type(e).__name__}: {e}"
    configs = report["baseline_configs"] = {}
    for name, fn in (
            ("raft_notary_3node", bench_raft_cluster),
            # The validating flagship is sidecar-fed even host-only:
            # measured at parity without a device (41.0 vs 40.3 tx/s,
            # p99 3.52 vs 3.55 s), and it keeps the host-only report on
            # the same code path the device flagship measures. Round 13
            # arms the adaptive coalesce window — the flagship result IS
            # the armed run, with the static A/B under
            # adaptive_coalesce_ab.
            ("raft_validating_3node", bench_validating_flagship),
            ("open_loop_latency", bench_open_loop_latency),
            ("raft_open_loop_latency", lambda: bench_raft_open_loop(
                sidecar=True)),
            # The SLO verdict is a host-path claim (lane scheduling +
            # admission, not kernels) — the host-only run measures the
            # identical section the device path does.
            ("slo_sweep", bench_slo_sweep),
            # The ingest plane's capability ladder is a host-path claim
            # (client build/sign + transport amortization, notary on host
            # crypto) — the host-only run measures the identical section.
            ("ingest_sweep", bench_ingest_sweep),
            # Always-on telemetry: round_breakdown coverage + Prometheus
            # round-trip over an in-process loadtest — pure host path.
            ("telemetry", bench_telemetry),
            ("shard_scaling", bench_shard_scaling),
            # Group count doubles mid-sweep under the lossy reshard plan;
            # the contract is exactly_once + a bounded p99 blip.
            ("reshard", bench_reshard),
            # Virtual host mesh: parity + pad/occupancy contract without
            # real chips (sigs/s not expected to scale — see docstring).
            ("multichip_scaling", lambda: bench_multichip_scaling(
                n_sigs=1024, rounds=3)),
            # Federated verify plane: simulated hosts are sidecar
            # processes on this box, so the host-only run measures the
            # REAL scaling claim (latency-hiding across coalesce
            # windows), just with smaller sweep parameters.
            ("multihost_scaling", lambda: bench_multihost_scaling(
                seconds=2.5)),
            ("resolve_ids", lambda: bench_resolve_ids(host_only=True)),
            ("trader_dvp", lambda: bench_trades(verifier=CpuVerifier())),
            ("composite_3of3", lambda: bench_multisig(
                verifier=CpuVerifier())),
            ("partial_merkle", bench_partial_merkle),
            ("flow_churn", bench_flow_churn),
            # The autotune plane's closed loop: a real doctor verdict
            # over a baseline ingest run steers a gated knob sweep —
            # pure host path (multiprocess harness, host crypto), so
            # the host-only run measures the identical section.
            ("autotune", bench_autotune),
            # Indexed vault plane: selection/query/boot scaling on
            # sqlite stores — host path by construction (no kernels in
            # the claim), trimmed sizes keep the host run bounded.
            ("vault_scaling", lambda: bench_vault_scaling(
                sizes=(10_000, 100_000), queries=32, selections=32))):
        set_phase(name)
        try:
            configs[name] = fn()
        except BenchTimeout:
            raise
        except Exception as e:
            configs[name] = {"error": f"{type(e).__name__}: {e}"}
    set_phase("chaos")
    try:
        report["chaos"] = bench_chaos()
    except BenchTimeout:
        raise
    except Exception as e:
        report["chaos"] = {"error": f"{type(e).__name__}: {e}"}
    set_phase("durability")
    try:
        report["durability"] = bench_durability()
    except BenchTimeout:
        raise
    except Exception as e:
        report["durability"] = {"error": f"{type(e).__name__}: {e}"}
    set_phase("partition_chaos")
    try:
        report["partition_chaos"] = bench_partition_chaos()
    except BenchTimeout:
        raise
    except Exception as e:
        report["partition_chaos"] = {"error": f"{type(e).__name__}: {e}"}
    set_phase("cpu_oracle")
    pks, msgs, sigs, _ = make_corpus()
    report["cpu_oracle_sigs_per_sec"] = round(
        bench_cpu_oracle(pks, msgs, sigs), 1)
    # The doctor diagnoses the finished report — last, so its roofline
    # sees the cpu_oracle ceiling this degraded path just measured.
    set_phase("doctor")
    try:
        report["doctor"] = bench_doctor(report)
    except BenchTimeout:
        raise
    except Exception as e:
        report["doctor"] = {"error": f"{type(e).__name__}: {e}"}
    set_phase("done")


def _run_phases(report: dict) -> None:
    import jax

    # Persistent compilation cache: the kernel zoo (per-bucket Ed25519 +
    # SHA-512 graphs) compiles once per machine instead of once per run —
    # the shared helper also makes lowering location-free so cache keys
    # survive source edits (see corda_tpu/ops/__init__.py).
    from corda_tpu.ops import enable_persistent_compile_cache

    enable_persistent_compile_cache()

    # Device init runs in a worker thread with a join timeout — the ONE
    # liveness gate: the observed tunnel wedge blocks uninterruptibly (and
    # can flap, so a prior successful probe proves nothing). On timeout the
    # stuck thread is deliberately leaked and the host-side configs still
    # get measured.
    clock = _PhaseClock(report)
    set_phase = clock.set
    # Bounded backoff ACROSS a flap: the relay has been observed to answer
    # a probe and then wedge the very next init, so one failed leash does
    # not prove the tunnel is down for the whole run. Attempts × leash stay
    # well under the run watchdog (default 2700 s).
    import os as _os
    init_attempts = max(1, int(_os.environ.get(
        "CORDA_TPU_DEVICE_INIT_RETRIES", "2")))
    device = None
    for attempt in range(init_attempts):
        device = _device_init_with_timeout(300.0 if attempt == 0 else 150.0)
        if device is not None:
            break
        if attempt + 1 < init_attempts:
            report["device_init_retries"] = attempt + 1
            time.sleep(30.0)
    if device is None:
        _run_host_only_phases(report, clock)
        return
    report["device"] = device
    pks, msgs, sigs, valid = make_corpus()

    from corda_tpu.ops import ed25519_jax

    # Compile every backend at every bucket BEFORE anything is timed (see
    # warm_buckets docstring — this is the round-3 postmortem fix).
    set_phase("warm")
    try:
        _warm_verify_kernel()
        warm_buckets(pks, msgs, sigs)
    except BenchTimeout:
        raise
    except Exception as e:
        # A device that answered the init probe and then faulted during
        # warm (observed: tunnel flap -> JaxRuntimeError UNAVAILABLE) is
        # not trustworthy for ANY device phase — degrade to the host-only
        # sweep instead of failing slowly phase by phase.
        report["device_error"] = f"{type(e).__name__}: {e}"
        prior = report.get("error")
        msg = ("accelerator faulted during warm-up; device phases "
               "skipped, framework configs measured on the host path")
        report["error"] = f"{prior}; {msg}" if prior else msg
        _run_host_only_phases(report, clock)
        return

    # Roundtrip FIRST: it uses small (1024-lane) buckets, and running it
    # after the 64k-bucket phases was measured to suffer a multi-second
    # device-allocator stall that has nothing to do with the protocol.
    set_phase("notary_roundtrip")
    try:
        report["notary_roundtrip"] = bench_notary_roundtrip()
        report["notary_roundtrip_error"] = None
    except BenchTimeout:
        raise  # the one-shot alarm must abort the RUN, not become a phase error
    except Exception as e:  # keep the headline number even if e2e tier breaks
        report["notary_roundtrip"] = None
        report["notary_roundtrip_error"] = f"{type(e).__name__}: {e}"

    # HEADLINE phases (kernel buckets + stream) run BEFORE the multiprocess
    # framework configs: those spawn clusters, wait out device warm-ups and
    # have the least predictable wall time — if the run watchdog fires, it
    # must take the tail configs, never the north-star number.
    set_phase("kernel_buckets")
    kernel, e2e, devhash = {}, {}, {}
    backends = {"kernel": {}, "e2e": {}, "e2e_devhash": {}}
    try:
        kernel, e2e, devhash, backends = bench_kernel(pks, msgs, sigs, valid)
    except BenchTimeout:
        raise
    except Exception as e:
        report["kernel_error"] = f"{type(e).__name__}: {e}"
    report["kernel_sigs_per_sec"] = {
        str(k): round(v, 1) for k, v in kernel.items()}
    report["e2e_sigs_per_sec"] = {str(k): round(v, 1) for k, v in e2e.items()}
    report["e2e_devhash_sigs_per_sec"] = {
        str(k): round(v, 1) for k, v in devhash.items()}

    # Best-of with every pass reported: the axon tunnel's transfer
    # bandwidth varies >2x between runs (see bench_stream doc) and the
    # sustained capability is what matters; the spread stays visible.
    set_phase("stream")
    stream = 0.0
    try:
        stream, passes, stream_backend = bench_stream(
            pks, msgs, sigs, valid, repeats=4)
        backends["stream"] = stream_backend
        report["e2e_stream_sigs_per_sec"] = round(stream, 1)
        report["e2e_stream_passes"] = passes
    except BenchTimeout:
        raise
    except Exception as e:
        report["stream_error"] = f"{type(e).__name__}: {e}"
    set_phase("sha256")
    try:
        report["sha256_64B_hashes_per_sec"] = round(bench_sha256(), 1)
    except BenchTimeout:
        raise
    except Exception as e:
        report["sha256_error"] = f"{type(e).__name__}: {e}"
    set_phase("cpu_oracle")
    try:
        report["cpu_oracle_sigs_per_sec"] = round(
            bench_cpu_oracle(pks, msgs, sigs), 1)
    except BenchTimeout:
        raise
    except Exception as e:
        report["cpu_oracle_error"] = f"{type(e).__name__}: {e}"

    best = {**e2e, **{k: max(e2e[k], devhash[k]) for k in devhash}}
    best_bucket = max(best, key=lambda b: best[b], default=None)
    if best_bucket is None or stream >= best.get(best_bucket, 0.0):
        headline, headline_backend = stream, backends.get("stream")
    else:
        headline = best[best_bucket]
        which = ("e2e" if e2e[best_bucket] >= devhash.get(best_bucket, 0)
                 else "e2e_devhash")
        headline_backend = backends[which][best_bucket]
    pallas_error = ed25519_jax.last_pallas_error()
    if pallas_error:  # full stack to stderr; the JSON line stays one line
        import sys

        print(pallas_error, file=sys.stderr)
    report.update({
        "value": round(headline, 1),
        "vs_baseline": round(headline / BASELINE_SIGS_PER_SEC, 3),
        "backend": headline_backend,
        "backend_by_phase": {
            phase: ({str(k): v for k, v in b.items()}
                    if isinstance(b, dict) else b)
            for phase, b in backends.items()},
        "pallas_error": (pallas_error.strip().splitlines()[-1]
                         if pallas_error else None),
        "pallas_failures_total":
            ed25519_jax._PALLAS_STATE["failures_total"],
        "best_bucket": best_bucket,
    })

    # Per-BASELINE.json-config measurements, AFTER the headline is safe
    # (each is bounded, but cluster spawn + device warm-waits make the
    # aggregate the least predictable stretch of the run; config 3 — the
    # 100k synthetic firehose — IS the stream measurement above).
    configs = report["baseline_configs"] = {}
    # The flagship device phases run with the verification sidecar: ONE
    # device-owning server all members feed, coalescing micro-batches
    # across processes (the r05 device_batches=0 fix — crypto/sidecar.py).
    for name, fn in (("raft_notary_3node", bench_raft_cluster),
                     # Armed adaptive-coalesce flagship (static A/B rides
                     # under adaptive_coalesce_ab — round 13).
                     ("raft_validating_3node",
                      lambda: bench_validating_flagship(
                          verifier="jax", notary_device="accelerator")),
                     ("open_loop_latency", bench_open_loop_latency),
                     ("raft_open_loop_latency", lambda: bench_raft_open_loop(
                         verifier="jax", notary_device="accelerator",
                         sidecar=True)),
                     # Sidecar-fed on the device path so the deadline
                     # scheduler's early-flush is in the measured loop;
                     # the sweep itself stays on host crypto (the SLO
                     # claim is about scheduling, not kernels).
                     ("slo_sweep", lambda: bench_slo_sweep(sidecar=True)),
                     # Same host crypto path as the host-only run: the
                     # ingest sweep measures the CLIENT plane (and names
                     # the first server-side stage it saturates) — the
                     # device never sits in the driven path here.
                     ("ingest_sweep", bench_ingest_sweep),
                     # Telemetry plane: round profiler coverage + the
                     # Prometheus render/parse contract, host path on
                     # both runs (the claim is attribution, not kernels).
                     ("telemetry", bench_telemetry),
                     ("shard_scaling", bench_shard_scaling),
                     # Group count doubles mid-sweep under the lossy
                     # reshard plan; exactly_once + a bounded p99 blip.
                     ("reshard", bench_reshard),
                     ("multichip_scaling", lambda: bench_multichip_scaling(
                         notary_device="accelerator", flagship=True)),
                     # Federated verify plane: the simulated hosts stay
                     # on host crypto even on the device run (the claim
                     # is cross-host ROUTING; the chip belongs to the
                     # multichip section) — longer sweep than host-only.
                     ("multihost_scaling", bench_multihost_scaling),
                     ("resolve_ids", bench_resolve_ids),
                     ("trader_dvp", bench_trades),
                     ("composite_3of3", bench_multisig),
                     ("partial_merkle", bench_partial_merkle),
                     ("flow_churn", bench_flow_churn),
                     # Autotune closed loop: verdict -> gated knob sweep
                     # -> committed overlay. Host-path harness on both
                     # runs (the claim is the LOOP, not kernels).
                     ("autotune", bench_autotune),
                     # Indexed vault plane at full spread: the 1M-state
                     # store proves the 100x-size/10x-p99 sublinearity
                     # claim and the 100k watermark boot speedup.
                     ("vault_scaling", bench_vault_scaling)):
        set_phase(name)
        try:
            configs[name] = fn()
        except BenchTimeout:
            raise
        except Exception as e:
            configs[name] = {"error": f"{type(e).__name__}: {e}"}
    set_phase("chaos")
    try:
        report["chaos"] = bench_chaos()
    except BenchTimeout:
        raise
    except Exception as e:
        report["chaos"] = {"error": f"{type(e).__name__}: {e}"}
    set_phase("durability")
    try:
        report["durability"] = bench_durability()
    except BenchTimeout:
        raise
    except Exception as e:
        report["durability"] = {"error": f"{type(e).__name__}: {e}"}
    set_phase("partition_chaos")
    try:
        report["partition_chaos"] = bench_partition_chaos()
    except BenchTimeout:
        raise
    except Exception as e:
        report["partition_chaos"] = {"error": f"{type(e).__name__}: {e}"}
    # The doctor diagnoses the finished report — last, so its roofline
    # sees every section (kernel ceiling, flagship, chaos) this run
    # produced.
    set_phase("doctor")
    try:
        report["doctor"] = bench_doctor(report)
    except BenchTimeout:
        raise
    except Exception as e:
        report["doctor"] = {"error": f"{type(e).__name__}: {e}"}
    set_phase("done")


if __name__ == "__main__":
    main()
