"""corda_tpu — a TPU-native distributed-ledger framework.

A from-scratch rebuild of the capabilities of Corda (reference:
MarioAriasC/corda @ 0.7-SNAPSHOT): a P2P network of nodes, a UTXO ledger with
contract verification, a resumable multi-party flow framework with
checkpoint/recovery, durable deduplicated messaging, and notary services for
transaction-uniqueness consensus.

Architecture: the *control plane* (nodes, flows, notary protocol, messaging)
is idiomatic host Python; the *data plane* — batched Ed25519 signature
verification and SHA-256 Merkle hashing on the notary hot path — runs as
vmap'd JAX/XLA kernels on TPU (corda_tpu.ops), sharded across chips with
jax.sharding (corda_tpu.parallel), behind a pluggable crypto-provider seam
with a bit-identical pure-Python CPU path as the conformance oracle.

Package map (layers per SURVEY.md §1):
  crypto/    L0 host crypto: hashes, keys, composite keys, Merkle proofs, oracle
  ops/       L0 TPU kernels: fe25519 limb arithmetic, Ed25519 verify, SHA-256
  models/    L1 ledger data model: states, contracts, transactions
  flows/     L2/L3 flow framework + library flows (notary, resolve, finality)
  node/      L4/L5 services, state-machine manager, messaging, notary services
  parallel/  device-mesh sharding of the verification data plane
  utils/     canonical serialization, bytes, progress tracking
  testing/   MockNetwork-style deterministic test infrastructure
"""

__version__ = "0.1.0"

from .serialization import wire as _wire  # noqa: E402,F401  (whitelist core types)
