"""Static invariant analyzer for the corda_tpu tree.

``python -m corda_tpu.analysis corda_tpu/`` runs every rule over the tree
and exits 0 iff no live (unsuppressed, unbaselined) findings remain. The
rules machine-check the framework's load-bearing prose invariants —
determinism of the replicated apply paths, no silent exception swallowing
on verify/notarise paths, one cached jit executable per (graph, mesh), no
blocking I/O under general-purpose locks, an acyclic lock-acquisition
graph, and span names drawn from the obs stage registry.

Stdlib-only (``ast`` + ``json`` + ``re``): importable and runnable with no
jax present, so tier-1 and bare CI shells can gate on it.
"""

from __future__ import annotations

from .engine import (
    DEFAULT_BASELINE,
    FileContext,
    Finding,
    Report,
    Rule,
    analyze_paths,
    analyze_source,
    baseline_entries_from_findings,
    load_baseline,
)
from .rules import ALL_RULES

__all__ = [
    "ALL_RULES",
    "DEFAULT_BASELINE",
    "FileContext",
    "Finding",
    "Report",
    "Rule",
    "analyze_paths",
    "analyze_source",
    "baseline_entries_from_findings",
    "load_baseline",
]
