"""CLI for the invariant analyzer.

    python -m corda_tpu.analysis corda_tpu/            # human output
    python -m corda_tpu.analysis --json corda_tpu/     # machine output
    python -m corda_tpu.analysis --list-rules          # rule inventory

Exit status: 0 iff the scan is clean (no live findings). ``--json`` prints
one JSON object (Report.as_dict()) so bench.py and CI can stamp
``analysis_findings`` without parsing human text.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .engine import (DEFAULT_BASELINE, analyze_paths,
                     baseline_entries_from_findings)
from .rules import ALL_RULES


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m corda_tpu.analysis",
        description="AST invariant analyzer for the corda_tpu tree")
    ap.add_argument("paths", nargs="*", default=["corda_tpu"],
                    help="files or directories to scan (default: corda_tpu)")
    ap.add_argument("--json", action="store_true",
                    help="emit one JSON report object instead of text")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file (default: the checked-in one)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: every finding is live")
    ap.add_argument("--write-baseline", metavar="REASON",
                    help="write current live findings to the baseline file "
                         "with REASON and exit (bootstrap/refresh helper)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print each rule name and the contract it encodes")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name}\n    {rule.contract}")
        return 0

    paths = [p for p in args.paths if Path(p).exists()]
    if not paths:
        print("no scannable paths given", file=sys.stderr)
        return 2

    if args.write_baseline:
        report = analyze_paths(paths, use_baseline=False)
        entries = baseline_entries_from_findings(report.findings,
                                                 args.write_baseline)
        Path(args.baseline).write_text(json.dumps(
            {"entries": entries}, indent=2, sort_keys=True) + "\n")
        print(f"wrote {len(entries)} baseline entries -> {args.baseline}")
        return 0

    report = analyze_paths(
        paths,
        baseline_path=None if args.no_baseline else args.baseline,
        use_baseline=not args.no_baseline)

    if args.json:
        print(json.dumps(report.as_dict(), indent=2))
    else:
        for f in report.findings:
            print(f.render())
        print(f"{len(report.findings)} finding(s) · "
              f"{report.checked_files} file(s) · "
              f"{len(report.rules)} rule(s) · "
              f"{len(report.suppressed)} suppressed · "
              f"{len(report.baselined)} baselined")
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
