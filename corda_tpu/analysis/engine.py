"""Rule engine for the invariant analyzer.

One parse per file: the engine reads each ``*.py`` under the scanned paths,
builds the AST and the source-line table once, and hands a ``FileContext``
to every rule whose scope matches the file. Rules return ``Finding``s; the
engine then applies the two escape hatches:

  * inline suppression — ``# lint: allow(<rule>) <reason>`` on the finding's
    anchor line or the line directly above it. A reason is MANDATORY: an
    allow() with no reason (or naming an unknown rule) is itself reported
    under the ``bad-suppression`` rule, so the tree can never accumulate
    unexplained exemptions.
  * baseline — a checked-in JSON file enumerating accepted pre-existing
    sites as (rule, path, code, reason) entries, matched by the stripped
    source text of the finding's anchor line (robust to line drift). Each
    entry absorbs up to ``count`` findings (default 1); excess findings
    surface normally. Entries whose file no longer exists, whose reason is
    empty, or which matched nothing this run are reported under the
    ``stale-baseline`` rule — the baseline shrinks monotonically or fails
    tier-1.

Exit contract (used by ``__main__`` and tests/test_static_analysis.py):
zero live findings == the tree upholds every machine-checked invariant.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass, field
from pathlib import Path

__all__ = [
    "Finding",
    "FileContext",
    "Rule",
    "Report",
    "analyze_paths",
    "analyze_source",
    "load_baseline",
    "baseline_entries_from_findings",
    "DEFAULT_BASELINE",
]

# Checked-in baseline lives next to the engine so `python -m
# corda_tpu.analysis` finds it without flags from any cwd.
DEFAULT_BASELINE = Path(__file__).with_name("baseline.json")

_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([A-Za-z0-9_-]+)\)\s*(.*)")

# Engine-level pseudo-rules (never suppressible themselves).
BAD_SUPPRESSION = "bad-suppression"
STALE_BASELINE = "stale-baseline"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # posix path as scanned (package-relative for scoping)
    line: int
    message: str
    hint: str = ""
    code: str = ""     # stripped source text of the anchor line

    def render(self) -> str:
        out = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "hint": self.hint,
                "code": self.code}


class FileContext:
    """Everything a rule needs about one file, parsed once."""

    def __init__(self, path: str, source: str, tree: ast.AST):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        # Parent links let rules walk outward (enclosing function stack).
        self.parents: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def enclosing_functions(self, node: ast.AST) -> list[ast.AST]:
        """Innermost-first stack of FunctionDef/AsyncFunctionDef containing
        ``node`` (lambdas excluded — they can't carry the constructs the
        rules scope by)."""
        out = []
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(cur)
            cur = self.parents.get(cur)
        return out

    def finding(self, rule: "Rule", node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(rule.name, self.path, line, message,
                       hint=rule.hint, code=self.line_text(line))


class Rule:
    """Base rule: subclasses set ``name``, ``contract`` (the prose invariant
    this rule machine-checks), ``hint`` (the fix direction shown with every
    finding), optionally ``scope`` (path substrings; empty = whole tree),
    and implement ``check(ctx) -> list[Finding]``."""

    name = ""
    contract = ""
    hint = ""
    scope: tuple[str, ...] = ()
    exclude: tuple[str, ...] = ()

    def applies_to(self, path: str) -> bool:
        if any(part in path for part in self.exclude):
            return False
        if not self.scope:
            return True
        return any(part in path for part in self.scope)

    def check(self, ctx: FileContext) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


@dataclass
class Report:
    findings: list[Finding] = field(default_factory=list)
    suppressed: list[Finding] = field(default_factory=list)
    baselined: list[Finding] = field(default_factory=list)
    checked_files: int = 0
    rules: tuple[str, ...] = ()

    @property
    def clean(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "checked_files": self.checked_files,
            "rules": list(self.rules),
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "clean": self.clean,
        }


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


def _parse_suppressions(ctx: FileContext,
                        known_rules: set[str]) -> tuple[dict, list[Finding]]:
    """-> ({line -> set(rule names allowed on/below that comment)}, bad
    suppression findings). A comment on line N covers findings anchored on
    line N (trailing comment) and line N+1 (comment-above style)."""
    allowed: dict[int, set[str]] = {}
    bad: list[Finding] = []
    for i, text in enumerate(ctx.lines, start=1):
        m = _ALLOW_RE.search(text)
        if m is None:
            continue
        rule, reason = m.group(1), m.group(2).strip()
        if rule not in known_rules:
            bad.append(Finding(
                BAD_SUPPRESSION, ctx.path, i,
                f"allow() names unknown rule {rule!r}",
                hint="suppressions must name an active rule",
                code=text.strip()))
            continue
        if not reason:
            bad.append(Finding(
                BAD_SUPPRESSION, ctx.path, i,
                f"allow({rule}) carries no reason",
                hint="every suppression must say WHY the site is exempt: "
                     "lint: allow(<rule>) <reason>",
                code=text.strip()))
            continue
        allowed.setdefault(i, set()).add(rule)
        allowed.setdefault(i + 1, set()).add(rule)
    return allowed, bad


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------


def load_baseline(path: Path | str | None) -> list[dict]:
    if path is None:
        return []
    p = Path(path)
    if not p.exists():
        return []
    doc = json.loads(p.read_text())
    return list(doc.get("entries", ()))


def baseline_entries_from_findings(findings: list[Finding],
                                   reason: str) -> list[dict]:
    """Entry list for --write-baseline: one entry per distinct
    (rule, path, code) with the multiplicity as count."""
    grouped: dict[tuple, int] = {}
    for f in findings:
        grouped[(f.rule, f.path, f.code)] = \
            grouped.get((f.rule, f.path, f.code), 0) + 1
    return [{"rule": r, "path": p, "code": c, "count": n, "reason": reason}
            for (r, p, c), n in sorted(grouped.items())]


class _Baseline:
    def __init__(self, entries: list[dict]):
        self.entries = entries
        # (rule, path, code) -> remaining absorb budget
        self.budget: dict[tuple, int] = {}
        self.used: dict[tuple, int] = {}
        for e in entries:
            key = (e.get("rule"), e.get("path"), e.get("code"))
            self.budget[key] = self.budget.get(key, 0) + int(
                e.get("count", 1))
            self.used.setdefault(key, 0)

    def absorb(self, f: Finding) -> bool:
        key = (f.rule, f.path, f.code)
        if self.budget.get(key, 0) > 0:
            self.budget[key] -= 1
            self.used[key] += 1
            return True
        return False

    def stale_findings(self, seen_paths: set[str]) -> list[Finding]:
        out = []
        for e in self.entries:
            rule, path = e.get("rule"), e.get("path", "")
            reason = str(e.get("reason", "")).strip()
            key = (rule, path, e.get("code"))
            if not reason:
                out.append(Finding(
                    STALE_BASELINE, path, 0,
                    f"baseline entry for [{rule}] carries no reason",
                    hint="every baseline entry must say WHY the site is "
                         "accepted"))
            elif path not in seen_paths:
                out.append(Finding(
                    STALE_BASELINE, path, 0,
                    f"baseline entry for [{rule}] names a file that was "
                    "not scanned (deleted or renamed)",
                    hint="remove the entry — baselines shrink, never rot"))
            elif self.used.get(key, 0) == 0:
                out.append(Finding(
                    STALE_BASELINE, path, 0,
                    f"baseline entry for [{rule}] matched no finding "
                    f"(site fixed?): {e.get('code', '')!r}",
                    hint="remove the entry — the violation it excused is "
                         "gone"))
        return out


# ---------------------------------------------------------------------------
# Driving
# ---------------------------------------------------------------------------


def _iter_py_files(paths) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out.extend(sorted(q for q in p.rglob("*.py")
                              if "__pycache__" not in q.parts))
        elif p.suffix == ".py":
            out.append(p)
    return out


def _scoped_path(p: Path) -> str:
    """Path used for rule scoping and reports: rebased to start at the
    package dir ("corda_tpu/...") when the file lives under one, else the
    given path as-is (fixtures, out-of-tree scans)."""
    parts = p.as_posix().split("/")
    if "corda_tpu" in parts:
        return "/".join(parts[parts.index("corda_tpu"):])
    return p.as_posix()


def _check_file(path: str, source: str, rules, report: Report,
                baseline: _Baseline | None) -> None:
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        report.findings.append(Finding(
            "syntax-error", path, e.lineno or 1, str(e.msg),
            hint="the analyzer (and the interpreter) must be able to "
                 "parse every file"))
        return
    ctx = FileContext(path, source, tree)
    known = {r.name for r in rules}
    allowed, bad = _parse_suppressions(ctx, known)
    report.findings.extend(bad)
    for rule in rules:
        if not rule.applies_to(path):
            continue
        for f in rule.check(ctx):
            if f.rule in allowed.get(f.line, ()):
                report.suppressed.append(f)
            elif baseline is not None and baseline.absorb(f):
                report.baselined.append(f)
            else:
                report.findings.append(f)


def analyze_paths(paths, rules=None, baseline_path=DEFAULT_BASELINE,
                  use_baseline: bool = True) -> Report:
    """Run every rule over every python file under ``paths``."""
    if rules is None:
        from .rules import ALL_RULES

        rules = ALL_RULES
    baseline = _Baseline(load_baseline(baseline_path)) if use_baseline \
        else None
    report = Report(rules=tuple(r.name for r in rules))
    seen: set[str] = set()
    for file_path in _iter_py_files(paths):
        scoped = _scoped_path(file_path)
        seen.add(scoped)
        try:
            source = file_path.read_text()
        except (OSError, UnicodeDecodeError) as e:
            report.findings.append(Finding(
                "syntax-error", scoped, 1, f"unreadable: {e}"))
            continue
        report.checked_files += 1
        _check_file(scoped, source, rules, report, baseline)
    if baseline is not None:
        report.findings.extend(baseline.stale_findings(seen))
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return report


def analyze_source(source: str, path: str, rules=None,
                   baseline_entries: list[dict] | None = None) -> Report:
    """Test hook: run the rules over one in-memory snippet under a chosen
    scoping path (e.g. "corda_tpu/node/services/raft.py")."""
    if rules is None:
        from .rules import ALL_RULES

        rules = ALL_RULES
    baseline = _Baseline(baseline_entries) if baseline_entries else None
    report = Report(rules=tuple(r.name for r in rules))
    _check_file(path, source, rules, report, baseline)
    if baseline is not None:
        report.findings.extend(baseline.stale_findings({path}))
    report.findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return report
