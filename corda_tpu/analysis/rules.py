"""The invariant rules: each one machine-checks a contract that previously
existed only as prose in CHANGES.md / ARCHITECTURE.md.

Rules are deliberately lexical/AST-level — no type inference, no
cross-module call graphs. Where a contract genuinely needs an exemption
(coordinator stamping, an executable-cache constructor), the site carries
an inline ``# lint: allow(<rule>) <reason>`` so the exemption is visible,
reasoned, and enumerable, instead of the rule being quietly weakened.
"""

from __future__ import annotations

import ast

from .engine import FileContext, Finding, Rule

__all__ = ["ALL_RULES"]


# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> str:
    """'self._db.conn.execute'-style dotted text for Name/Attribute chains
    ('' when the expression is not a plain chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _last_attr(dotted: str) -> str:
    return dotted.rsplit(".", 1)[-1] if dotted else ""


class _Imports:
    """Module-alias table for one file: which local names refer to the
    ``time`` / ``datetime`` / obs ``trace`` / obs ``telemetry`` modules,
    and which bare names are from-imported clock functions."""

    def __init__(self, tree: ast.AST):
        self.time_aliases: set[str] = set()
        self.datetime_aliases: set[str] = set()
        self.obs_trace_aliases: set[str] = set()
        self.telemetry_aliases: set[str] = set()
        self.clock_names: dict[str, str] = {}   # local name -> origin fn
        self.record_names: set[str] = set()     # from obs.trace import record
        # local name -> "inc" | "observe"  (from obs.telemetry import ...)
        self.metric_fn_names: dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    if a.name == "time":
                        self.time_aliases.add(local)
                    elif a.name == "datetime":
                        self.datetime_aliases.add(local)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "time":
                    for a in node.names:
                        self.clock_names[a.asname or a.name] = a.name
                elif mod == "datetime":
                    for a in node.names:
                        if a.name in ("datetime", "date"):
                            self.datetime_aliases.add(a.asname or a.name)
                elif mod.endswith("obs") or mod.endswith("obs.trace") \
                        or mod.endswith("obs.telemetry"):
                    for a in node.names:
                        if a.name == "trace":
                            self.obs_trace_aliases.add(a.asname or a.name)
                        elif a.name == "telemetry":
                            self.telemetry_aliases.add(a.asname or a.name)
                        elif a.name == "record" and mod.endswith("trace"):
                            self.record_names.add(a.asname or a.name)
                        elif a.name in ("inc", "observe") and \
                                mod.endswith("telemetry"):
                            self.metric_fn_names[a.asname or a.name] = a.name


_EPOCH_ATTRS = ("time", "time_ns")
_MONO_ATTRS = ("monotonic", "monotonic_ns", "perf_counter",
               "perf_counter_ns")
_DATETIME_ATTRS = ("now", "utcnow", "today")


def _clock_kind(call: ast.Call, imports: _Imports) -> str | None:
    """'epoch' | 'mono' | None for a Call node."""
    func = call.func
    if isinstance(func, ast.Name):
        origin = imports.clock_names.get(func.id)
        if origin in _EPOCH_ATTRS:
            return "epoch"
        if origin in _MONO_ATTRS:
            return "mono"
        return None
    dotted = _dotted(func)
    if not dotted or "." not in dotted:
        return None
    root, attr = dotted.split(".", 1)[0], _last_attr(dotted)
    if root in imports.time_aliases:
        if attr in _EPOCH_ATTRS:
            return "epoch"
        if attr in _MONO_ATTRS:
            return "mono"
    if root in imports.datetime_aliases and attr in _DATETIME_ATTRS:
        return "epoch"
    return None


def _walk_skip_functions(body) -> list[ast.AST]:
    """Every node under ``body`` WITHOUT descending into nested function or
    class definitions — their bodies execute at call time, not here."""
    out: list[ast.AST] = []
    stack = list(body)
    while stack:
        node = stack.pop()
        out.append(node)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _in_decorator(ctx: FileContext, node: ast.AST, fn: ast.AST) -> bool:
    """Is ``node`` inside one of ``fn``'s decorator expressions (rather
    than its body)? A module-level ``fn = jax.jit(...)``-style decorator
    call parents to the FunctionDef it decorates, which must not count as
    'inside a function'."""
    cur = node
    while cur is not None and ctx.parents.get(cur) is not fn:
        cur = ctx.parents.get(cur)
    if cur is None:
        return False
    return any(cur is d or cur in ast.walk(d)
               for d in getattr(fn, "decorator_list", ()))


def _enclosing_class(ctx: FileContext, node: ast.AST) -> str:
    cur = ctx.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return cur.name
        cur = ctx.parents.get(cur)
    return ""


# ---------------------------------------------------------------------------
# Rule 1: no-wallclock-in-apply
# ---------------------------------------------------------------------------


class NoWallclockInApply(Rule):
    """Replicated state machines never read clocks: every replica must
    compute the same result from the same command, so expiry/TTL decisions
    compare command-carried ``issued_at`` stamps, never a local clock
    (ARCHITECTURE.md, sharded-notary TTL contract). In the consensus
    modules, epoch reads (``time.time``/``datetime.now``) are findings
    everywhere — coordinator stamping sites are the explicit, reasoned
    exceptions — and inside apply-path functions even monotonic reads are
    findings (apply must be a pure function of the command + db state)."""

    name = "no-wallclock-in-apply"
    contract = ("replicas never read clocks: apply paths are deterministic "
                "functions of (command, db); TTL expiry compares "
                "command-carried issued_at stamps")
    hint = ("carry the timestamp in the command (coordinator-stamped "
            "issued_at) and compare stamps; if this IS a coordinator "
            "stamping site, add an allow comment naming the rule with "
            "the why")
    scope = ("node/services/raft.py", "node/services/sharding.py")

    APPLY_ROOTS = ("make_apply_command",)

    def _in_apply_scope(self, ctx: FileContext, node: ast.AST) -> bool:
        for fn in ctx.enclosing_functions(node):
            name = fn.name
            if (name == "apply" or name.startswith("_apply")
                    or name in self.APPLY_ROOTS):
                return True
        return False

    def check(self, ctx: FileContext) -> list[Finding]:
        imports = _Imports(ctx.tree)
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            kind = _clock_kind(node, imports)
            if kind is None:
                continue
            if kind == "epoch":
                out.append(ctx.finding(
                    self, node,
                    f"epoch clock read ({_dotted(node.func) or 'time'}) in "
                    "a consensus module — replicas that re-apply this path "
                    "would diverge"))
            elif self._in_apply_scope(ctx, node):
                out.append(ctx.finding(
                    self, node,
                    "monotonic clock read inside an apply-path function — "
                    "apply must be deterministic in (command, db state)"))
        return out


# ---------------------------------------------------------------------------
# Rule 2: no-silent-except
# ---------------------------------------------------------------------------


class NoSilentExcept(Rule):
    """A broad ``except Exception: pass`` on a verify/notarise path can
    swallow the exact infrastructure fault the degrade machinery exists to
    surface (crypto.provider.degrade_device, node_metrics counters). Broad
    handlers must narrow the exception, count the event, or route to the
    degrade path — silence is never a handling strategy."""

    name = "no-silent-except"
    contract = ("broad exception handlers on production paths must narrow, "
                "count, or degrade — never silently pass")
    hint = ("narrow the except to the exceptions this site can actually "
            "absorb, bump a node_metrics/stats counter, or call the "
            "degrade path; best-effort tooling sites carry an allow() "
            "with the reason")

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True
        if isinstance(t, ast.Name):
            return t.id in ("Exception", "BaseException")
        if isinstance(t, ast.Tuple):
            return any(isinstance(e, ast.Name)
                       and e.id in ("Exception", "BaseException")
                       for e in t.elts)
        return False

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node):
                continue
            if all(isinstance(s, ast.Pass) for s in node.body):
                out.append(ctx.finding(
                    self, node,
                    "broad except with a silent pass body swallows every "
                    "failure class, including the ones the degrade path "
                    "must see"))
        return out


# ---------------------------------------------------------------------------
# Rule 3: no-jit-in-hotpath
# ---------------------------------------------------------------------------


class NoJitInHotpath(Rule):
    """One cached executable per (graph, mesh): ``jax.jit`` / ``shard_map``
    / mesh construction inside a per-batch call path recompiles (seconds)
    or re-partitions (re-layout per dispatch) on the hot path — the p99
    collapse class PAPERS.md attributes to XLA recompilation hazards. Such
    calls belong at module level, behind a functools cache, or inside the
    ``_sharded_fn``-style keyed-cache constructor (which carries its own
    allow())."""

    name = "no-jit-in-hotpath"
    contract = ("one cached jit executable per (graph, mesh): never "
                "construct jit/shard_map/mesh inside a per-batch path")
    hint = ("hoist to module level, decorate the builder with "
            "functools.lru_cache/cache, or route through the keyed "
            "executable cache (ops/sharded._sharded_fn)")

    JIT_NAMES = ("jit", "pjit", "shard_map", "make_mesh", "Mesh")
    CACHE_DECORATORS = ("lru_cache", "cache")

    def _is_jit_call(self, call: ast.Call) -> bool:
        func = call.func
        if isinstance(func, ast.Name):
            return func.id in self.JIT_NAMES or func.id == "_shard_map"
        dotted = _dotted(func)
        return _last_attr(dotted) in self.JIT_NAMES

    def _cached_builder(self, fn: ast.AST) -> bool:
        for dec in getattr(fn, "decorator_list", ()):
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = target.id if isinstance(target, ast.Name) \
                else _last_attr(_dotted(target))
            if name in self.CACHE_DECORATORS:
                return True
        return False

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not self._is_jit_call(node):
                continue
            enclosing = [fn for fn in ctx.enclosing_functions(node)
                         if not _in_decorator(ctx, node, fn)]
            if not enclosing:
                continue  # module level: compiled once at import
            if any(self._cached_builder(fn) for fn in enclosing):
                continue  # functools-cached builder: one construction per key
            out.append(ctx.finding(
                self, node,
                f"{_dotted(node.func) or 'jit'}() constructed inside "
                f"{enclosing[0].name}() — a per-call jit/mesh build "
                "recompiles or re-partitions on the hot path"))
        return out


# ---------------------------------------------------------------------------
# Rules 4+5 share lock identification
# ---------------------------------------------------------------------------


_LOCK_CTORS = ("Lock", "RLock", "Condition")

# Locks whose PURPOSE is to serialize I/O on a shared connection: holding
# them across sqlite calls is the design (single-writer architecture,
# node/services/persistence.py), not a hazard. Matched by dotted suffix.
_IO_SERIALIZATION_LOCKS = ("db.lock", "db.aux_lock", "aux_lock",
                           "_db.lock", "_db.aux_lock")


class _LockTable:
    """Per-file lock inventory: attribute/variable names assigned a
    threading.Lock/RLock/Condition, with Condition names kept separately
    (their .wait() releases the lock and is exempt from blocking checks)."""

    def __init__(self, tree: ast.AST):
        self.lock_attrs: set[str] = set()
        self.condition_attrs: set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or \
                    not isinstance(node.value, ast.Call):
                continue
            func = node.value.func
            ctor = func.id if isinstance(func, ast.Name) \
                else _last_attr(_dotted(func))
            if ctor not in _LOCK_CTORS:
                continue
            for target in node.targets:
                name = _last_attr(_dotted(target))
                if not name:
                    continue
                if ctor == "Condition":
                    self.condition_attrs.add(name)
                self.lock_attrs.add(name)

    def is_lock_expr(self, expr: ast.AST) -> str:
        """Dotted text when ``with <expr>:`` acquires a known lock, else
        ''. Falls back to the textual convention (last attribute contains
        'lock') so locks constructed in another file still count."""
        dotted = _dotted(expr)
        if not dotted:
            return ""
        attr = _last_attr(dotted)
        if attr in self.lock_attrs or "lock" in attr.lower():
            return dotted
        return ""


def _is_io_serialization_lock(dotted: str) -> bool:
    return any(dotted.endswith(sfx) for sfx in _IO_SERIALIZATION_LOCKS)


class NoBlockingUnderLock(Rule):
    """Socket, sqlite, or device-dispatch I/O while holding a
    general-purpose mutex turns every contender on that lock into a convoy
    behind the I/O's tail latency — a p99 hazard per-stage tracing can only
    attribute after the fact. Locks guard state, not I/O: copy under the
    lock, perform the I/O outside it. Locks whose documented purpose IS
    I/O serialization (the sqlite single-writer ``db.lock``/``aux_lock``)
    are exempt by name."""

    name = "no-blocking-under-lock"
    contract = ("never hold a general-purpose threading.Lock across "
                "socket/sqlite/device I/O — copy under the lock, do the "
                "I/O outside")
    hint = ("move the blocking call outside the with-block (snapshot the "
            "state under the lock), hand the work to the owning thread, "
            "or — when the lock's purpose IS the I/O serialization — "
            "allow() the with-statement with that reason")

    SOCKET_ATTRS = ("sendall", "recv", "recv_into", "accept", "connect",
                    "connect_ex", "makefile", "create_connection",
                    "wrap_socket")
    # Project framing helpers that wrap sendall/recv on a passed socket.
    FRAMING_FNS = ("send_frame", "_send_frame", "recv_frame", "_recv_frame",
                   "recv_exact", "_recv_exact")
    SQL_ATTRS = ("execute", "executemany", "executescript", "commit",
                 "fetchone", "fetchall")
    DEVICE_ATTRS = ("verify_batch", "verify_packed", "pack_device", "warm",
                    "block_until_ready")

    def _blocking_call(self, call: ast.Call, imports: _Imports) -> str:
        func = call.func
        if isinstance(func, ast.Name):
            if func.id in self.FRAMING_FNS:
                return func.id
            if imports.clock_names.get(func.id) == "sleep":
                return func.id
            return ""
        dotted = _dotted(func)
        attr = _last_attr(dotted)
        prefix = dotted[: -(len(attr) + 1)] if "." in dotted else ""
        if attr in self.SOCKET_ATTRS or attr in self.FRAMING_FNS:
            return dotted
        if attr in self.DEVICE_ATTRS:
            return dotted
        if attr == "sleep" and dotted.split(".", 1)[0] in \
                imports.time_aliases:
            return dotted
        if attr in self.SQL_ATTRS and any(
                tok in prefix for tok in ("conn", "db", "cursor")):
            return dotted
        return ""

    def check(self, ctx: FileContext) -> list[Finding]:
        table = _LockTable(ctx.tree)
        imports = _Imports(ctx.tree)
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.With):
                continue
            lock_exprs = [table.is_lock_expr(item.context_expr)
                          for item in node.items]
            lock_exprs = [e for e in lock_exprs
                          if e and not _is_io_serialization_lock(e)]
            if not lock_exprs:
                continue
            blocking: list[str] = []
            for sub in _walk_skip_functions(node.body):
                if not isinstance(sub, ast.Call):
                    continue
                name = self._blocking_call(sub, imports)
                if not name:
                    continue
                # cond.wait() RELEASES the lock while blocked — exempt on
                # the condition this with-statement holds.
                if _last_attr(name) == "wait":
                    continue
                if any(name.startswith(e + ".") for e in lock_exprs):
                    continue
                blocking.append(f"{name}():{sub.lineno}")
            if blocking:
                out.append(ctx.finding(
                    self, node,
                    f"blocking call(s) {', '.join(sorted(set(blocking)))} "
                    f"while holding {' + '.join(lock_exprs)}"))
        return out


class LockOrder(Rule):
    """Deadlock freedom by construction: the static lock-acquisition graph
    (lock A held while acquiring lock B, per class) must stay acyclic, and
    a non-reentrant Lock must never be acquired while already held. The
    sidecar scheduler, feeder, and Raft streams put 32 threading sites
    across 12 files on these edges — a cycle introduced by a future PR is
    a hang that only reproduces under load."""

    name = "lock-order"
    contract = ("the static lock-acquisition graph is acyclic and no "
                "plain Lock is re-acquired while held")
    hint = ("acquire locks in one global order (sort before acquiring, as "
            "the 2PC coordinator does with shard groups), or restructure "
            "so one thread owns the state")

    def check(self, ctx: FileContext) -> list[Finding]:
        table = _LockTable(ctx.tree)
        out: list[Finding] = []
        edges: dict[tuple[str, str], int] = {}  # (outer, inner) -> line

        def walk(body, held: list[tuple[str, int]], cls: str) -> None:
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    walk(stmt.body, [], cls)
                    continue
                if isinstance(stmt, ast.ClassDef):
                    walk(stmt.body, [], f"{cls}.{stmt.name}" if cls
                         else stmt.name)
                    continue
                if isinstance(stmt, ast.With):
                    acquired = []
                    for item in stmt.items:
                        dotted = table.is_lock_expr(item.context_expr)
                        if not dotted:
                            continue
                        qual = f"{cls}:{dotted}" if cls else dotted
                        for outer, _line in held + acquired:
                            if outer == qual and _last_attr(dotted) not in \
                                    table.condition_attrs:
                                out.append(ctx.finding(
                                    self, stmt,
                                    f"{dotted} re-acquired while already "
                                    "held — a plain threading.Lock "
                                    "self-deadlocks here"))
                            elif outer != qual:
                                edges.setdefault((outer, qual), stmt.lineno)
                        acquired.append((qual, stmt.lineno))
                    walk(stmt.body, held + acquired, cls)
                    continue
                # Recurse into compound statements' bodies while keeping
                # the held stack (if/for/while/try/match all hold the lock).
                for attr in ("body", "orelse", "finalbody", "handlers",
                             "cases"):
                    sub = getattr(stmt, attr, None)
                    if isinstance(sub, list) and sub:
                        inner = []
                        for s in sub:
                            inner.extend(s.body if hasattr(s, "body")
                                         and not isinstance(s, ast.stmt)
                                         else [s])
                        walk(inner, held, cls)

        walk(list(getattr(ctx.tree, "body", ())), [], "")

        # Cycle detection over the per-file edge set.
        graph: dict[str, set[str]] = {}
        for (a, b) in edges:
            graph.setdefault(a, set()).add(b)
        seen_cycles: set[frozenset] = set()
        for start in list(graph):
            stack = [(start, [start])]
            while stack:
                cur, path = stack.pop()
                for nxt in graph.get(cur, ()):
                    if nxt == start:
                        cyc = frozenset(path)
                        if cyc in seen_cycles:
                            continue
                        seen_cycles.add(cyc)
                        line = edges.get((cur, start), 1)
                        loop = " -> ".join(path + [start])
                        out.append(Finding(
                            self.name, ctx.path, line,
                            f"lock-order cycle: {loop}",
                            hint=self.hint,
                            code=ctx.line_text(line)))
                    elif nxt not in path:
                        stack.append((nxt, path + [nxt]))
        return out


# ---------------------------------------------------------------------------
# Rule 6: trace-stage-registry
# ---------------------------------------------------------------------------


class TraceStageRegistry(Rule):
    """``stage_breakdown`` attributes latency by exact span-name match; a
    span recorded under an unregistered name silently vanishes from the
    bench breakdown (no error — a missing stage). Every literal span name
    passed to ``_obs.record(...)`` must come from the obs stage registry
    (corda_tpu/obs/stages.py). The telemetry plane has the same failure
    shape with the opposite sign: ``_tm.inc``/``_tm.observe`` on a name
    the registry never pre-interned RAISES at runtime — possibly only on
    a rare error path — so literal metric names must come from
    obs/telemetry.py's single-source-of-truth name registry too."""

    name = "trace-stage-registry"
    contract = ("every recorded span name is registered in obs/stages.py "
                "and every telemetry counter/histogram name in "
                "obs/telemetry.py, so breakdowns never silently drop a "
                "stage and metric updates never raise on a rare path")
    hint = ("register the name in corda_tpu/obs/stages.py (breakdown "
            "stages get a slot in STAGES) or in obs/telemetry.py's "
            "COUNTER_NAMES/HISTOGRAM_NAMES, or reuse a registered name")
    exclude = ("obs/", "analysis/")

    def _registry(self):
        from ..obs import stages

        return stages.SPAN_NAMES, stages.SPAN_NAME_PREFIXES

    def _metric_registry(self):
        from ..obs import telemetry

        return telemetry.METRIC_NAMES

    def _is_record_call(self, call: ast.Call, imports: _Imports) -> bool:
        func = call.func
        if isinstance(func, ast.Name):
            return func.id in imports.record_names
        dotted = _dotted(func)
        if _last_attr(dotted) != "record":
            return False
        root = dotted.split(".", 1)[0]
        return root in imports.obs_trace_aliases

    def _is_metric_call(self, call: ast.Call, imports: _Imports) -> bool:
        func = call.func
        if isinstance(func, ast.Name):
            return func.id in imports.metric_fn_names
        dotted = _dotted(func)
        if _last_attr(dotted) not in ("inc", "observe"):
            return False
        root = dotted.split(".", 1)[0]
        return root in imports.telemetry_aliases

    def check(self, ctx: FileContext) -> list[Finding]:
        imports = _Imports(ctx.tree)
        track_spans = bool(imports.obs_trace_aliases or imports.record_names)
        track_metrics = bool(imports.telemetry_aliases
                             or imports.metric_fn_names)
        if not track_spans and not track_metrics:
            return []
        names, prefixes = self._registry()
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if track_metrics and self._is_metric_call(node, imports):
                if not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) and \
                        arg.value not in self._metric_registry():
                    out.append(ctx.finding(
                        self, arg,
                        f"metric name {arg.value!r} is not pre-interned in "
                        "obs/telemetry.py — inc/observe raises ValueError "
                        "here at runtime"))
                continue
            if not track_spans or not self._is_record_call(node, imports):
                continue
            if not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                name = arg.value
                if name in names or name.startswith(prefixes):
                    continue
                out.append(ctx.finding(
                    self, arg,
                    f"span name {name!r} is not in the obs stage registry "
                    "— stage_breakdown would silently drop it"))
            elif isinstance(arg, ast.JoinedStr) and arg.values:
                first = arg.values[0]
                if isinstance(first, ast.Constant) and \
                        isinstance(first.value, str):
                    piece = first.value
                    if not piece.startswith(prefixes):
                        out.append(ctx.finding(
                            self, arg,
                            f"dynamic span name starting {piece!r} matches "
                            "no registered prefix (obs/stages.py "
                            "SPAN_NAME_PREFIXES)"))
            # Non-literal names (variables) are checked at the site that
            # builds the literal; the registry rule stays lexical.
        return out


ALL_RULES: tuple[Rule, ...] = (
    NoWallclockInApply(),
    NoSilentExcept(),
    NoJitInHotpath(),
    NoBlockingUnderLock(),
    LockOrder(),
    TraceStageRegistry(),
)
