"""The autotune plane (round 21): a closed-loop controller that turns
perf-doctor verdicts into gated parameter sweeps and self-committed
configs.

Three parts, matching the shape of the loop:

  * :mod:`~corda_tpu.autotune.space` — the typed, bounded knob registry
    over the config keys that actually exist, each knob carrying its
    config path, bounds, step rule and the doctor cause(s) that
    implicate it, with analyzer-style validation pinning the registry
    to ``node/config.py`` so the space can never drift.
  * :mod:`~corda_tpu.autotune.controller` — verdict in, sweep out: a
    deterministic seeded hill-climb over the implicated knobs, every
    candidate measured by an existing loadtest harness and gated
    against the incumbent under ``perfdoctor --gate`` direction+band
    policy (exactly-once flags are hard gates), the winner emitted as
    a TOML overlay plus an ``autotune`` trajectory record with full
    provenance.
  * :mod:`~corda_tpu.autotune.runtime` — the opt-in bounded runtime
    leg: a controller thread feeding live ``round_breakdown`` deltas
    into the adaptive policies that already exist, with hysteresis and
    a hard revert-on-regression guard; off by default and bit-identical
    when disarmed.

``python -m corda_tpu.tools.autotune`` is the CLI face.
"""

from . import controller, runtime, space

__all__ = ["controller", "runtime", "space"]
