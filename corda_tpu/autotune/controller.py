"""The autotune controller: one doctor verdict in, one gated sweep out.

The loop is verdict -> sweep -> gate -> commit:

  * **verdict -> sweep**: :func:`spec_from_verdict` reads the top
    bottleneck's structured ``experiment`` spec (obs/doctor.RULE_SPECS,
    riding every bottleneck entry since round 21) and narrows its knob
    list to the registry knobs that apply to the chosen harness — no
    string-matching on the prose suggestion, ever.
  * **sweep**: :func:`run_autotune` is a deterministic seeded
    hill-climb: knob visit order and first step direction come from a
    seeded LCG, every proposed value from the registry's step rules,
    and nothing in the decision path reads a clock or an RNG stream
    beyond that LCG — the same seed against the same runner replays the
    identical decision sequence (pinned by ``decision_sequence``).
  * **gate**: every candidate is judged against the *incumbent* (the
    hand-tuned defaults, measured as candidate 0) by
    :func:`gate_candidate`, which literally runs ``obs/doctor.gate``
    over a two-record trajectory under the ``perfdoctor --gate``
    direction+band policy. Exactly-once/SLO flags are HARD gates: a
    candidate that flips one False is vetoed no matter how fast it got.
    A candidate that crashes is isolated — recorded with its error,
    hard-vetoed, and the search continues.
  * **commit**: the winner (if any candidate beat the incumbent on the
    swept metric AND survived the gate) is emitted as a TOML overlay an
    operator can apply verbatim, and the whole run — verdict consumed,
    every candidate's values/metrics/gate outcome, the decision
    sequence, the seed — lands in one ``autotune`` trajectory record.
    No improvement means no commit: the incumbent stands and the record
    says so honestly.

The runner is injected (``runner(values) -> metrics dict``): the CLI
and bench wire the real loadtest harnesses via :func:`make_ingest_runner`
(config knobs travel as one ``CORDA_TPU_CONFIG_OVERLAY`` env to every
spawned node, env knobs as their own vars, harness knobs as kwargs);
tests and ``--mock`` wire :func:`make_mock_runner`'s deterministic
response curves.
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass

from ..obs import doctor as _doctor
from ..obs import telemetry as _tm
from . import space as _space

__all__ = [
    "AUTOTUNE_SCHEMA",
    "HARD_GATE_FLAGS",
    "SweepSpec",
    "exploratory_spec",
    "gate_candidate",
    "make_ingest_runner",
    "make_mock_runner",
    "reset_between_candidates",
    "run_autotune",
    "spec_from_verdict",
]

AUTOTUNE_SCHEMA = 1

# Non-incumbent candidates a search may evaluate before it stops.
DEFAULT_BUDGET = 6

# Boolean flags that hard-gate a candidate even when absent from the
# doctor policy: incumbent True -> candidate False is an outright veto
# (a config that breaks exactly-once delivery is not "20% slower", it
# is wrong).
HARD_GATE_FLAGS = ("exactly_once", "exactly_once_all", "slo_met",
                   "parity_ok_all", "history_linearizable")

# harness name (RULE_SPECS vocabulary) -> (loadtest fn, default swept
# metric, direction). Harnesses outside this map have no sweepable
# runner (trace/partition/federation experiments are not parameter
# sweeps).
HARNESSES = {
    "ingest_sweep": ("run_ingest_sweep", "peak_achieved_tx_s", "higher"),
    "slo_sweep": ("run_slo_sweep", "peak_achieved_tx_s", "higher"),
}


@dataclass(frozen=True)
class SweepSpec:
    """One sweep, fully determined: which experiment, which knobs, which
    harness measures it, and which metric (with direction) decides."""

    experiment_id: str
    cause: str | None
    knobs: tuple
    harness: str
    metric: str
    direction: str = "higher"


def _top_bottleneck(verdict: dict):
    """(cause, experiment-spec|None) from any verdict shape: a
    stamp_attribution/diagnose dict (entries are dicts carrying the
    structured ``experiment``) or a trajectory-record verdict (entries
    are bare cause strings)."""
    entries = (verdict or {}).get("bottlenecks") or []
    if not entries:
        return None, None
    top = entries[0]
    if isinstance(top, dict):
        return top.get("cause"), top.get("experiment")
    return str(top), None


def spec_from_verdict(verdict: dict, *, metric: str | None = None,
                      pipelined: bool = False) -> SweepSpec:
    """Map a PerfVerdict to the sweep its top bottleneck implicates.
    Raises ValueError when the verdict abstained, the experiment has no
    sweepable harness, or no registry knob applies — the caller decides
    whether to fall back to :func:`exploratory_spec` or stop."""
    cause, experiment = _top_bottleneck(verdict)
    if cause is None:
        raise ValueError("verdict has no bottleneck to tune for")
    if not experiment:
        experiment = _doctor.suggest_spec(cause, pipelined)
    harness = experiment.get("harness", "")
    if harness not in HARNESSES:
        raise ValueError(
            f"experiment {experiment.get('experiment_id')!r} for cause "
            f"{cause!r} has no sweepable harness ({harness!r})")
    fn_name, default_metric, direction = HARNESSES[harness]
    knobs = tuple(n for n in experiment.get("knobs", ())
                  if n in _space.KNOBS
                  and _space.knob_applies(_space.KNOBS[n], fn_name))
    if not knobs:
        raise ValueError(
            f"experiment {experiment.get('experiment_id')!r} for cause "
            f"{cause!r} implicates no sweepable registry knob")
    return SweepSpec(experiment_id=experiment["experiment_id"],
                     cause=cause, knobs=knobs, harness=harness,
                     metric=metric or default_metric,
                     direction=direction)


def exploratory_spec(harness: str = "ingest_sweep",
                     knobs: tuple = ("batch.coalesce_ms",
                                     "raft.pipeline_window"),
                     metric: str | None = None) -> SweepSpec:
    """The no-verdict fallback: a default exploratory sweep over broadly
    load-bearing knobs, for runs whose doctor honestly abstained."""
    fn_name, default_metric, direction = HARNESSES[harness]
    usable = tuple(n for n in knobs
                   if _space.knob_applies(_space.KNOBS[n], fn_name))
    return SweepSpec(experiment_id="explore_defaults", cause=None,
                     knobs=usable, harness=harness,
                     metric=metric or default_metric, direction=direction)


# ---------------------------------------------------------------------------
# The gate.
# ---------------------------------------------------------------------------


def gate_candidate(incumbent: dict, candidate: dict,
                   policy: dict | None = None) -> dict:
    """``perfdoctor --gate`` semantics between two metric dicts: run the
    doctor's own ``gate`` over a two-record trajectory under the merged
    policy, then split the verdict into banded (soft) regressions and
    hard vetoes — equal-direction flag flips, the HARD_GATE_FLAGS not
    covered by the policy, and candidate crashes."""
    merged = dict(_doctor.DEFAULT_POLICY)
    merged.update(policy or {})
    verdict = _doctor.gate(
        [{"kind": "candidate", "source": "incumbent",
          "metrics": incumbent},
         {"kind": "candidate", "source": "candidate",
          "metrics": candidate}], merged)
    hard = [r for r in verdict["regressions"]
            if r.get("direction") == "equal"]
    soft = [r for r in verdict["regressions"]
            if r.get("direction") != "equal"]
    for flag in HARD_GATE_FLAGS:
        if flag in merged:
            continue  # already judged by the policy pass above
        if incumbent.get(flag) is True and candidate.get(flag) is False:
            hard.append({"metric": flag, "prev": True, "new": False,
                         "direction": "equal",
                         "detail": "flag flipped false"})
    if candidate.get("error"):
        hard.append({"metric": "candidate_error",
                     "detail": str(candidate["error"])})
    return {"ok": not (hard or soft),
            "soft_regressions": soft, "hard_vetoes": hard}


# ---------------------------------------------------------------------------
# The deterministic seeded search.
# ---------------------------------------------------------------------------


def _lcg(seed: int):
    """glibc-constant LCG — the ONLY randomness the decision path sees,
    fully determined by the seed so a run replays."""
    state = int(seed) & 0x7FFFFFFF
    while True:
        state = (1103515245 * state + 12345) % (1 << 31)
        yield state


def _fingerprint(values: dict) -> str:
    return json.dumps(values, sort_keys=True)


def _value_of(metrics: dict, metric: str):
    v = metrics.get(metric)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def reset_between_candidates(*targets) -> None:
    """Bust cross-candidate measurement state: call ``reset_window()``
    on every target that has one (SidecarVerifier's 5 s server-stats
    cache, SidecarServer's adaptive-coalesce window). Back-to-back
    short candidates would otherwise read the previous candidate's
    stats and adapted window."""
    for t in targets:
        fn = getattr(t, "reset_window", None)
        if callable(fn):
            fn()


def run_autotune(spec: SweepSpec, runner, *, budget: int = DEFAULT_BUDGET,
                 seed: int = 0, policy: dict | None = None,
                 baseline_values: dict | None = None,
                 baseline_metrics: dict | None = None,
                 reset=None, verdict_consumed: dict | None = None) -> dict:
    """The closed loop: measure the incumbent (hand-tuned defaults),
    hill-climb the spec's knobs under the gate, return the full
    provenance record. ``runner(values) -> metrics dict`` is the only
    side-effecting call; ``reset()`` (if given) runs before every
    measurement so candidates never read each other's stats."""
    values = _space.default_values(spec.knobs)
    if baseline_values:
        values.update({k: v for k, v in baseline_values.items()
                       if k in values})

    def measure(vals: dict) -> dict:
        if reset is not None:
            reset()
        try:
            metrics = runner(dict(vals))
        except Exception as exc:
            # Candidate crash is isolated: the failure becomes a
            # hard-vetoed candidate record, never a dead search.
            return {"error": f"{type(exc).__name__}: {exc}"}
        if not isinstance(metrics, dict):
            return {"error": f"runner returned {type(metrics).__name__}"}
        return metrics

    rng = _lcg(seed)
    order = list(spec.knobs)
    if order:
        rot = next(rng) % len(order)
        order = order[rot:] + order[:rot]
    directions = {name: ("up" if next(rng) & 1 else "down")
                  for name in order}

    if baseline_metrics is None:
        baseline_metrics = measure(values)
        _tm.inc("autotune_candidates_total")
    best_values = dict(values)
    best_metrics = baseline_metrics
    candidates = [{"id": 0, "role": "incumbent", "knob": None,
                   "values": dict(values), "metrics": baseline_metrics,
                   "gate": None, "accepted": True}]
    decisions: list = []
    tried = {_fingerprint(values)}
    gate_rejections = 0
    exhausted: set = set()

    def propose(name: str):
        knob = _space.KNOBS[name]
        cur = best_values[name]
        first = directions[name]
        for d in (first, "down" if first == "up" else "up"):
            step = _space.step_up if d == "up" else _space.step_down
            nxt = step(knob, cur)
            if nxt is None:
                continue
            cand = dict(best_values)
            cand[name] = nxt
            if _fingerprint(cand) in tried:
                continue
            return d, cur, nxt, cand
        return None

    def better(metrics: dict) -> bool:
        new = _value_of(metrics, spec.metric)
        cur = _value_of(best_metrics, spec.metric)
        if new is None:
            return False
        if cur is None:
            return True
        return new > cur if spec.direction == "higher" else new < cur

    cid = 0
    ki = 0
    while cid < budget and order and len(exhausted) < len(order):
        name = order[ki % len(order)]
        ki += 1
        if name in exhausted:
            continue
        move = propose(name)
        if move is None:
            exhausted.add(name)
            continue
        direction, cur, nxt, cand_values = move
        cid += 1
        tried.add(_fingerprint(cand_values))
        metrics = measure(cand_values)
        _tm.inc("autotune_candidates_total")
        verdict = gate_candidate(baseline_metrics, metrics, policy)
        improves = better(metrics)
        accepted = bool(verdict["ok"] and improves)
        if not verdict["ok"]:
            gate_rejections += 1
            _tm.inc("autotune_gate_rejections_total")
        candidates.append({"id": cid, "role": "candidate", "knob": name,
                           "from": cur, "to": nxt,
                           "values": dict(cand_values),
                           "metrics": metrics, "gate": verdict,
                           "accepted": accepted})
        decisions.append(
            f"{name}:{cur:g}->{nxt:g}:"
            f"{'accept' if accepted else 'reject'}")
        if accepted:
            best_values = cand_values
            best_metrics = metrics
            # A better incumbent re-opens neighbours everywhere.
            exhausted.clear()
        else:
            # Blocked uphill: prefer the other direction next visit.
            directions[name] = ("down" if direction == "up" else "up")

    base_value = _value_of(baseline_metrics, spec.metric)
    best_value = _value_of(best_metrics, spec.metric)
    improved = best_metrics is not baseline_metrics
    improvement_pct = None
    if improved and base_value and best_value is not None:
        improvement_pct = round(
            (best_value - base_value) / base_value * 100.0, 2)
    changed = {k: v for k, v in best_values.items() if v != values[k]}
    result = {
        "autotune_schema": AUTOTUNE_SCHEMA,
        "experiment_id": spec.experiment_id,
        "cause": spec.cause,
        "harness": spec.harness,
        "metric": spec.metric,
        "direction": spec.direction,
        "seed": int(seed),
        "budget": int(budget),
        "knobs": list(spec.knobs),
        "verdict_consumed": verdict_consumed,
        "incumbent": {"values": values, "metrics": baseline_metrics},
        "candidates": candidates,
        "candidates_evaluated": cid,
        "gate_rejections": gate_rejections,
        "best": {"values": best_values, "metrics": best_metrics},
        "baseline_value": base_value,
        "best_value": best_value if best_value is not None else base_value,
        "improved": improved,
        "improvement_pct": improvement_pct,
        "decision_sequence": decisions,
        "committed": improved,
        "overlay": None,
    }
    if improved:
        result["overlay"] = {
            "values": changed,
            "toml": _space.overlay_toml(changed),
            "env": _space.env_for(changed),
            "harness_kwargs": _space.harness_kwargs_for(
                changed, HARNESSES[spec.harness][0]),
        }
    return result


# ---------------------------------------------------------------------------
# Runners.
# ---------------------------------------------------------------------------


def make_mock_runner(spec: SweepSpec, curve: str = "monotone",
                     base: float = 1000.0):
    """Deterministic knob-response surfaces for tests and ``--mock``:
    the metric is a pure function of the candidate values (position of
    each knob inside its bounds), so replays are exact.

      monotone    value rises with every knob raised
      regressing  value falls with every knob raised
      noisy       monotone plus deterministic hash jitter
      cliff       value rises BUT any knob above its default flips the
                  exactly-once flag False (the hard-gate fixture)
    """
    knobs = [_space.KNOBS[n] for n in spec.knobs]
    if curve not in ("monotone", "regressing", "noisy", "cliff"):
        raise ValueError(f"unknown mock curve {curve!r}")

    def position(vals: dict) -> float:
        total = 0.0
        for k in knobs:
            span = k.hi - k.lo
            total += ((float(vals[k.name]) - k.lo) / span) if span else 0.0
        return total / len(knobs) if knobs else 0.0

    def runner(vals: dict) -> dict:
        pos = position(vals)
        once = True
        if curve == "monotone":
            value = base * (1.0 + 0.8 * pos)
        elif curve == "regressing":
            value = base * max(0.05, 1.0 - 0.8 * pos)
        elif curve == "noisy":
            jitter = (zlib.crc32(_fingerprint(vals).encode())
                      % 1000) / 1000.0
            value = base * (1.0 + 0.8 * pos + 0.05 * (jitter - 0.5))
        else:  # cliff
            value = base * (1.0 + 0.8 * pos)
            once = all(float(vals[k.name]) <= k.default for k in knobs)
        return {spec.metric: round(value, 3),
                "p99_ms": round(50.0 * base / max(value, 1e-9), 3),
                "exactly_once_all": once}

    return runner


def make_ingest_runner(*, rates=(2400.0,), n_tx: int = 400, width: int = 1,
                       workers: int = 2, notary: str = "simple",
                       max_seconds: float = 240.0):
    """The real thing: each candidate runs a small multiprocess ingest
    sweep. Config-target knobs travel to every spawned node as ONE
    ``CORDA_TPU_CONFIG_OVERLAY`` env (merged over node.toml by
    ``NodeConfig.load``), env-target knobs as their own vars, harness
    knobs as loadtest kwargs — then the env is restored so candidates
    never leak into each other or the caller. Only values that MOVED
    from the hand-tuned defaults ship: the incumbent runs overlay-free
    (it IS the default config), and a default is not always a no-op to
    restate (a [notary_shards] section enables sharding even at the
    default count)."""
    from ..tools import loadtest as _loadtest

    def runner(vals: dict) -> dict:
        vals = _space.changed_values(vals)
        overlay = _space.overlay_for(vals)
        env_vars = _space.env_for(vals)
        if overlay:
            env_vars["CORDA_TPU_CONFIG_OVERLAY"] = json.dumps(
                overlay, sort_keys=True)
        saved = {k: os.environ.get(k) for k in env_vars}
        os.environ.update(env_vars)
        try:
            sweep = _loadtest.run_ingest_sweep(
                rates=tuple(rates), n_tx=n_tx, width=width,
                workers=workers, notary=notary, max_seconds=max_seconds,
                **_space.harness_kwargs_for(vals, "run_ingest_sweep"))
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        rows = [r for r in sweep.results.values()
                if isinstance(r, dict) and "error" not in r]
        if not rows:
            return {"error": "every offered rate failed"}
        peak = max(rows, key=lambda r: r.get("achieved_tx_s") or 0.0)
        return {
            "peak_achieved_tx_s": peak.get("achieved_tx_s"),
            "p99_ms": peak.get("p99_ms"),
            "exactly_once_all": all(bool(r.get("exactly_once"))
                                    for r in rows),
            "first_bottleneck": sweep.first_bottleneck,
        }

    return runner
