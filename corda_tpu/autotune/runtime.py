"""The autotune runtime leg: an opt-in, bounded controller thread that
feeds live telemetry ``round_breakdown`` deltas into the adaptive
policies that already exist — never a new optimizer in the hot path.

The contract, in order of importance:

  * **Off by default, bit-identical when disarmed.** A tuner built with
    ``armed=False`` (the default) constructs no thread, calls no
    snapshot function, touches no target — ``start()`` returns None and
    ``step()`` is a no-op. The running system with the leg disarmed is
    the running system without this module.
  * **Hard revert-on-regression.** The tuner scores each window as
    committed rounds per second of attributed wall (from the telemetry
    round profiler's snapshot deltas). A window that drops more than
    ``guard_pct`` below the best score seen counts one strike; after
    ``hysteresis`` consecutive strikes every target's ``revert()`` runs
    once, ``autotune_reverts_total`` increments, and the tuner latches
    disarmed — one bad tune never oscillates.
  * **Bounded.** The thread ticks at a fixed interval, stops on
    ``stop()`` or after the optional ``max_steps``, and only ever calls
    the injected targets — it owns no knob of its own.

Targets wrap the existing adaptive policies: :func:`coalesce_target`
rides the sidecar's own ``adaptive_coalesce`` window (PR 7) and reverts
by restoring the configured window via ``reset_window()``;
:func:`admission_target` applies a fresh ``calibrate_admission``
calibration to a live AdmissionController (PR 10) and reverts by
restoring the rates it saw at arm time. The clock is injected for
deterministic tests.
"""

from __future__ import annotations

import threading
import time

from ..obs import telemetry as _tm

__all__ = [
    "AdaptiveTarget",
    "RuntimeTuner",
    "admission_target",
    "coalesce_target",
]


class AdaptiveTarget:
    """One revert-able lever: ``observe(delta)`` feeds a window's
    breakdown delta into the underlying adaptive policy; ``revert()``
    restores the pre-arm state. Both injected so the tuner never knows
    subsystem internals."""

    def __init__(self, name: str, observe=None, revert=None):
        self.name = name
        self._observe = observe
        self._revert = revert

    def observe(self, delta: dict) -> None:
        if self._observe is not None:
            self._observe(delta)

    def revert(self) -> None:
        if self._revert is not None:
            self._revert()


def coalesce_target(server) -> AdaptiveTarget:
    """The sidecar's adaptive-coalesce policy already observes its own
    batches; the runtime leg's job is the guardrail — revert restores
    the configured window and zeroes the adaptation state."""
    return AdaptiveTarget("sidecar.adaptive_coalesce",
                          revert=server.reset_window)


def admission_target(controller, calibration: dict | None = None) -> AdaptiveTarget:
    """Apply a measured-saturation calibration (qos/calibrate) to a live
    AdmissionController once at arm time; revert restores the rates the
    controller carried before."""
    from ..qos import calibrate as _calibrate

    saved = controller.stats()

    def observe(_delta: dict) -> None:
        if calibration:
            _calibrate.apply_calibration(controller, calibration)

    def revert() -> None:
        controller.reconfigure(
            interactive_rate=saved.get("interactive_rate"),
            bulk_rate=saved.get("bulk_rate"),
            queue_watermark=saved.get("queue_watermark"))

    return AdaptiveTarget("qos.admission", observe=observe, revert=revert)


class RuntimeTuner:
    """The bounded loop. ``snapshot_fn() -> {"rounds": int, "wall_s":
    float}`` (telemetry round-profiler totals); scoring and the revert
    guard work on per-window DELTAS of that snapshot."""

    def __init__(self, snapshot_fn, targets=(), *, interval_s: float = 5.0,
                 guard_pct: float = 25.0, hysteresis: int = 2,
                 armed: bool = False, max_steps: int | None = None,
                 clock=time.monotonic):
        self.armed = bool(armed)
        self.reverted = False
        self.steps = 0
        self._snapshot_fn = snapshot_fn
        self._targets = tuple(targets)
        self._interval_s = float(interval_s)
        self._guard_pct = float(guard_pct)
        self._hysteresis = max(1, int(hysteresis))
        self._max_steps = max_steps
        self._clock = clock
        self._thread = None
        self._stop = threading.Event()
        self._last_snapshot = None
        self._best_score = None
        self._strikes = 0

    def start(self):
        """Spawn the tick thread — only when armed; disarmed start is a
        no-op returning None (the bit-identity contract)."""
        if not self.armed or self._thread is not None:
            return None
        self._thread = threading.Thread(
            target=self._run, name="autotune-runtime", daemon=True)
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval_s + 1.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self._interval_s):
            self.step()
            if self.reverted or (self._max_steps is not None
                                 and self.steps >= self._max_steps):
                return

    def step(self) -> str:
        """One control tick (the thread calls this; tests call it
        directly). Returns what the tick did: "disarmed", "idle",
        "observed", or "reverted"."""
        if not self.armed or self.reverted:
            return "disarmed"
        snap = self._snapshot_fn() or {}
        last = self._last_snapshot
        self._last_snapshot = dict(snap)
        self.steps += 1
        if last is None:
            return "idle"
        rounds = (snap.get("rounds") or 0) - (last.get("rounds") or 0)
        wall = (snap.get("wall_s") or 0.0) - (last.get("wall_s") or 0.0)
        if rounds <= 0 or wall <= 0:
            return "idle"
        delta = {"rounds": rounds, "wall_s": wall}
        for target in self._targets:
            target.observe(delta)
        score = rounds / wall
        if self._best_score is None or score > self._best_score:
            self._best_score = score
            self._strikes = 0
            return "observed"
        if score < self._best_score * (1.0 - self._guard_pct / 100.0):
            self._strikes += 1
            if self._strikes >= self._hysteresis:
                for target in self._targets:
                    target.revert()
                _tm.inc("autotune_reverts_total")
                self.reverted = True
                self.armed = False
                return "reverted"
        else:
            self._strikes = 0
        return "observed"
