"""The autotune knob registry: a typed, bounded parameter space over the
config keys that already exist in this tree.

Every :class:`Knob` names a real lever — a ``section.key`` in
``node/config.py``, a loadtest-harness kwarg, or a documented env var —
plus its bounds, step rule, default, and the doctor cause(s) that
implicate it (the causes mirror ``obs/doctor.RULE_SPECS``; the
cross-reference is validated both ways). The controller never invents a
knob: a sweep spec is a subset of THIS registry, so every candidate it
tries is a config a human could have written by hand.

:func:`validate_registry` is the analyzer-style drift guard: every
config-target knob must resolve to a live dataclass field of
``node/config.py``, every harness-target knob to a real keyword of the
named ``tools/loadtest.py`` function, and every env-target knob's
variable name must appear in the source of the module that reads it.
A registry entry that stops resolving fails the test suite, exactly like
a stale stage name fails the trace-stage-registry rule.

Stdlib-only; imports of config/loadtest happen inside the validator so
the registry itself stays importable from bare tool processes.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = [
    "KNOBS",
    "Knob",
    "changed_values",
    "default_values",
    "env_for",
    "harness_kwargs_for",
    "knob_applies",
    "knobs_for",
    "neighbors",
    "overlay_for",
    "overlay_toml",
    "step_down",
    "step_up",
    "validate_registry",
]

# Target kinds: where a knob's value lands when a candidate runs.
#   config:<section>.<key>   -> CORDA_TPU_CONFIG_OVERLAY entry
#   harness:<func>:<kwarg>   -> keyword of a tools/loadtest.py harness
#   env:<VAR>:<module>       -> env var read by <module>
_CONFIG, _HARNESS, _ENV = "config", "harness", "env"


@dataclass(frozen=True)
class Knob:
    """One bounded lever. ``step`` is multiplicative when ``step_mode``
    is "mul" (doubling walks a log-scale space in few trials) and
    additive when "add"; ``seed`` is the first non-zero value a "mul"
    step proposes when the current value is 0 (0 * 2 goes nowhere)."""

    name: str               # registry key, e.g. "raft.pipeline_window"
    target: str             # "config:raft.pipeline_window", see above
    kind: str               # "int" | "float"
    lo: float
    hi: float
    step: float
    step_mode: str          # "mul" | "add"
    default: float
    causes: tuple           # doctor causes implicating this knob
    seed: float = 0.0       # mul-from-zero seed (0 = unused)


KNOBS: dict = {k.name: k for k in (
    # Verify plane: the sidecar accumulation window (harness-level knob —
    # the sweep passes it to the sidecar argv via run_slo_sweep) and the
    # device-routing floor (env knob read by node/verify_client.py).
    Knob("sidecar.coalesce_us", "harness:run_slo_sweep:sidecar_coalesce_us",
         "int", 0, 20_000, 2.0, "mul", 2000,
         ("device_occupancy", "verify", "verify_wait"), seed=250),
    Knob("batch.device_min_sigs",
         "env:CORDA_TPU_SIDECAR_MIN_SIGS:corda_tpu.node.verify_client",
         "int", 1, 4096, 2.0, "mul", 16,
         ("device_occupancy", "pad_fraction", "verify")),
    # Batch/verify config ([batch] in node.toml).
    Knob("batch.coalesce_ms", "config:batch.coalesce_ms",
         "float", 0.0, 10.0, 2.0, "mul", 0.0,
         ("rounds", "poll", "seal", "fsync"), seed=0.5),
    Knob("batch.max_sigs", "config:batch.max_sigs",
         "int", 256, 16_384, 2.0, "mul", 4096,
         ("pad_fraction",)),
    Knob("batch.async_depth", "config:batch.async_depth",
         "int", 1, 16, 2.0, "mul", 2,
         ("verify_wait",)),
    # Raft commit plane ([raft]).
    Knob("raft.pipeline_window", "config:raft.pipeline_window",
         "int", 64, 8192, 2.0, "mul", 1024,
         ("replicate",)),
    Knob("raft.append_chunk", "config:raft.append_chunk",
         "int", 32, 2048, 2.0, "mul", 256,
         ("replicate", "seal")),
    Knob("raft.apply_queue_depth", "config:raft.apply_queue_depth",
         "int", 256, 65_536, 2.0, "mul", 4096,
         ("apply", "rounds")),
    # Admission ([qos]) — the calibrate_admission levers.
    Knob("qos.interactive_rate", "config:qos.interactive_rate",
         "float", 0.0, 1e6, 2.0, "mul", 0.0,
         ("admission",), seed=100.0),
    Knob("qos.bulk_rate", "config:qos.bulk_rate",
         "float", 0.0, 1e6, 2.0, "mul", 0.0,
         ("admission",), seed=100.0),
    Knob("qos.queue_watermark", "config:qos.queue_watermark",
         "int", 0, 8192, 2.0, "mul", 0,
         ("admission",), seed=64),
    # Sharded notary ([notary_shards]).
    Knob("notary_shards.count", "config:notary_shards.count",
         "int", 1, 4, 2.0, "mul", 1,
         ("rounds",)),
    # Vault engine ([vault]) — a boolean lever walked as 0/1: arming it
    # swaps the in-memory vault for the sqlite indexed engine when the
    # doctor's vault_scan rule fires.
    Knob("vault.indexed", "config:vault.indexed",
         "int", 0, 1, 1.0, "add", 0,
         ("vault_scan",)),
)}


def _quantize(knob: Knob, value: float) -> float:
    value = min(knob.hi, max(knob.lo, value))
    if knob.kind == "int":
        return int(round(value))
    return round(float(value), 6)


def step_up(knob: Knob, value: float):
    """The next larger candidate value, or None at the upper bound."""
    if knob.step_mode == "mul":
        nxt = knob.seed if (value == 0 and knob.seed) else value * knob.step
    else:
        nxt = value + knob.step
    nxt = _quantize(knob, nxt)
    return nxt if nxt > value else None


def step_down(knob: Knob, value: float):
    """The next smaller candidate value, or None at the lower bound
    (a "mul" knob seeded from zero steps back down to zero)."""
    if knob.step_mode == "mul":
        nxt = 0.0 if (knob.seed and value <= knob.seed) else \
            value / knob.step
    else:
        nxt = value - knob.step
    nxt = _quantize(knob, nxt)
    return nxt if nxt < value else None


def neighbors(knob: Knob, value: float) -> tuple:
    """(up, down) candidates around ``value``, Nones dropped."""
    return tuple(v for v in (step_up(knob, value), step_down(knob, value))
                 if v is not None)


def knobs_for(cause: str) -> tuple:
    """Registry knobs a doctor cause implicates, in registry order."""
    return tuple(k for k in KNOBS.values() if cause in k.causes)


def knob_applies(knob: Knob, harness_fn: str) -> bool:
    """Whether a knob can reach a run measured by ``harness_fn``:
    config/env knobs reach every spawned process (overlay env / env
    var); a harness-target knob only applies to its own function."""
    kind, _, rest = knob.target.partition(":")
    if kind != _HARNESS:
        return True
    return rest.split(":", 1)[0] == harness_fn


def default_values(names) -> dict:
    """name -> hand-tuned default for a knob subset (the incumbent)."""
    return {n: KNOBS[n].default for n in names}


def changed_values(values: dict) -> dict:
    """The subset of ``values`` that differs from the hand-tuned
    defaults — what a candidate actually ships. Shipping a default is
    not always a no-op (a ``[notary_shards]`` section with the default
    count still ENABLES sharding on a node that had none), so the
    incumbent must travel with no overlay at all."""
    return {n: v for n, v in values.items() if v != KNOBS[n].default}


def overlay_for(values: dict) -> dict:
    """The nested config dict for the config-target knobs in ``values``
    — the ``CORDA_TPU_CONFIG_OVERLAY`` payload. Non-config knobs
    (harness/env targets) are skipped; they travel by other roads."""
    out: dict = {}
    for name, value in sorted(values.items()):
        knob = KNOBS[name]
        kind, _, rest = knob.target.partition(":")
        if kind != _CONFIG:
            continue
        section, key = rest.split(".", 1)
        out.setdefault(section, {})[key] = value
    return out


def overlay_toml(values: dict) -> str:
    """The committed-config rendering: the same overlay as TOML text an
    operator can drop next to node.toml (or paste into it)."""
    lines = []
    for section, keys in sorted(overlay_for(values).items()):
        lines.append(f"[{section}]")
        for key, value in sorted(keys.items()):
            if isinstance(value, bool):
                rendered = "true" if value else "false"
            else:
                rendered = repr(value)
            lines.append(f"{key} = {rendered}")
        lines.append("")
    return "\n".join(lines)


def env_for(values: dict) -> dict:
    """Env-var assignments for the env-target knobs in ``values``."""
    out = {}
    for name, value in sorted(values.items()):
        knob = KNOBS[name]
        kind, _, rest = knob.target.partition(":")
        if kind == _ENV:
            var = rest.split(":", 1)[0]
            out[var] = str(value)
    return out


def harness_kwargs_for(values: dict, func_name: str) -> dict:
    """Keyword overrides for harness-target knobs bound to ``func_name``."""
    out = {}
    for name, value in sorted(values.items()):
        knob = KNOBS[name]
        kind, _, rest = knob.target.partition(":")
        if kind == _HARNESS:
            fn, kwarg = rest.split(":", 1)
            if fn == func_name:
                out[kwarg] = value
    return out


# ---------------------------------------------------------------------------
# Drift validation (analyzer-style: run by the test suite, importable by
# the CLI's --validate).
# ---------------------------------------------------------------------------


def _config_sections() -> dict:
    """section name -> dataclass type, from node/config.py itself."""
    from ..node import config as _config
    return {
        "batch": _config.BatchConfig,
        "raft": _config.RaftConfig,
        "qos": _config.QosConfig,
        "durability": _config.DurabilityConfig,
        "notary_shards": _config.ShardConfig,
        "vault": _config.VaultConfig,
    }


def validate_registry() -> list:
    """Every registry entry must resolve to a live lever; every doctor
    rule-spec knob must resolve to a registry entry. Returns the list of
    violations (empty = the space matches the tree)."""
    import dataclasses
    import importlib
    import inspect

    errors = []
    sections = _config_sections()
    for knob in KNOBS.values():
        kind, _, rest = knob.target.partition(":")
        if kind == _CONFIG:
            section, _, key = rest.partition(".")
            cls = sections.get(section)
            if cls is None:
                errors.append(f"{knob.name}: unknown config section "
                              f"[{section}]")
            elif key not in {f.name for f in dataclasses.fields(cls)}:
                errors.append(f"{knob.name}: no field {key!r} on "
                              f"{cls.__name__}")
        elif kind == _HARNESS:
            fn_name, _, kwarg = rest.partition(":")
            from ..tools import loadtest as _loadtest
            fn = getattr(_loadtest, fn_name, None)
            if fn is None:
                errors.append(f"{knob.name}: no harness "
                              f"loadtest.{fn_name}")
            elif kwarg not in inspect.signature(fn).parameters:
                errors.append(f"{knob.name}: loadtest.{fn_name} has no "
                              f"kwarg {kwarg!r}")
        elif kind == _ENV:
            var, _, module = rest.partition(":")
            try:
                src = inspect.getsource(importlib.import_module(module))
            except (ImportError, OSError):
                errors.append(f"{knob.name}: cannot read source of "
                              f"{module}")
                continue
            if var not in src:
                errors.append(f"{knob.name}: env var {var} not read by "
                              f"{module}")
        else:
            errors.append(f"{knob.name}: unknown target kind {kind!r}")
        if not (knob.lo <= knob.default <= knob.hi):
            errors.append(f"{knob.name}: default {knob.default} outside "
                          f"[{knob.lo}, {knob.hi}]")
        if knob.step_mode not in ("mul", "add"):
            errors.append(f"{knob.name}: bad step_mode {knob.step_mode!r}")

    # The doctor's structured specs must stay a subset of this registry.
    from ..obs import doctor as _doctor
    for table_name in ("RULE_SPECS", "PIPELINED_RULE_SPECS"):
        table = getattr(_doctor, table_name)
        for cause, spec in table.items():
            for name in spec.get("knobs", ()):
                if name not in KNOBS:
                    errors.append(f"doctor.{table_name}[{cause!r}] names "
                                  f"unknown knob {name!r}")
    return errors
