"""L1 ledger data model: states, contracts, commands, transactions-for-contract."""

from .structures import (  # noqa: F401
    Attachment,
    AuthenticatedObject,
    Command,
    CommandData,
    ContractState,
    Contract,
    DealState,
    FungibleAsset,
    IssueCommand,
    Issued,
    LinearState,
    MoveCommand,
    OwnableState,
    SchedulableState,
    StateAndRef,
    StateRef,
    Timestamp,
    TransactionState,
    TypeOnlyCommandData,
    UniqueIdentifier,
)
from .verification import (  # noqa: F401
    InOutGroup,
    TransactionForContract,
    TransactionVerificationException,
    ContractRejection,
    MoreThanOneNotary,
    NotaryChangeInWrongTransactionType,
    SignersMissing,
    InvalidNotaryChange,
    TransactionMissingEncumbranceException,
    TransactionResolutionException,
)
from .dsl import require_that, RequirementFailed  # noqa: F401
