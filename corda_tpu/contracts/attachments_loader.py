"""Loading contract code from attachments, vetted before execution.

Capability match for the reference's AttachmentsClassLoader (reference:
core/src/main/kotlin/net/corda/core/node/AttachmentsClassLoader.kt:23-103):
contract logic ships *on the ledger* as content-addressed attachment
archives, and a node verifying a transaction materialises the contract
classes from those attachments rather than from its own install. The
reference scans every attachment JAR up front, rejects overlapping file
paths (case-insensitively — OverlappingAttachments), serves classes and
resources only from the scanned set, and notes its future direction is a
sandboxing classloader ("defence in depth").

Python form: an attachment is a zip of ``.py`` sources + resources. The
loader scans all archives with the same overlap rule, and *imports are
closed over the attachment set*: a module executes with a private
``__import__`` that resolves sibling modules from the attachments and only
lets whitelisted platform modules through. Every module's code is statically
vetted by the DeterministicSandbox **before** it is executed — the
"sandboxing classloader" the reference left as a TODO — so attachment code
gets the same determinism guarantees as any sandboxed contract, at load time
rather than first call.
"""

from __future__ import annotations

import builtins as _builtins
import io
import types
import zipfile

from .sandbox import (
    ALLOWED_BUILTINS,
    DEFAULT_MODULE_WHITELIST,
    DeterministicSandbox,
    SandboxViolation,
    _EXCEPTION_NAMES,
)
from .structures import Attachment, Contract


class OverlappingAttachments(Exception):
    """Two attachments define the same (case-folded) path
    (AttachmentsClassLoader.kt:27-29)."""

    def __init__(self, path: str):
        super().__init__(f"Multiple attachments define a file at path {path}")
        self.path = path


class AttachmentsModuleLoader:
    """Loads Python modules and resources from a set of attachments
    (AttachmentsClassLoader.kt findClass/findResource/getResourceAsStream)."""

    def __init__(self, attachments: list[Attachment],
                 sandbox: DeterministicSandbox | None = None):
        self._paths: dict[str, bytes] = {}
        self._modules: dict[str, types.ModuleType] = {}
        self._loading: set[str] = set()
        for attachment in attachments:
            archive = zipfile.ZipFile(io.BytesIO(attachment.open()))
            for info in archive.infolist():
                if info.is_dir():
                    continue
                # Reject case-only and separator-only variants, exactly as
                # the reference does for Windows/Mac developer filesystems.
                path = info.filename.lower().replace("\\", "/")
                if path in self._paths:
                    raise OverlappingAttachments(path)
                self._paths[path] = archive.read(info)
        module_names = tuple(
            p[:-3].replace("/", ".") for p in self._paths if p.endswith(".py"))
        # The *platform* whitelist is what real imports may fall through to;
        # attachment names extend only the vetting whitelist. Keeping the two
        # separate stops a hostile attachment from whitelisting a host
        # package by shipping a same-named stub (e.g. an empty os.py plus
        # `from os.path import ...`).
        self._platform_whitelist = (
            sandbox.module_whitelist if sandbox else DEFAULT_MODULE_WHITELIST)
        self._sandbox = sandbox or DeterministicSandbox(
            module_whitelist=DEFAULT_MODULE_WHITELIST + module_names)

    # ------------------------------------------------------------- modules

    def load_module(self, name: str) -> types.ModuleType:
        """Import a module from the attachment set (findClass:68-84). The
        source is sandbox-vetted before exec; unknown names raise
        ModuleNotFoundError (the reference's ClassNotFoundException)."""
        if name in self._modules:
            return self._modules[name]
        path = name.replace(".", "/").lower() + ".py"
        source = self._paths.get(path)
        if source is None:
            raise ModuleNotFoundError(f"{name} is not in the attachments")
        if name in self._loading:
            raise ImportError(f"circular attachment import: {name}")
        self._loading.add(name)
        try:
            code = compile(source, f"attachment://{path}", "exec")
            self._sandbox._vet_code(code, {})
            module = types.ModuleType(name)
            module.__dict__["__builtins__"] = self._restricted_builtins()
            self._modules[name] = module
            try:
                exec(code, module.__dict__)
            except BaseException:
                del self._modules[name]
                raise
            return module
        finally:
            self._loading.discard(name)

    def _restricted_builtins(self) -> dict:
        """Builtins for attachment modules: ONLY the sandbox-allowed names
        plus exception types and class-machinery hooks — not the real
        builtins dict. Static vetting is the first line of defence; this is
        the second, so that even a dynamically-reached ``__builtins__``
        subscript yields nothing beyond the whitelist. ``__import__`` is the
        shim that resolves sibling modules from the attachment set and only
        lets *platform*-whitelisted modules through."""
        loader = self

        def attachment_import(name, globals=None, locals=None, fromlist=(),
                              level=0):
            if level != 0:
                raise SandboxViolation(
                    "relative imports are not supported in attachments")
            if name.replace(".", "/").lower() + ".py" in loader._paths:
                if "." in name and not fromlist:
                    # `import a.b` binds the root name; keep the namespace
                    # model flat instead of emulating package machinery.
                    raise SandboxViolation(
                        f"use 'from {name} import ...' for dotted "
                        "attachment modules")
                return loader.load_module(name)
            if not any(name == w or name.startswith(w + ".")
                       for w in loader._platform_whitelist):
                raise SandboxViolation(
                    f"attachment import of non-whitelisted module {name!r}")
            return _builtins.__import__(name, globals, locals, fromlist,
                                        level)

        b = {name: getattr(_builtins, name)
             for name in (ALLOWED_BUILTINS | _EXCEPTION_NAMES)
             if hasattr(_builtins, name)}
        b["__build_class__"] = _builtins.__build_class__
        b["__name__"] = "attachment"
        b["None"] = None
        b["True"] = True
        b["False"] = False
        b["NotImplemented"] = NotImplemented
        b["__import__"] = attachment_import
        return b

    # ----------------------------------------------------------- resources

    def get_resource(self, path: str) -> bytes:
        """Raw file bytes from the attachment set (findResource /
        getResourceAsStream). KeyError if absent."""
        return self._paths[path.lower().replace("\\", "/")]

    # ----------------------------------------------------------- contracts

    def load_contract(self, qualified_name: str) -> Contract:
        """'module.ClassName' -> a vetted Contract instance, ready for
        sandboxed verification (the AttachmentsClassLoader + ContractExecutor
        composition)."""
        module_name, _, cls_name = qualified_name.rpartition(".")
        module = self.load_module(module_name)
        cls = getattr(module, cls_name, None)
        if not (isinstance(cls, type) and issubclass(cls, Contract)):
            raise TypeError(f"{qualified_name} is not a Contract")
        contract = cls()
        self._sandbox.vet_contract(contract)
        return contract


def make_attachment_zip(files: dict[str, bytes]) -> bytes:
    """Helper (used by tests and tooling): path -> content mapping to a
    deterministic zip blob suitable for attachment storage."""
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for path in sorted(files):
            info = zipfile.ZipInfo(path, date_time=(1980, 1, 1, 0, 0, 0))
            z.writestr(info, files[path])
    return buf.getvalue()
