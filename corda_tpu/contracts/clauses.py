"""The clause framework: composable contract verification.

Capability match for the reference's clause machinery (reference:
core/src/main/kotlin/net/corda/core/contracts/clauses/Clause.kt,
GroupClauseVerifier.kt, AllComposition/AnyComposition/FirstComposition —
which Cash/CommercialPaper/Obligation are built from in finance/): a clause
declares the commands it needs and a verify step; compositions combine
clauses; a group verifier fans a transaction's state groups across them.

The built-in finance contracts in this framework express the same rules as
direct requireThat groups (equivalent semantics, flatter code); the framework
exists for apps that prefer the compositional style and for parity with the
reference's contract-authoring model.

    class Issue(Clause):
        required_commands = (CashIssue,)
        def verify(self, tx, inputs, outputs, commands, key):
            ...; return the commands this clause consumed

    verify_clause(tx, AllComposition(Issue(), Conserve()), commands)
"""

from __future__ import annotations

from typing import Any, Sequence

from .dsl import RequirementFailed
from .structures import AuthenticatedObject, ContractState


class Clause:
    """One verification rule (Clause.kt). Subclasses set required_commands
    (the clause only triggers when one is present; empty = always) and
    implement verify(), returning the set of command payloads it processed.
    """

    required_commands: tuple[type, ...] = ()

    def matches(self, commands: Sequence[AuthenticatedObject]) -> bool:
        if not self.required_commands:
            return True
        return any(isinstance(c.value, self.required_commands)
                   for c in commands)

    def get_matched_commands(self, commands):
        return [c for c in commands
                if isinstance(c.value, self.required_commands)]

    def verify(self, tx, inputs: Sequence[ContractState],
               outputs: Sequence[ContractState],
               commands: Sequence[AuthenticatedObject],
               grouping_key: Any) -> set:
        raise NotImplementedError


class AllComposition(Clause):
    """Every matching sub-clause must accept (AllComposition.kt)."""

    def __init__(self, *clauses: Clause):
        self.clauses = clauses

    def matches(self, commands) -> bool:
        return any(c.matches(commands) for c in self.clauses)

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> set:
        processed: set = set()
        for clause in self.clauses:
            if clause.matches(commands):
                processed |= clause.verify(
                    tx, inputs, outputs, commands, grouping_key)
        return processed


class AnyComposition(Clause):
    """At least one matching sub-clause must accept (AnyComposition.kt)."""

    def __init__(self, *clauses: Clause):
        self.clauses = clauses

    def matches(self, commands) -> bool:
        return any(c.matches(commands) for c in self.clauses)

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> set:
        matched = [c for c in self.clauses if c.matches(commands)]
        if not matched:
            raise RequirementFailed(
                "no clause matched the transaction's commands")
        processed: set = set()
        for clause in matched:
            processed |= clause.verify(
                tx, inputs, outputs, commands, grouping_key)
        return processed


class FirstComposition(Clause):
    """The FIRST matching sub-clause decides (FirstComposition.kt) — the
    usual way to dispatch issue/move/exit alternatives."""

    def __init__(self, *clauses: Clause):
        self.clauses = clauses

    def matches(self, commands) -> bool:
        return any(c.matches(commands) for c in self.clauses)

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> set:
        for clause in self.clauses:
            if clause.matches(commands):
                return clause.verify(
                    tx, inputs, outputs, commands, grouping_key)
        raise RequirementFailed(
            "no clause matched the transaction's commands")


class GroupClauseVerifier(Clause):
    """Fan a top-level clause across a transaction's state groups
    (GroupClauseVerifier.kt). Subclasses implement group_states(tx)."""

    def __init__(self, clause: Clause):
        self.clause = clause

    def group_states(self, tx):
        raise NotImplementedError

    def verify(self, tx, inputs, outputs, commands, grouping_key) -> set:
        processed: set = set()
        for group in self.group_states(tx):
            processed |= self.clause.verify(
                tx, group.inputs, group.outputs, commands, group.grouping_key)
        return processed


def verify_clause(tx, clause: Clause,
                  commands: Sequence[AuthenticatedObject]) -> None:
    """Run a clause tree over the transaction and require every command to
    have been processed by some clause (ClauseVerifier.verifyClause —
    unprocessed commands mean the contract didn't understand the tx)."""
    inputs = getattr(tx, "inputs", ())
    outputs = getattr(tx, "outputs", ())
    processed = clause.verify(tx, inputs, outputs, commands, None)
    unprocessed = [c.value for c in commands
                   if c.value not in processed
                   and not _is_foreign(c, clause)]
    if unprocessed:
        raise RequirementFailed(
            f"commands not processed by any clause: {unprocessed}")


def _is_foreign(command: AuthenticatedObject, clause: Clause) -> bool:
    """Commands no clause in the tree declares are someone else's business
    (multi-contract transactions share one command list)."""
    for sub in _walk(clause):
        if sub.required_commands and isinstance(
                command.value, sub.required_commands):
            return False
    return True


def _walk(clause: Clause):
    yield clause
    for child in getattr(clause, "clauses", ()) or ():
        yield from _walk(child)
    inner = getattr(clause, "clause", None)
    if inner is not None:
        yield from _walk(inner)
