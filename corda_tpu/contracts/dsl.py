"""The `requireThat` verification DSL.

Capability match for the reference's ContractsDSL (reference:
core/src/main/kotlin/net/corda/core/contracts/ContractsDSL.kt): contracts
state their rules as named boolean requirements; the first failing requirement
aborts verification with its message.

Python form:

    with require_that() as req:
        req("the amounts balance", inputs_sum == outputs_sum)
        req("owner has signed", owner in signers)

plus helpers to select commands by type (select_command / select_commands).
"""

from __future__ import annotations

from typing import Iterable, Sequence

from ..crypto.composite import CompositeKey
from .structures import AuthenticatedObject


class RequirementFailed(Exception):
    """A contract requirement evaluated false (reference: requireThat)."""


class _Requirements:
    def __call__(self, description: str, condition: bool) -> None:
        if not condition:
            raise RequirementFailed(f"Failed requirement: {description}")

    def using(self, description: str, condition: bool) -> None:
        self(description, condition)


class require_that:
    def __enter__(self) -> _Requirements:
        return _Requirements()

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


def select_commands(
    commands: Sequence[AuthenticatedObject],
    of_type: type,
    signers: Iterable[CompositeKey] | None = None,
    parties=None,
) -> list[AuthenticatedObject]:
    """Filter commands by payload type (ContractsDSL.kt select<T>)."""
    out = []
    for cmd in commands:
        if not isinstance(cmd.value, of_type):
            continue
        if signers is not None and not set(signers) <= set(cmd.signers):
            continue
        if parties is not None and not set(parties) <= set(cmd.signing_parties):
            continue
        out.append(cmd)
    return out


def select_command(
    commands: Sequence[AuthenticatedObject], of_type: type, **kw
) -> AuthenticatedObject:
    """Expect exactly one matching command (ContractsDSL.kt requireSingleCommand)."""
    found = select_commands(commands, of_type, **kw)
    if len(found) != 1:
        raise RequirementFailed(
            f"Required single {of_type.__name__} command, found {len(found)}"
        )
    return found[0]
