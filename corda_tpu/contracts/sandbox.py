"""Deterministic contract sandbox: static vetting + runtime cost accounting.

Capability match for the reference's experimental deterministic-JVM sandbox
(reference: experimental/sandbox/src/main/java/net/corda/sandbox/
WhitelistClassLoader.java:21, CandidacyStatus.java, costing/
RuntimeCostAccounter.java, costing/ContractExecutor.java): contract
verification logic must be (a) *deterministic* — every node replaying the
same transaction must reach the same verdict, so clocks, randomness, IO,
process state and reflection are off limits — and (b) *bounded* — a hostile
contract must not be able to stall a notary with an infinite loop or an
allocation bomb.

The reference enforces (a) by ASM-rewriting bytecode through a whitelist
classloader and (b) by injecting cost-accounting call sites at every branch,
allocation, invoke and throw. The Python equivalents used here:

- **Static vetting** (`vet`): walk the contract's code objects with `dis`,
  resolving every global/builtin reference and import. Only whitelisted
  builtins, whitelisted modules (the ledger data model plus pure-math
  stdlib), and code defined in whitelisted modules may be reached.
  Forbidden names (``open``, ``eval``, ``exec``, ``globals``, ``id``,
  ``hash``, …) and non-whitelisted imports fail vetting with the offending
  name, mirroring WhitelistCheckingClassVisitor's reason codes.
- **Runtime cost accounting** (`run`): execute under a ``sys.settrace``
  tracer counting line transitions (the reference's *jump* cost), calls
  (*invoke* cost) and raised exceptions (*throw* cost), plus a peak-memory
  watermark via ``tracemalloc`` (*allocation* cost). Any budget breach
  raises ``SandboxCostExceeded`` inside the traced frame, aborting
  verification exactly like RuntimeCostAccounter's kill thresholds.

Known limits (documented, as the reference's README documents its own):
native builtins (e.g. ``sorted`` of a huge list) execute outside the line
tracer, so their time is bounded only indirectly by the allocation budget;
and set/dict *iteration order* over hash-randomised strings is not policed —
ledger ids are immune because the canonical codec sorts by encoding.
"""

from __future__ import annotations

import builtins
import dis
import sys
import tracemalloc
import types
from dataclasses import dataclass

from .structures import Contract
from .verification import TransactionForContract


class SandboxViolation(Exception):
    """Static vetting failed: the code can reach a non-deterministic or
    non-whitelisted facility (WhitelistClassloadingException equivalent)."""


class SandboxCostExceeded(Exception):
    """A runtime cost budget was breached (RuntimeCostAccounter kill)."""

    def __init__(self, kind: str, spent: int, budget: int):
        super().__init__(
            f"contract exceeded its {kind} budget: {spent} > {budget}")
        self.kind = kind
        self.spent = spent
        self.budget = budget


@dataclass(frozen=True)
class CostBudget:
    """Kill thresholds (RuntimeCostAccounter.java BASELINE_*_KILL_THRESHOLD,
    scaled for line-level rather than branch-level accounting)."""

    jumps: int = 1_000_000  # line transitions
    invokes: int = 200_000  # Python-level calls
    throws: int = 50
    alloc_bytes: int = 1 << 20  # 1 MiB peak above the starting watermark


# Builtins a contract may use: pure, deterministic, side-effect free.
ALLOWED_BUILTINS = frozenset({
    "abs", "all", "any", "bin", "bool", "bytearray", "bytes", "callable",
    "chr", "classmethod", "dict", "divmod", "enumerate", "filter", "float",
    "format",
    "frozenset", "hex", "int", "isinstance", "NotImplemented",
    "issubclass", "iter", "len", "list", "map", "max", "min", "next",
    "object", "oct", "ord", "pow", "property", "range", "repr", "reversed",
    "round", "set", "slice", "sorted", "staticmethod", "str", "sum", "super",
    "tuple", "type", "zip",
})

# Explicitly banned names — each with the determinism/containment reason.
FORBIDDEN_BUILTINS = frozenset({
    "open", "input", "print",            # IO
    "eval", "exec", "compile", "__import__",  # dynamic code loading
    "globals", "locals", "vars", "dir", "__builtins__",  # environment
                                         # reflection (subscripting
                                         # __builtins__ reaches everything)
    "getattr", "hasattr",                # string-named attribute access would
                                         # bypass the FORBIDDEN_ATTRS check
    "id", "hash",                        # address/seed dependent values
    "memoryview", "breakpoint", "exit", "quit", "help",
    "setattr", "delattr",                # state mutation outside the tx view
})

# Modules whose code a contract may call into. The ledger data model is
# trusted (it is the platform), plus a small pure-math stdlib allowance.
# `operator` is deliberately absent: attrgetter/methodcaller take string
# attribute names and would bypass the FORBIDDEN_ATTRS LOAD_ATTR check
# (operator.attrgetter('__globals__') reaches real builtins). `copy` and
# `re` are absent because their module-level caches (_copy_dispatch,
# re._cache) are mutable via STORE_SUBSCR, which static vetting cannot see.
DEFAULT_MODULE_WHITELIST = (
    "corda_tpu.contracts",
    "corda_tpu.crypto",
    "corda_tpu.finance",
    "corda_tpu.serialization",
    "corda_tpu.transactions",
    "math", "cmath", "decimal", "fractions", "itertools", "functools",
    "dataclasses", "enum", "typing", "abc", "numbers",
    "collections",
)

# Reflection attributes that escape any static whitelist if reachable
# (SandboxRemapper.java's rewrite targets, translated to CPython).
FORBIDDEN_ATTRS = frozenset({
    "__globals__", "__builtins__", "__code__", "__closure__", "__dict__",
    "__subclasses__", "__getattribute__", "__reduce__", "__reduce_ex__",
    "__loader__", "__spec__", "__import__", "gi_frame", "f_globals",
    # str.format's replacement-field mini-language does attribute traversal
    # ("{0.__globals__}") outside any LOAD_ATTR the vetter can see; f-strings
    # compile to real LOAD_ATTR opcodes and stay usable.
    "format", "format_map", "vformat",
})

# Names that define WHERE code claims to come from. Assigning them (module
# body `__name__ = "math"`, class body `__module__ = "math"`) would let
# hostile code impersonate a whitelisted module and borrow its trust, so the
# vetter rejects the stores. One emission is excused: every class body
# implicitly runs `__module__ = __name__` (LOAD_NAME __name__ directly
# before the store) — harmless, because __name__ itself cannot be forged.
# (__doc__ / __all__ / __qualname__ stay assignable — no trust decision
# reads them.)
_IDENTITY_NAMES = frozenset({
    "__name__", "__module__", "__package__",
    "__builtins__", "__loader__", "__spec__", "__class__",
})

# Exception types are fine to reference (contracts raise to reject).
_EXCEPTION_NAMES = frozenset(
    n for n in dir(builtins)
    if isinstance(getattr(builtins, n), type)
    and issubclass(getattr(builtins, n), BaseException))


def _module_allowed(name: str, whitelist: tuple[str, ...]) -> bool:
    return any(name == w or name.startswith(w + ".") for w in whitelist)


# The interpreter-level callable types that genuinely carry no __code__ /
# __globals__. Only these may earn trust through module OWNERSHIP in
# _trusted_home — any Python-defined object can forge the same attribute
# surface, but it cannot forge its C-level type.
_C_CALLABLE_TYPES = (
    types.BuiltinFunctionType,  # == BuiltinMethodType
    types.WrapperDescriptorType,
    types.MethodWrapperType,
    types.MethodDescriptorType,
    types.ClassMethodDescriptorType,
)


def _is_dataclass_hash(cls: type, attr) -> bool:
    """True only for the __hash__ dataclasses generates for frozen/eq
    classes: defined on a dataclass, compiled from the '<string>' source
    dataclasses uses, reaching nothing but the hash() builtin and the
    class's own field names, and carrying no constants. Anything else —
    including a hand-written hash smuggling code — gets vetted normally.
    (Forging this shape needs compile()/exec(), which module vetting bans.)
    """
    code = getattr(attr, "__code__", None)
    # co_consts may carry the empty tuple: a fieldless frozen dataclass
    # hashes `()`, so its generated __hash__ embeds it as a constant.
    return (isinstance(attr, types.FunctionType)
            and code is not None
            and "__dataclass_fields__" in vars(cls)
            and code.co_filename == "<string>"
            and not code.co_freevars
            and set(code.co_consts) <= {None, ()}
            and set(code.co_names)
            <= {"hash"} | set(cls.__dataclass_fields__))


class DeterministicSandbox:
    """Vets and executes contract verification code (ContractExecutor.java:
    execute/isSuitable, with vetting transitive like WhitelistClassLoader's
    candidacy resolution)."""

    def __init__(self, budget: CostBudget = CostBudget(),
                 module_whitelist: tuple[str, ...] = DEFAULT_MODULE_WHITELIST):
        self.budget = budget
        self.module_whitelist = tuple(module_whitelist)
        self._vetted: set[types.CodeType] = set()
        self._vetting_instances: set[int] = set()

    # ------------------------------------------------------------- vetting

    def is_suitable(self, contract: Contract) -> bool:
        """Non-raising form of vet (ContractExecutor.isSuitable)."""
        try:
            self.vet_contract(contract)
            return True
        except SandboxViolation:
            return False

    def vet_contract(self, contract: Contract) -> None:
        self.vet(type(contract).verify)

    def vet(self, fn) -> None:
        """Statically verify every name `fn` can reach, transitively through
        functions defined in non-whitelisted (i.e. user) modules. Functions
        *defined in* whitelisted modules are trusted as-is (the platform is
        the trust root, exactly as the reference's classloader trusts the
        JDK/platform jars it doesn't rewrite)."""
        fn = getattr(fn, "__func__", fn)
        if self._trusted_home(fn):
            return
        code = getattr(fn, "__code__", None)
        if code is None:
            raise SandboxViolation(f"not vettable: {fn!r}")
        closure: dict = {}
        for name, cell in zip(code.co_freevars, fn.__closure__ or ()):
            try:
                closure[name] = cell.cell_contents
            except ValueError:
                pass  # unbound cell; resolves to NameError at runtime
        self._vet_code(code, getattr(fn, "__globals__", {}), closure)

    def _trusted_home(self, fn) -> bool:
        """Is `fn` genuinely defined in a whitelisted module? Both name
        sources a function carries — __module__ (which functools.wraps
        copies from the wrapped function) and __globals__['__name__'] —
        are just strings that hostile module-level code could forge before
        vetting ever runs. So a name alone is NOT trusted: the function's
        __globals__ must BE the claimed module's real namespace
        (sys.modules identity). Forging that requires replacing a
        sys.modules entry, which needs `sys` (not whitelisted) or
        setattr/STORE_ATTR (both vetted away). The __module__ leg accepts
        e.g. platform functions; the __globals__ leg accepts whitelisted-
        module wrappers whose __module__ was overwritten by wraps (e.g.
        dataclasses' _recursive_repr around a generated __repr__).

        C-level callables (math.floor, a descriptor's builtin accessor)
        carry no __globals__ at all, so the identity check above can never
        pass; for those, trust requires the claimed whitelisted module to
        actually OWN the object — it is bound to the module (__self__) or
        reachable under its own name there. A bare __module__ string still
        earns nothing."""
        globs = getattr(fn, "__globals__", None)
        if globs is None and getattr(fn, "__code__", None) is None:
            # Only REAL C-callable types qualify for the ownership leg: a
            # user instance can forge __module__/__self__ as class
            # attributes (type() with an arbitrary dict) but cannot forge
            # its own Python type.
            if not isinstance(fn, _C_CALLABLE_TYPES):
                return False
            mod_name = getattr(fn, "__module__", None)
            if not isinstance(mod_name, str) or not _module_allowed(
                    mod_name, self.module_whitelist):
                return False
            owner = sys.modules.get(mod_name)
            if owner is None:
                return False
            if getattr(fn, "__self__", None) is owner:
                return True
            return getattr(owner, getattr(fn, "__name__", ""), None) is fn
        names = (getattr(fn, "__module__", None),
                 globs.get("__name__") if globs else None)
        for name in names:
            if not isinstance(name, str) or not _module_allowed(
                    name, self.module_whitelist):
                continue
            mod = sys.modules.get(name)
            if mod is not None and getattr(mod, "__dict__", None) is globs:
                return True
        return False

    def _trusted_class(self, cls: type) -> bool:
        """Is `cls` genuinely defined in a whitelisted module (or builtins)?
        Like _trusted_home, a bare __module__ string is forgeable
        (functools.wraps works on classes too), so the claimed module must
        actually own the class: walking the qualname from the module object
        must arrive back at this exact class object."""
        mod_name = getattr(cls, "__module__", None)
        if not isinstance(mod_name, str):
            return False
        if mod_name == "builtins":
            owner = builtins
        elif _module_allowed(mod_name, self.module_whitelist):
            owner = sys.modules.get(mod_name)
        else:
            return False
        obj = owner
        for part in getattr(cls, "__qualname__", cls.__name__).split("."):
            if part == "<locals>" or obj is None:
                return False
            obj = getattr(obj, part, None)
        return obj is cls

    def _vet_code(self, code: types.CodeType, globs: dict,
                  closure: dict | None = None) -> None:
        if code in self._vetted:
            return
        # Mark before recursing so cycles terminate, but UNWIND on failure:
        # leaving a failed code object in the cache would let the same
        # malicious contract pass a later vet on this sandbox instance.
        self._vetted.add(code)
        try:
            self._vet_code_inner(code, globs, closure)
        except BaseException:
            self._vetted.discard(code)
            raise

    def _vet_code_inner(self, code: types.CodeType, globs: dict,
                        closure: dict | None = None) -> None:
        where = f"{code.co_filename}:{code.co_name}"

        prev = None
        for inst in dis.get_instructions(code):
            if inst.opname in ("IMPORT_NAME", "IMPORT_FROM"):
                mod = str(inst.argval)
                if inst.opname == "IMPORT_NAME" and not _module_allowed(
                        mod, self.module_whitelist):
                    raise SandboxViolation(
                        f"{where}: import of non-whitelisted module {mod!r}")
            elif inst.opname in ("LOAD_GLOBAL", "LOAD_NAME"):
                self._vet_name(str(inst.argval), globs, where)
            elif inst.opname == "LOAD_DEREF" and closure \
                    and inst.argval in closure:
                self._vet_value(str(inst.argval), closure[inst.argval], where)
            elif inst.opname in ("LOAD_ATTR", "LOAD_METHOD"):
                if str(inst.argval) in FORBIDDEN_ATTRS:
                    raise SandboxViolation(
                        f"{where}: access to reflection attribute "
                        f"{inst.argval!r}")
            elif inst.opname in ("STORE_GLOBAL", "DELETE_GLOBAL"):
                # Persistent module-level state makes replays diverge.
                raise SandboxViolation(
                    f"{where}: mutation of global {inst.argval!r}")
            elif inst.opname in ("STORE_NAME", "DELETE_NAME") \
                    and str(inst.argval) in _IDENTITY_NAMES:
                implicit_class_module = (
                    inst.argval == "__module__" and prev is not None
                    and prev.opname == "LOAD_NAME"
                    and prev.argval == "__name__")
                if not implicit_class_module:
                    raise SandboxViolation(
                        f"{where}: assignment to identity name "
                        f"{inst.argval!r}")
            elif inst.opname in ("STORE_ATTR", "DELETE_ATTR"):
                # Contracts must treat the tx view (and anything reachable
                # from it, including platform modules) as immutable.
                raise SandboxViolation(
                    f"{where}: attribute mutation {inst.argval!r}")
            prev = inst

        # The docstring slot (co_consts[0] of a non-lambda code object) is
        # exempt from the dunder scan below: docs and error text legitimately
        # *mention* names like __dict__, and this scan is evadable
        # defense-in-depth anyway — precision beats breadth here (round-3
        # advisor). But co_consts[0] is only a docstring if the code never
        # USES it as data: in `X = "__globals__"` (no docstring) the string
        # lands in slot 0 too, so exempt it only when it is never loaded, or
        # loaded solely to be stored as __doc__ (the module-body pattern).
        doc = None
        if (code.co_consts and isinstance(code.co_consts[0], str)
                and code.co_name != "<lambda>"):
            doc = code.co_consts[0]
            insts = list(dis.get_instructions(code))
            for i, ins in enumerate(insts):
                if ins.opname == "LOAD_CONST" and ins.argval is doc:
                    nxt = insts[i + 1] if i + 1 < len(insts) else None
                    if not (nxt is not None and nxt.opname == "STORE_NAME"
                            and nxt.argval == "__doc__"):
                        doc = None  # slot 0 is data, not a docstring
                        break
        for const in code.co_consts:
            if isinstance(const, types.CodeType):
                self._vet_code(const, globs)
            elif isinstance(const, str) and const is not doc:
                # Reflection attribute names smuggled as *data* — e.g. a
                # string handed to a platform helper that does attribute
                # lookup. Defense in depth only: a string assembled at
                # runtime ("__glo"+"bals__" via join) evades a constant
                # scan, which is why str.format itself is banned via
                # FORBIDDEN_ATTRS above. Scan for the dunder names only;
                # "format" itself appears in ordinary message text.
                for banned in FORBIDDEN_ATTRS:
                    if banned.startswith("__") and banned in const:
                        raise SandboxViolation(
                            f"{where}: string constant embeds reflection "
                            f"attribute {banned!r}")

    def _vet_name(self, name: str, globs: dict, where: str) -> None:
        if name in FORBIDDEN_BUILTINS:
            raise SandboxViolation(
                f"{where}: use of forbidden builtin {name!r}")
        if name in globs:
            self._vet_value(name, globs[name], where)
            return
        if name in ALLOWED_BUILTINS or name in _EXCEPTION_NAMES:
            return
        if name in ("__name__", "__qualname__", "__module__", "__doc__",
                    "__debug__", "__build_class__"):
            return  # interpreter-supplied metadata in class/module bodies
        if hasattr(builtins, name):
            raise SandboxViolation(
                f"{where}: builtin {name!r} is not whitelisted")
        # A truly unresolvable name would NameError at runtime; fine.

    def _vet_value(self, name: str, value, where: str) -> None:
        if isinstance(value, types.ModuleType):
            if not _module_allowed(value.__name__, self.module_whitelist):
                raise SandboxViolation(
                    f"{where}: reference to non-whitelisted module "
                    f"{value.__name__!r} (as {name!r})")
            return
        # Functions and classes FIRST: their __module__ is a bare string
        # functools.wraps can stamp with a whitelisted name, so it earns no
        # trust here. vet() / _trusted_class decide by sys.modules identity
        # and everything that fails that check is vetted as user code.
        if isinstance(value, (types.FunctionType, types.MethodType)):
            self.vet(value)
            return
        if isinstance(value, type):
            if self._trusted_class(value):
                # Ownership established — but an ALIAS of a forbidden
                # builtin type (memoryview) must still fail the name
                # screen, exactly as the spelled-out name would.
                if value.__module__ == "builtins":
                    self._vet_name(value.__name__, {}, where)
                return
            self._vet_class(value, where)
            return
        if callable(value) and getattr(value, "__code__", None) is None \
                and self._trusted_home(value):
            return  # C-level callable genuinely owned by a whitelisted module
        mod = getattr(value, "__module__", None)
        if mod == "builtins" and isinstance(value, _C_CALLABLE_TYPES):
            # Identity discipline, not a string compare: a user instance
            # forging __module__="builtins" has the wrong Python type and
            # never lands here; a genuine C callable must additionally BE
            # the object the builtins namespace owns under its qualname
            # (len, dict.get, ...) before the name screen decides.
            obj = builtins
            for part in getattr(value, "__qualname__",
                                getattr(value, "__name__", "")).split("."):
                obj = getattr(obj, part, None)
            if obj is value:
                self._vet_name(getattr(value, "__name__", name), {}, where)
                return
            raise SandboxViolation(
                f"{where}: C callable {name!r} claims builtins but is not "
                f"owned by it")
        if isinstance(value, (int, float, str, bytes, bool, complex)) \
                or value is None:
            return  # immutable scalar constants
        if isinstance(value, (tuple, frozenset)):
            # Immutable CONTAINERS are only as safe as their contents: a
            # tuple is the one-line smuggle for a real builtin ((open,)[0]
            # from confined code), so every element is vetted.
            for i, item in enumerate(value):
                self._vet_value(f"{name}[{i}]", item, where)
            return
        # Instances pass only when their CLASS genuinely lives in a
        # whitelisted module (identity, not the forgeable string) AND the
        # instance is an immutable value shape whose PAYLOAD also vets:
        # mutable containers are cross-replay state, and callable wrappers
        # (functools.partial over open) or a frozen dataclass field holding
        # open smuggle real builtins past confinement.
        self._vet_instance(name, value, where)

    def _vet_instance(self, name: str, value, where: str) -> None:
        """Vet an instance global: trusted-class enum members, frozen
        dataclasses (fields vetted recursively — a field can hold any
        object), and the well-known numeric value types. Deliberately
        closed-world: everything else is rejected."""
        import dataclasses
        import decimal
        import enum
        import fractions

        cls = type(value)
        if self._trusted_class(cls):
            if id(value) in self._vetting_instances:
                return  # cycle (only constructible by platform C tricks)
            self._vetting_instances.add(id(value))
            try:
                if isinstance(value, enum.Enum):
                    self._vet_value(f"{name}.value", value.value, where)
                    return
                params = getattr(cls, "__dataclass_params__", None)
                if params is not None and getattr(params, "frozen", False):
                    for f in dataclasses.fields(cls):
                        self._vet_value(f"{name}.{f.name}",
                                        getattr(value, f.name, None), where)
                    return
                if isinstance(value, (decimal.Decimal, fractions.Fraction)):
                    return
            finally:
                self._vetting_instances.discard(id(value))
        raise SandboxViolation(
            f"{where}: global {name!r} of type {type(value).__name__} from "
            f"non-whitelisted module "
            f"{getattr(value, '__module__', None)!r}")

    def _vet_class(self, cls: type, where: str,
                   seen: set[type] | None = None) -> None:
        """Vet every executable attribute of a user class: plain functions,
        class/static methods, property fget/fset/fdel, functools.wraps
        chains, nested classes, and user base classes. (The round-2 advisor
        showed the function-only walk let code smuggled in a property run
        unconfined.)"""
        seen = set() if seen is None else seen
        if cls in seen:
            return
        seen.add(cls)
        for base in cls.__bases__:
            if self._trusted_class(base):
                continue
            self._vet_class(base, where, seen)
        for name, attr in vars(cls).items():
            if name in ("__dict__", "__weakref__", "__doc__", "__module__",
                        "__qualname__", "__firstlineno__",
                        "__static_attributes__", "__slots__",
                        "__annotations__", "__match_args__",
                        "__dataclass_fields__", "__dataclass_params__",
                        "__parameters__", "__orig_bases__",
                        "__abstractmethods__", "_abc_impl"):
                continue
            # __hash__ is vetted like any method (round-3 advisor: a blanket
            # skip let a user-defined __hash__ run unvetted — a full escape
            # the moment an instance lands in a set). The ONE shape excused
            # is the dataclass-generated hash, which calls the otherwise-
            # forbidden hash() builtin; it is recognised by provenance and
            # body shape, not by name.
            if name == "__hash__" and _is_dataclass_hash(cls, attr):
                continue
            attr = getattr(attr, "__func__", attr)  # class/staticmethod
            if isinstance(attr, property):
                for accessor in (attr.fget, attr.fset, attr.fdel):
                    if accessor is not None:
                        self.vet(accessor)
                continue
            wrapped = getattr(attr, "__wrapped__", None)
            if isinstance(wrapped, (types.FunctionType, types.MethodType)):
                self.vet(wrapped)
            if isinstance(attr, (types.FunctionType, types.MethodType)):
                self.vet(attr)
                continue
            if isinstance(attr, type):
                self._vet_class(attr, where, seen)
                continue
            if attr is None or isinstance(
                    attr, (int, float, str, bytes, bool, complex)):
                continue
            if isinstance(attr, (tuple, frozenset)):
                # Same contents rule as module globals: `T = (open,)` as a
                # class attribute is the identical smuggle one level down.
                self._vet_value(f"{cls.__name__}.{name}", attr, where)
                continue
            # Arbitrary descriptors (functools.cached_property, user
            # __get__ objects, …) carry code the simple walk above misses:
            # vet every embedded callable we can find, and FAIL CLOSED on
            # attributes we cannot see into — an unrecognised mutable or
            # executable class attribute is exactly where smuggled code or
            # cross-replay state hides.
            vetted_embedded = False
            for accessor_name in ("func", "fget", "fset", "fdel",
                                  "__wrapped__", "__call__"):
                f = getattr(attr, accessor_name, None)
                f = getattr(f, "__func__", f)
                if isinstance(f, (types.FunctionType, types.MethodType)):
                    self.vet(f)
                    vetted_embedded = True
            if not vetted_embedded:
                raise SandboxViolation(
                    f"{where}: unvettable class attribute {name!r} of type "
                    f"{type(attr).__name__}")

    # ----------------------------------------------------------- execution

    def _confine(self, fn):
        """Rebuild a *user* entry function over a globals dict whose
        ``__builtins__`` holds only the allowed names — runtime defense in
        depth behind static vetting, the same belt-and-braces the
        attachments loader uses. Platform (whitelisted-module) functions run
        unmodified."""
        fn = getattr(fn, "__func__", fn)
        # Same identity rule as vetting: a wraps-stamped __module__ string
        # must not exempt user code from confinement.
        if self._trusted_home(fn):
            return fn
        code = getattr(fn, "__code__", None)
        if code is None:
            return fn
        restricted = {n: getattr(builtins, n)
                      for n in (ALLOWED_BUILTINS | _EXCEPTION_NAMES)
                      if hasattr(builtins, n)}
        restricted["__build_class__"] = builtins.__build_class__
        globs = dict(fn.__globals__)
        globs["__builtins__"] = restricted
        confined = types.FunctionType(
            code, globs, fn.__name__, fn.__defaults__, fn.__closure__)
        confined.__kwdefaults__ = fn.__kwdefaults__
        return confined

    def run(self, fn, *args, **kwargs):
        """Vet, then execute under the cost tracer. Returns fn's result;
        raises SandboxViolation / SandboxCostExceeded."""
        self.vet(fn)
        fn = self._confine(fn)
        budget = self.budget
        counts = {"jump": 0, "invoke": 0, "throw": 0}

        def charge(kind: str, limit: int) -> None:
            counts[kind] += 1
            if counts[kind] > limit:
                raise SandboxCostExceeded(kind, counts[kind], limit)

        def check_alloc() -> None:
            current, peak = tracemalloc.get_traced_memory()
            if max(current, peak) - base > budget.alloc_bytes:
                raise SandboxCostExceeded(
                    "alloc", max(current, peak) - base, budget.alloc_bytes)

        def tracer(frame, event, arg):
            if event == "call":
                charge("invoke", budget.invokes)
                return tracer
            if event == "line":
                charge("jump", budget.jumps)
                # Kill allocation bombs *mid-loop*, not after the damage is
                # done; sampled so the common case stays cheap.
                if counts["jump"] % 64 == 0:
                    check_alloc()
            elif event == "exception":
                charge("throw", budget.throws)
            return tracer

        started_tracemalloc = not tracemalloc.is_tracing()
        if started_tracemalloc:
            tracemalloc.start()
        else:
            tracemalloc.reset_peak()
        base, _ = tracemalloc.get_traced_memory()
        old_trace = sys.gettrace()
        sys.settrace(tracer)
        try:
            result = fn(*args, **kwargs)
        finally:
            sys.settrace(old_trace)
            _, peak = tracemalloc.get_traced_memory()
            if started_tracemalloc:
                tracemalloc.stop()
        allocated = max(0, peak - base)
        if allocated > budget.alloc_bytes:
            raise SandboxCostExceeded("alloc", allocated, budget.alloc_bytes)
        return result

    def execute(self, contract: Contract, tx: TransactionForContract) -> None:
        """Run a contract's verify inside the sandbox
        (ContractExecutor.execute)."""
        self.run(type(contract).verify, contract, tx)


def sandboxed_verify(tx: TransactionForContract,
                     sandbox: DeterministicSandbox | None = None) -> None:
    """Verify every contract referenced by a transaction inside one sandbox —
    the drop-in hardened twin of platform contract verification."""
    sandbox = sandbox or DeterministicSandbox()
    contracts = {s.contract for s in tx.inputs} | {
        s.contract for s in tx.outputs}
    for contract in sorted(contracts, key=lambda c: type(c).__name__):
        sandbox.execute(contract, tx)
