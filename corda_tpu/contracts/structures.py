"""Core ledger data types.

Capability match for the reference's contract structures (reference:
core/src/main/kotlin/net/corda/core/contracts/Structures.kt): the UTXO state
model — states owned by composite keys, commands that instruct contracts,
state references forming the transaction DAG, and the marker interfaces
(Linear/Ownable/Schedulable/Deal/FungibleAsset) that services key off.

All types are frozen dataclasses registered with the canonical codec so their
serialized hashes are stable transaction-Merkle leaves.

Time is represented as integer epoch-microseconds (not floats/datetimes) so
timestamps serialize canonically.
"""

from __future__ import annotations

import os
import time as _time
from dataclasses import dataclass, field
from typing import Any, TYPE_CHECKING

from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.party import Party, PartyAndReference
from ..serialization.codec import register

if TYPE_CHECKING:
    from .verification import TransactionForContract

MICROS = 1_000_000

# Default clock-skew allowance for notarised timestamps (TimestampChecker's
# default). Flows that build time windows anchor their guards to this so
# "the flow refused" and "the notary refused" stay consistent.
DEFAULT_TIMESTAMP_TOLERANCE_MICROS = 30 * MICROS


def now_micros() -> int:
    return int(_time.time() * MICROS)


class ContractState:
    """A fact on the ledger. Implementations are frozen dataclasses.

    Reference: Structures.kt:64-136. `contract` is the program that governs
    state transitions; `participants` the keys that must sign any transaction
    consuming the state (used by vaults to decide relevance); `encumbrance`
    optionally ties consumption of this state to another output of the same
    transaction.
    """

    @property
    def contract(self) -> "Contract":
        raise NotImplementedError

    @property
    def participants(self) -> list[CompositeKey]:
        raise NotImplementedError

    @property
    def encumbrance(self) -> int | None:
        return None


class Contract:
    """Shared-ledger business logic (reference: Structures.kt:431-446).

    verify() must raise to reject a state transition; every contract mentioned
    by a transaction's states must accept it.
    """

    def verify(self, tx: "TransactionForContract") -> None:
        raise NotImplementedError

    @property
    def legal_contract_reference(self) -> SecureHash:
        raise NotImplementedError

    # Contracts are compared by type (stateless singletons), as in the
    # reference where contract classes are the unit of identity.
    def __eq__(self, other):
        return type(other) is type(self)

    def __hash__(self):
        return hash(type(self).__qualname__)


@register
@dataclass(frozen=True, order=True)
class StateRef:
    """(tx id, output index) — a Bitcoin-style outpoint (Structures.kt:337)."""

    txhash: SecureHash
    index: int

    def __str__(self) -> str:
        return f"{self.txhash}({self.index})"


@register
@dataclass(frozen=True)
class TransactionState:
    """A ContractState plus the notary in whose custody it lives
    (Structures.kt:142-160)."""

    data: ContractState
    notary: Party

    def with_notary(self, new_notary: Party) -> "TransactionState":
        return TransactionState(self.data, new_notary)

    def out_ref(self, txhash: SecureHash, index: int) -> "StateAndRef":
        return StateAndRef(self, StateRef(txhash, index))


@register
@dataclass(frozen=True)
class StateAndRef:
    """A (state, ref) pair, e.g. a vault entry (Structures.kt:342)."""

    state: TransactionState
    ref: StateRef


class CommandData:
    """Marker base for command payloads (Structures.kt:358)."""


class TypeOnlyCommandData(CommandData):
    """Commands whose presence alone matters (Structures.kt:361-364)."""

    def __eq__(self, other):
        return type(other) is type(self)

    def __hash__(self):
        return hash(type(self).__qualname__)


class IssueCommand(CommandData):
    """Common issue command carrying an anti-replay nonce (Structures.kt:375)."""

    nonce: int


class MoveCommand(CommandData):
    """Common change-of-owner command (Structures.kt:382)."""

    contract_hash: SecureHash | None


@register
@dataclass(frozen=True)
class Command:
    """Command payload plus the keys that must sign for it (Structures.kt:367)."""

    value: CommandData
    signers: tuple[CompositeKey, ...]

    def __post_init__(self):
        if isinstance(self.signers, CompositeKey):
            object.__setattr__(self, "signers", (self.signers,))
        else:
            object.__setattr__(self, "signers", tuple(self.signers))
        if not self.signers:
            raise ValueError("Command requires at least one signer")


@register
@dataclass(frozen=True)
class AuthenticatedObject:
    """A value plus who signed it, with recognised parties resolved
    (Structures.kt:401)."""

    signers: tuple[CompositeKey, ...]
    signing_parties: tuple[Party, ...]
    value: Any


@register
@dataclass(frozen=True)
class Timestamp:
    """Notarised time window in epoch-microseconds (Structures.kt:412-425):
    the true commit time lies in (after, before)."""

    after: int | None
    before: int | None

    def __post_init__(self):
        if self.after is None and self.before is None:
            raise ValueError("At least one of before/after must be specified")
        if self.after is not None and self.before is not None and self.after > self.before:
            raise ValueError("after must be <= before")

    @staticmethod
    def around(time_micros: int, tolerance_micros: int) -> "Timestamp":
        return Timestamp(time_micros - tolerance_micros, time_micros + tolerance_micros)

    @property
    def midpoint(self) -> int:
        assert self.after is not None and self.before is not None
        return self.after + (self.before - self.after) // 2


@register
@dataclass(frozen=True)
class Issued:
    """'X issued by Y': definition of a claim against an issuer
    (Structures.kt:172-180)."""

    issuer: PartyAndReference
    product: Any

    def __str__(self) -> str:
        return f"{self.product} issued by {self.issuer}"


@register
@dataclass(frozen=True, order=True)
class UniqueIdentifier:
    """A linear-state id: optional external reference + unique internal id
    (reference: core/.../contracts/Structures.kt UniqueIdentifier in later
    snapshots; here id bytes replace a JVM UUID)."""

    external_id: str | None = None
    id: bytes = field(default_factory=lambda: os.urandom(16))

    def __str__(self) -> str:
        return f"{self.external_id}_{self.id.hex()}" if self.external_id else self.id.hex()


class OwnableState(ContractState):
    """A state with a singular owner that can be moved (Structures.kt:186)."""

    @property
    def owner(self) -> CompositeKey:
        raise NotImplementedError

    def with_new_owner(self, new_owner: CompositeKey) -> tuple[CommandData, "OwnableState"]:
        raise NotImplementedError


class LinearState(ContractState):
    """A state standing in for a evolving fact-thread on the ledger, tracked
    by linear_id across transactions (Structures.kt:226-246)."""

    @property
    def linear_id(self) -> UniqueIdentifier:
        raise NotImplementedError

    def is_relevant(self, our_keys: set) -> bool:
        raise NotImplementedError


class SchedulableState(ContractState):
    """A state that can request a flow run at a future time
    (Structures.kt:259-270)."""

    def next_scheduled_activity(self, this_state_ref: StateRef, flow_factory) -> Any | None:
        raise NotImplementedError


class DealState(LinearState):
    """A deal between parties that can be regenerated (Structures.kt:276-300)."""

    @property
    def parties(self) -> list[Party]:
        raise NotImplementedError


class FungibleAsset(OwnableState):
    """An asset splittable/mergeable by amount, e.g. cash or commodities
    (reference: core/.../contracts/FungibleAsset.kt:23)."""

    @property
    def amount(self):
        raise NotImplementedError

    @property
    def exit_keys(self) -> list[CompositeKey]:
        raise NotImplementedError


class NamedByHash:
    """Anything content-addressed by a SecureHash (Structures.kt:22)."""

    @property
    def id(self) -> SecureHash:
        raise NotImplementedError


class Attachment(NamedByHash):
    """A content-addressed blob of public static data referenced by
    transactions (Structures.kt:459-475)."""

    def open(self) -> bytes:
        raise NotImplementedError
