"""Universal (composable) contracts: one generic contract, many products.

Capability match for the reference's experimental universal-contracts module
(reference: experimental/src/main/kotlin/net/corda/contracts/universal/
Arrangement.kt, Perceivable.kt, UniversalContract.kt:13-317, Util.kt): a
financial product is not code but a *value* — an ``Arrangement`` tree built
from transfers, choices ("actions") and schedules ("roll-outs"), with all
observables ("perceivables") expressed as a symbolic expression tree. A
single generic contract verifies every product by structural reduction:
exercising an action, applying an oracle fixing, or rolling a schedule
forward must transform the input arrangement into exactly the output
arrangement.

Design differences from the reference (deliberate, TPU-framework idioms):

- All money amounts are integer fixed-point scaled by ``SCALE`` (10^4), the
  same convention as ``flows.oracle.Fix.value`` — floats/BigDecimal never
  enter the codec, so arrangement values hash canonically into tx ids.
- Dates are integer epoch days (``finance.types``), schedule arithmetic uses
  ``Tenor``/``BusinessCalendar`` from the finance layer.
- Every node is a frozen dataclass registered with the canonical codec, so
  whole products serialize, checkpoint, and Merkle-hash like any other state.
  Determinism of arithmetic (floor-division, fixed scale) is part of the
  contract's semantics: every node on the network reduces an arrangement to
  bit-identical bytes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.party import Party, PartyAndReference
from ..finance.types import BusinessCalendar, Tenor, days_to_date
from ..flows.oracle import Fix, FixOf
from ..serialization.codec import register
from ..transactions.builder import TransactionBuilder
from .dsl import require_that, select_command
from .structures import (
    CommandData,
    Contract,
    ContractState,
    TransactionState,
    TypeOnlyCommandData,
)
from .verification import TransactionForContract

SCALE = 10_000  # fixed-point scale for amounts and rates (matches Fix.value)
_DAY_MICROS = 86_400 * 1_000_000

LT, LTE, GT, GTE = "LT", "LTE", "GT", "GTE"
PLUS, MINUS, TIMES, DIV = "PLUS", "MINUS", "TIMES", "DIV"


def to_quanta(units: int | float) -> int:
    """Whole currency units -> fixed-point quanta."""
    return round(units * SCALE)


# ---------------------------------------------------------------------------
# Perceivables — symbolic observables (Perceivable.kt)
# ---------------------------------------------------------------------------


class Perceivable:
    """An observable value: constant, time, arithmetic, or an oracle fixing
    (reference: Perceivable.kt:10). Structural equality; immutable."""

    # Arithmetic sugar so products read like the reference's DSL.
    def __add__(self, other):
        return BinOp(self, PLUS, _lift(other))

    def __sub__(self, other):
        return BinOp(self, MINUS, _lift(other))

    def __mul__(self, other):
        return BinOp(self, TIMES, _lift(other))

    def __floordiv__(self, other):
        return BinOp(self, DIV, _lift(other))

    def __and__(self, other):
        return PAnd(self, _lift(other))

    def __or__(self, other):
        return POr(self, _lift(other))


def _lift(v) -> "Perceivable":
    return v if isinstance(v, Perceivable) else Const(v)


@register
@dataclass(frozen=True)
class Const(Perceivable):
    """A constant (Perceivable.kt Const). ints/bools/strings only — anything
    that serializes canonically."""

    value: Any


def const(v) -> Const:
    return Const(v)


@register
@dataclass(frozen=True)
class StartDate(Perceivable):
    """Placeholder for the current roll-out period's start day; replaced with
    a Const during roll-out reduction (Perceivable.kt StartDate)."""


@register
@dataclass(frozen=True)
class EndDate(Perceivable):
    """Placeholder for the current roll-out period's end day."""


@register
@dataclass(frozen=True)
class TimeCondition(Perceivable):
    """Boolean observable over notarised time (Perceivable.kt
    TimePerceivable): LTE = "before day", GTE = "after day". ``day`` is a
    Perceivable of epoch days."""

    cmp: str
    day: Perceivable

    def __post_init__(self):
        if self.cmp not in (LTE, GTE):
            raise ValueError(f"unsupported time comparison {self.cmp!r}")


def before(day: int | Perceivable) -> TimeCondition:
    return TimeCondition(LTE, _lift(day))


def after(day: int | Perceivable) -> TimeCondition:
    return TimeCondition(GTE, _lift(day))


@register
@dataclass(frozen=True)
class PAnd(Perceivable):
    left: Perceivable
    right: Perceivable


@register
@dataclass(frozen=True)
class POr(Perceivable):
    left: Perceivable
    right: Perceivable


@register
@dataclass(frozen=True)
class Compare(Perceivable):
    """left <cmp> right over fixed-point amounts (PerceivableComparison)."""

    left: Perceivable
    cmp: str
    right: Perceivable


@register
@dataclass(frozen=True)
class BinOp(Perceivable):
    """Fixed-point arithmetic (PerceivableOperation). TIMES and DIV rescale
    by SCALE with floor division — deterministic by construction."""

    left: Perceivable
    op: str
    right: Perceivable


@register
@dataclass(frozen=True)
class PosPart(Perceivable):
    """max(x, 0) — the reference's UnaryPlus, the option-payoff primitive."""

    arg: Perceivable


@register
@dataclass(frozen=True)
class Max(Perceivable):
    args: frozenset

    def __post_init__(self):
        object.__setattr__(self, "args", frozenset(self.args))


@register
@dataclass(frozen=True)
class Min(Perceivable):
    args: frozenset

    def __post_init__(self):
        object.__setattr__(self, "args", frozenset(self.args))


@register
@dataclass(frozen=True)
class Interest(Perceivable):
    """Simple interest accrual: amount * rate * dcf(start, end)
    (Perceivable.kt Interest). Rate is an annualised percentage in
    fixed-point; day-count is ACT/360 or ACT/365 on epoch days."""

    amount: Perceivable
    day_count_convention: str
    rate: Perceivable  # percent, fixed-point (e.g. 5% = 5 * SCALE)
    start: Perceivable  # epoch days
    end: Perceivable


@register
@dataclass(frozen=True)
class Fixing(Perceivable):
    """An oracle fixing not yet observed (Perceivable.kt Fixing). Replaced by
    a Const via the ApplyFixes command, which must be accompanied in the same
    transaction by a ``Fix`` command signed by ``oracle`` — the product pins
    the identity trusted for this source at issue time (the tear-off signing
    pattern of flows/oracle.py, hardened over the reference which never
    checks who signed the fix)."""

    source: str
    day: Perceivable  # epoch days
    tenor: str
    oracle: CompositeKey


def fixing(source: str, day: int | Perceivable, tenor: str,
           oracle: Party | CompositeKey) -> Fixing:
    key = oracle.owning_key if isinstance(oracle, Party) else oracle
    return Fixing(source, _lift(day), tenor, key)


def interest(amount: int, dcc: str, rate, start, end) -> Interest:
    return Interest(_lift(amount), dcc, _lift(rate), _lift(start), _lift(end))


# ---------------------------------------------------------------------------
# Arrangements — the product algebra (Arrangement.kt)
# ---------------------------------------------------------------------------


class Arrangement:
    """A tree of rights and obligations (Arrangement.kt:9)."""


@register
@dataclass(frozen=True)
class Zero(Arrangement):
    """No rights, no obligations; termination is a transition to Zero."""


ZERO = Zero()


@register
@dataclass(frozen=True)
class Transfer(Arrangement):
    """Immediate transfer of ``amount`` quanta of ``currency`` from
    ``from_party`` to ``to_party`` (Arrangement.kt Obligation — renamed: this
    framework already has an Obligation *contract* in the finance layer)."""

    amount: Perceivable
    currency: str
    from_party: Party
    to_party: Party


@register
@dataclass(frozen=True)
class All(Arrangement):
    """Conjunction of independent arrangements (Arrangement.kt And)."""

    arrangements: frozenset

    def __post_init__(self):
        object.__setattr__(self, "arrangements", frozenset(self.arrangements))


@register
@dataclass(frozen=True)
class Action(Arrangement):
    """A named transition any of ``actors`` may take when ``condition`` holds
    (Arrangement.kt Action)."""

    name: str
    condition: Perceivable
    actors: frozenset  # of Party
    arrangement: Arrangement

    def __post_init__(self):
        object.__setattr__(self, "actors", frozenset(self.actors))


@register
@dataclass(frozen=True)
class Actions(Arrangement):
    """The menu of available transitions (Arrangement.kt Actions)."""

    actions: frozenset  # of Action

    def __post_init__(self):
        object.__setattr__(self, "actions", frozenset(self.actions))


@register
@dataclass(frozen=True)
class RollOut(Arrangement):
    """A schedule: instantiate ``template`` per period from start to end at
    ``frequency`` (Arrangement.kt RollOut). The template refers to the
    current period via StartDate/EndDate and recurses via Continuation."""

    start_day: int
    end_day: int
    frequency: Tenor
    template: Arrangement


@register
@dataclass(frozen=True)
class Continuation(Arrangement):
    """Inside a RollOut template: "the rest of the schedule"."""


def actions(*acts: Action) -> Actions:
    return Actions(frozenset(acts))


def arrange(name: str, condition: Perceivable, actors, arrangement: Arrangement) -> Action:
    party_set = {actors} if isinstance(actors, Party) else set(actors)
    return Action(name, condition, frozenset(party_set), arrangement)


def transfer(amount, currency: str, from_party: Party, to_party: Party) -> Transfer:
    return Transfer(_lift(amount), currency, from_party, to_party)


def all_of(*arrangements: Arrangement) -> Arrangement:
    flat = [a for a in arrangements if a != ZERO]
    if not flat:
        return ZERO
    if len(flat) == 1:
        return flat[0]
    return All(frozenset(flat))


def _flat_parts(arr: Arrangement) -> list:
    """Flatten an arrangement into its conjunct leaves (nested Alls opened,
    Zeros dropped). Used for multiset output comparison in UAction."""
    if isinstance(arr, All):
        parts: list = []
        for a in arr.arrangements:
            parts.extend(_flat_parts(a))
        return parts
    return [] if arr == ZERO else [arr]


def _multiset_equal(produced: list, expected: list) -> bool:
    """==-based multiset equality: for each expected part find and remove
    one equal produced part. Quadratic, but action results are a handful of
    parts; crucially it depends only on Arrangement.__eq__, never on repr
    ordering of frozenset fields."""
    remaining = list(produced)
    for part in expected:
        for i, cand in enumerate(remaining):
            if cand == part:
                del remaining[i]
                break
        else:
            return False
    return not remaining


# ---------------------------------------------------------------------------
# Structural utilities (Util.kt)
# ---------------------------------------------------------------------------


def liable_parties(arrangement: Arrangement) -> frozenset[CompositeKey]:
    """Keys of parties that may end up owing something (Util.kt
    liableParties:15-36): transfer sources, minus an action's sole actor (a
    party can't be surprised by an obligation only they can trigger)."""
    if isinstance(arrangement, (Zero, Continuation)):
        return frozenset()
    if isinstance(arrangement, Transfer):
        return frozenset({arrangement.from_party.owning_key})
    if isinstance(arrangement, All):
        out: frozenset = frozenset()
        for a in arrangement.arrangements:
            out |= liable_parties(a)
        return out
    if isinstance(arrangement, Actions):
        out = frozenset()
        for act in arrangement.actions:
            inner = liable_parties(act.arrangement)
            if len(act.actors) == 1:
                inner -= {next(iter(act.actors)).owning_key}
            out |= inner
        return out
    if isinstance(arrangement, RollOut):
        return liable_parties(arrangement.template)
    raise TypeError(f"liable_parties: {type(arrangement).__name__}")


def involved_parties(arrangement: Arrangement) -> frozenset[CompositeKey]:
    """Every key mentioned by the product (Util.kt involvedParties:38-53)."""
    if isinstance(arrangement, (Zero, Continuation)):
        return frozenset()
    if isinstance(arrangement, Transfer):
        return frozenset(
            {arrangement.from_party.owning_key, arrangement.to_party.owning_key})
    if isinstance(arrangement, All):
        out: frozenset = frozenset()
        for a in arrangement.arrangements:
            out |= involved_parties(a)
        return out
    if isinstance(arrangement, Actions):
        out = frozenset()
        for act in arrangement.actions:
            out |= involved_parties(act.arrangement)
            out |= frozenset(p.owning_key for p in act.actors)
        return out
    if isinstance(arrangement, RollOut):
        return involved_parties(arrangement.template)
    raise TypeError(f"involved_parties: {type(arrangement).__name__}")


def replace_party(arrangement: Arrangement, old: Party, new: Party) -> Arrangement:
    """Substitute a party everywhere (Util.kt replaceParty:55-71)."""
    if isinstance(arrangement, (Zero, Continuation)):
        return arrangement
    if isinstance(arrangement, Transfer):
        return Transfer(
            arrangement.amount, arrangement.currency,
            new if arrangement.from_party == old else arrangement.from_party,
            new if arrangement.to_party == old else arrangement.to_party)
    if isinstance(arrangement, All):
        return All(frozenset(
            replace_party(a, old, new) for a in arrangement.arrangements))
    if isinstance(arrangement, Actions):
        return Actions(frozenset(
            Action(a.name, a.condition,
                   frozenset(new if p == old else p for p in a.actors),
                   replace_party(a.arrangement, old, new))
            for a in arrangement.actions))
    if isinstance(arrangement, RollOut):
        return RollOut(arrangement.start_day, arrangement.end_day,
                       arrangement.frequency,
                       replace_party(arrangement.template, old, new))
    raise TypeError(f"replace_party: {type(arrangement).__name__}")


def actions_of(arrangement: Arrangement) -> dict[str, Action]:
    """Name -> Action over the top level (Util.kt actions:86-99)."""
    if isinstance(arrangement, (Zero, Transfer, RollOut)):
        return {}
    if isinstance(arrangement, Actions):
        return {a.name: a for a in arrangement.actions}
    if isinstance(arrangement, All):
        out: dict[str, Action] = {}
        for a in arrangement.arrangements:
            out.update(actions_of(a))
        return out
    raise TypeError(f"actions_of: {type(arrangement).__name__}")


def extract_remainder(arrangement: Arrangement, action: Action) -> Arrangement:
    """What's left if ``action`` is exercised (Util.kt extractRemainder)."""
    if isinstance(arrangement, Actions):
        return ZERO if action in arrangement.actions else arrangement
    if isinstance(arrangement, All):
        rest = [extract_remainder(a, action) for a in arrangement.arrangements]
        return all_of(*rest)
    return arrangement


# --- roll-out reduction (UniversalContract.kt reduceRollOut:103-121) -------


def _substitute(p: Perceivable, mapping) -> Perceivable:
    """Rebuild a perceivable tree with ``mapping`` applied to each node
    bottom-up. mapping(node) returns a replacement or None."""
    if isinstance(p, (Const, StartDate, EndDate)):
        pass  # leaves
    elif isinstance(p, TimeCondition):
        p = TimeCondition(p.cmp, _substitute(p.day, mapping))
    elif isinstance(p, (PAnd, POr)):
        p = type(p)(_substitute(p.left, mapping), _substitute(p.right, mapping))
    elif isinstance(p, Compare):
        p = Compare(_substitute(p.left, mapping), p.cmp,
                    _substitute(p.right, mapping))
    elif isinstance(p, BinOp):
        p = BinOp(_substitute(p.left, mapping), p.op,
                  _substitute(p.right, mapping))
    elif isinstance(p, PosPart):
        p = PosPart(_substitute(p.arg, mapping))
    elif isinstance(p, (Max, Min)):
        p = type(p)(frozenset(_substitute(a, mapping) for a in p.args))
    elif isinstance(p, Interest):
        p = Interest(_substitute(p.amount, mapping), p.day_count_convention,
                     _substitute(p.rate, mapping), _substitute(p.start, mapping),
                     _substitute(p.end, mapping))
    elif isinstance(p, Fixing):
        p = Fixing(p.source, _substitute(p.day, mapping), p.tenor, p.oracle)
    else:
        raise TypeError(f"substitute: {type(p).__name__}")
    replacement = mapping(p)
    return p if replacement is None else replacement


def _map_arrangement(arrangement: Arrangement, p_map, a_map,
                     into_rollout: bool = True) -> Arrangement:
    """Rebuild an arrangement tree applying p_map to every perceivable and
    a_map to every arrangement node (bottom-up). ``into_rollout=False`` stops
    at nested RollOut boundaries: their StartDate/EndDate/Continuation
    placeholders belong to the *inner* schedule's scope, so period
    substitution must not rewrite them (fixing substitution must — the
    reference's replaceFixing recurses into RollOut templates while
    replaceStartEnd does not, UniversalContract.kt:124-146,286)."""
    if isinstance(arrangement, (Zero, Continuation)):
        out: Arrangement = arrangement
    elif isinstance(arrangement, Transfer):
        out = Transfer(_substitute(arrangement.amount, p_map),
                       arrangement.currency, arrangement.from_party,
                       arrangement.to_party)
    elif isinstance(arrangement, All):
        out = All(frozenset(
            _map_arrangement(a, p_map, a_map, into_rollout)
            for a in arrangement.arrangements))
    elif isinstance(arrangement, Actions):
        out = Actions(frozenset(
            Action(a.name, _substitute(a.condition, p_map), a.actors,
                   _map_arrangement(a.arrangement, p_map, a_map,
                                    into_rollout))
            for a in arrangement.actions))
    elif isinstance(arrangement, RollOut):
        if not into_rollout:
            return arrangement
        out = RollOut(arrangement.start_day, arrangement.end_day,
                      arrangement.frequency,
                      _map_arrangement(arrangement.template, p_map, a_map,
                                       into_rollout))
    else:
        raise TypeError(f"map_arrangement: {type(arrangement).__name__}")
    replacement = a_map(out)
    return out if replacement is None else replacement


def reduce_rollout(roll: RollOut,
                   calendar: BusinessCalendar = BusinessCalendar()) -> Arrangement:
    """Expand one period of a schedule (UniversalContract.kt
    reduceRollOut:103-121): instantiate the template with this period's
    start/end, and splice either the remaining RollOut (via Continuation) or
    nothing if this was the last period."""
    period_end = calendar.advance(roll.start_day, roll.frequency)
    this_start, this_end = roll.start_day, min(period_end, roll.end_day)

    def p_map(p):
        if isinstance(p, StartDate):
            return Const(this_start)
        if isinstance(p, EndDate):
            return Const(this_end)
        return None

    if period_end < roll.end_day:
        rest: Arrangement = RollOut(period_end, roll.end_day, roll.frequency,
                                    roll.template)
    else:
        rest = ZERO

    def a_map(a):
        if isinstance(a, Continuation):
            return rest
        if isinstance(a, All):  # renormalise after Continuation -> Zero
            return all_of(*a.arrangements)
        return None

    return _map_arrangement(roll.template, p_map, a_map, into_rollout=False)


def collect_fixings(arrangement: Arrangement) -> dict[FixOf, CompositeKey]:
    """Every date-resolved Fixing in the tree as FixOf -> pinned oracle key —
    the discovery side of replace_fixings (flows use it to know what to ask
    an oracle for)."""
    found: dict[FixOf, CompositeKey] = {}

    def p_map(p):
        if isinstance(p, Fixing) and isinstance(p.day, Const):
            found[FixOf(p.source, p.day.value, p.tenor)] = p.oracle
        return None

    _map_arrangement(arrangement, p_map, lambda a: None)
    return found


def replace_fixings(arrangement: Arrangement, fixes: dict[FixOf, int],
                    used: set | None = None,
                    oracles: dict | None = None) -> Arrangement:
    """Substitute observed oracle values for Fixing nodes
    (UniversalContract.kt replaceFixing:246-290). ``used`` collects the
    FixOfs actually consumed so verify can insist none were superfluous;
    ``oracles`` collects FixOf -> pinned oracle CompositeKey so verify can
    insist each substitution was signed by the key the product trusts."""
    consumed = set() if used is None else used
    trusted = {} if oracles is None else oracles

    def p_map(p):
        if isinstance(p, Fixing) and isinstance(p.day, Const):
            key = FixOf(p.source, p.day.value, p.tenor)
            if key in fixes:
                consumed.add(key)
                trusted[key] = p.oracle
                return Const(fixes[key])
        return None

    return _map_arrangement(arrangement, p_map, lambda a: None)


# ---------------------------------------------------------------------------
# Evaluation (UniversalContract.kt eval:34-90)
# ---------------------------------------------------------------------------


class EvalError(Exception):
    """A perceivable could not be reduced to a value (unfixed oracle data,
    unresolved StartDate/EndDate, malformed tree)."""


def eval_amount(tx: TransactionForContract, p: Perceivable) -> int:
    """Reduce to fixed-point quanta. Arithmetic is exact for +/-, floor-
    rescaled for * and / — every node computes identical ints."""
    if isinstance(p, Const):
        if not isinstance(p.value, int) or isinstance(p.value, bool):
            raise EvalError(f"non-numeric constant {p.value!r}")
        return p.value
    if isinstance(p, BinOp):
        left, right = eval_amount(tx, p.left), eval_amount(tx, p.right)
        if p.op == PLUS:
            return left + right
        if p.op == MINUS:
            return left - right
        if p.op == TIMES:
            return (left * right) // SCALE
        if p.op == DIV:
            if right == 0:
                raise EvalError("division by zero")
            return (left * SCALE) // right
        raise EvalError(f"unknown op {p.op!r}")
    if isinstance(p, PosPart):
        return max(eval_amount(tx, p.arg), 0)
    if isinstance(p, Max):
        return max(eval_amount(tx, a) for a in p.args)
    if isinstance(p, Min):
        return min(eval_amount(tx, a) for a in p.args)
    if isinstance(p, Interest):
        principal = eval_amount(tx, p.amount)
        rate = eval_amount(tx, p.rate)  # percent, fixed-point
        start, end = eval_day(tx, p.start), eval_day(tx, p.end)
        basis = {"ACT/360": 360, "ACT/365": 365}.get(p.day_count_convention)
        if basis is None:
            raise EvalError(f"unknown day count {p.day_count_convention!r}")
        # principal * (rate/100) * days/basis, all in fixed point.
        return (principal * rate * (end - start)) // (100 * SCALE * basis)
    if isinstance(p, Fixing):
        raise EvalError(
            f"unfixed oracle value {p.source} — an ApplyFixes command must "
            "substitute it before it can be evaluated")
    raise EvalError(f"eval_amount: {type(p).__name__}")


def eval_day(tx: TransactionForContract, p: Perceivable) -> int:
    if isinstance(p, Const):
        if not isinstance(p.value, int):
            raise EvalError(f"non-day constant {p.value!r}")
        return p.value
    if isinstance(p, (StartDate, EndDate)):
        raise EvalError("start/end date outside a roll-out context")
    raise EvalError(f"eval_day: {type(p).__name__}")


def eval_condition(tx: TransactionForContract, p: Perceivable) -> bool:
    if isinstance(p, Const):
        if not isinstance(p.value, bool):
            raise EvalError(f"non-boolean constant {p.value!r}")
        return p.value
    if isinstance(p, PAnd):
        return eval_condition(tx, p.left) and eval_condition(tx, p.right)
    if isinstance(p, POr):
        return eval_condition(tx, p.left) or eval_condition(tx, p.right)
    if isinstance(p, TimeCondition):
        if tx.timestamp is None:
            raise EvalError("time condition on an untimestamped transaction")
        day_micros = eval_day(tx, p.day) * _DAY_MICROS
        if p.cmp == LTE:  # "before day": latest possible time <= day
            return tx.timestamp.before is not None and tx.timestamp.before <= day_micros
        # GTE, "after day": earliest possible time >= day
        return tx.timestamp.after is not None and tx.timestamp.after >= day_micros
    if isinstance(p, Compare):
        left, right = eval_amount(tx, p.left), eval_amount(tx, p.right)
        return {LT: left < right, LTE: left <= right,
                GT: left > right, GTE: left >= right}[p.cmp]
    raise EvalError(f"eval_condition: {type(p).__name__}")


# ---------------------------------------------------------------------------
# The contract (UniversalContract.kt:13-317)
# ---------------------------------------------------------------------------


class UniversalCommand(CommandData):
    """Marker base for the universal contract's commands."""


@register
@dataclass(frozen=True)
class UIssue(TypeOnlyCommandData, UniversalCommand):
    """Put a product on ledger; all liable parties must sign."""


@register
@dataclass(frozen=True)
class UMove(UniversalCommand):
    """Replace a party; liable parties of the result must sign."""

    old: Party
    new: Party


@register
@dataclass(frozen=True)
class UAction(UniversalCommand):
    """Exercise the named action."""

    name: str


@register
@dataclass(frozen=True)
class UApplyFixes(UniversalCommand):
    """Substitute oracle fixings into the product. The same transaction
    carries the corresponding oracle-signed ``Fix`` commands (the tear-off
    pattern of flows/oracle.py), so the substitution is attested."""

    fixes: tuple  # of Fix

    def __post_init__(self):
        object.__setattr__(self, "fixes", tuple(self.fixes))


class UniversalContract(Contract):
    """The one contract that verifies every arrangement
    (UniversalContract.kt verify:182-245)."""

    @property
    def legal_contract_reference(self) -> SecureHash:
        return SecureHash.sha256(b"corda_tpu/universal-contract")

    def verify(self, tx: TransactionForContract) -> None:
        cmd = select_command(tx.commands, UniversalCommand)
        value = cmd.value

        if isinstance(value, UIssue):
            with require_that() as req:
                req("the transaction has no input states", not tx.inputs)
                out = self._single_state(tx.outputs, "output")
                req("the transaction is signed by all liable parties",
                    liable_parties(out.details) <= frozenset(cmd.signers))

        elif isinstance(value, UMove):
            in_state = self._single_state(tx.inputs, "input")
            out = self._single_state(tx.outputs, "output")
            with require_that() as req:
                req("the transaction is signed by all liable parties",
                    liable_parties(out.details) <= frozenset(cmd.signers))
                req("output state reflects the move command",
                    replace_party(in_state.details, value.old, value.new)
                    == out.details)

        elif isinstance(value, UAction):
            in_state = self._single_state(tx.inputs, "input")
            arr = self._reducible(in_state.details)
            action = actions_of(arr).get(value.name)
            with require_that() as req:
                req("action must be defined", action is not None)
                req("action must be timestamped", tx.timestamp is not None)
                actor_keys = {p.owning_key for p in action.actors}
                req("action must be authorized",
                    any(s in actor_keys for s in cmd.signers))
                req("condition must be met",
                    eval_condition(tx, action.condition))
                # Single-state model as in the reference (verify:206-210):
                # exercising an action consumes the whole input arrangement.
                req("exercising an action must consume the whole state",
                    extract_remainder(arr, action) == ZERO)
            result = self._validate_transfers(tx, action.arrangement)
            # Compare outputs to the action result as a MULTISET of flattened
            # parts, not via all_of: All's frozenset collapses duplicates, so
            # outputs [X, Y, Y] would compare equal to All{X, Y} and an
            # authorized actor could mint duplicate obligation states
            # (round-2 advisor finding). ==-based find-and-remove matching,
            # NOT sorted(key=repr): equal arrangements holding frozenset
            # fields can repr in different element orders, and a repr-keyed
            # sort would then misalign equal multisets and nondeterministically
            # reject valid transactions across nodes (round-3 advisor
            # finding — a consensus hazard on the notary path).
            out_details = []
            for o in tx.outputs:
                if not isinstance(o, UniversalState):
                    raise ValueError("output state is not a UniversalState")
                out_details.append(o.details)
            expected = list(_flat_parts(result))
            produced = [p for d in out_details for p in _flat_parts(d)]
            with require_that() as req:
                req("output states must match action result state "
                    "part-for-part", _multiset_equal(produced, expected))

        elif isinstance(value, UApplyFixes):
            in_state = self._single_state(tx.inputs, "input")
            out = self._single_state(tx.outputs, "output")
            arr = self._reducible(in_state.details)
            fixes = {f.of: f.value for f in value.fixes}
            # FixOf -> set of leaf keys that signed a Fix command with that
            # exact (of, value). Only signatures over the matching value
            # count as attestation.
            attested: dict[FixOf, set] = {}
            for c in tx.commands:
                if isinstance(c.value, Fix) \
                        and fixes.get(c.value.of) == c.value.value:
                    leaves = attested.setdefault(c.value.of, set())
                    for signer in c.signers:
                        leaves |= set(signer.keys)
            used: set = set()
            oracles: dict = {}
            expected = replace_fixings(arr, fixes, used, oracles)
            with require_that() as req:
                req("relevant fixing must be included", used == set(fixes))
                req("every fix is attested by a Fix command signed by the "
                    "oracle the product pins for its source", all(
                        oracles[of].is_fulfilled_by(attested.get(of, set()))
                        for of in used))
                req("output state reflects the fix command",
                    expected == out.details)
        else:
            raise ValueError(f"Unrecognised command {type(value).__name__}")

    @staticmethod
    def _single_state(states, what: str) -> "UniversalState":
        if len(states) != 1:
            raise ValueError(f"expected exactly one {what} state")
        state = states[0]
        if not isinstance(state, UniversalState):
            raise ValueError(f"{what} state is not a UniversalState")
        return state

    @staticmethod
    def _reducible(details: Arrangement) -> Arrangement:
        """An input arrangement ready for action lookup: Actions directly, or
        a RollOut expanded by one period (verify:188-193)."""
        if isinstance(details, Actions):
            return details
        if isinstance(details, RollOut):
            return reduce_rollout(details)
        raise ValueError(
            f"unexpected arrangement {type(details).__name__}: only Actions "
            "or RollOut states can transition")

    def _validate_transfers(self, tx: TransactionForContract,
                            arrangement: Arrangement) -> Arrangement:
        """Evaluate every immediate transfer amount to a non-negative
        constant (UniversalContract.kt validateImmediateTransfers:92-100)."""
        if isinstance(arrangement, Transfer):
            amount = eval_amount(tx, arrangement.amount)
            with require_that() as req:
                req("transferred quantity is non-negative", amount >= 0)
            return Transfer(Const(amount), arrangement.currency,
                            arrangement.from_party, arrangement.to_party)
        if isinstance(arrangement, All):
            return all_of(*(self._validate_transfers(tx, a)
                            for a in arrangement.arrangements))
        return arrangement


UNIVERSAL_PROGRAM = UniversalContract()


@register
@dataclass(frozen=True)
class UniversalState(ContractState):
    """The on-ledger holder of an arrangement (UniversalContract.kt State)."""

    parts: tuple  # of CompositeKey (participants)
    details: Arrangement

    def __post_init__(self):
        object.__setattr__(self, "parts", tuple(self.parts))

    @property
    def contract(self) -> Contract:
        return UNIVERSAL_PROGRAM

    @property
    def participants(self) -> list[CompositeKey]:
        return list(self.parts)


# --- transaction generation (UniversalContract.kt generateIssue:311-316) ----


def generate_issue(arrangement: Arrangement, at: PartyAndReference,
                   notary: Party) -> TransactionBuilder:
    builder = TransactionBuilder(notary=notary)
    keys = sorted(involved_parties(arrangement),
                  key=lambda k: k.to_base58_string())
    builder.add_output_state(
        TransactionState(UniversalState(tuple(keys), arrangement), notary))
    # Declare every liable party as a command signer (verify demands their
    # signatures; two-sided products like swaps have several, so declaring
    # only the issuer — as the reference's generateIssue:311-316 does —
    # would make the issue unverifiable by counterparties).
    signers = sorted(liable_parties(arrangement) | {at.party.owning_key},
                     key=lambda k: k.to_base58_string())
    builder.add_command(UIssue(), *signers)
    return builder
