"""Contract-facing transaction view and verification exceptions.

Capability match for the reference's TransactionForContract and
TransactionVerificationException hierarchy (reference:
core/src/main/kotlin/net/corda/core/contracts/TransactionVerification.kt).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

from ..crypto.hashes import SecureHash
from ..crypto.party import Party
from .structures import (
    Attachment,
    AuthenticatedObject,
    ContractState,
    Timestamp,
)


class TransactionVerificationException(Exception):
    """Base for all platform-level transaction verification failures
    (reference: TransactionVerification.kt:30-80)."""

    def __init__(self, tx_id: SecureHash | None, message: str):
        super().__init__(message)
        self.tx_id = tx_id


class ContractRejection(TransactionVerificationException):
    def __init__(self, tx_id, contract, cause: Exception):
        super().__init__(tx_id, f"Contract verification failed: {cause}")
        self.contract = contract
        self.cause = cause


class MoreThanOneNotary(TransactionVerificationException):
    def __init__(self, tx_id):
        super().__init__(tx_id, "More than one notary in the transaction inputs")


class SignersMissing(TransactionVerificationException):
    def __init__(self, tx_id, missing):
        super().__init__(tx_id, f"Signers missing: {missing}")
        self.missing = missing


class NotaryChangeInWrongTransactionType(TransactionVerificationException):
    def __init__(self, tx_id, output_notary):
        super().__init__(
            tx_id, f"Outputs posted to a different notary {output_notary} in a general transaction"
        )
        self.output_notary = output_notary


class InvalidNotaryChange(TransactionVerificationException):
    def __init__(self, tx_id):
        super().__init__(tx_id, "Invalid notary change: states modified beyond the notary field")


class TransactionMissingEncumbranceException(TransactionVerificationException):
    INPUT = "input"
    OUTPUT = "output"

    def __init__(self, tx_id, missing: int, direction: str):
        super().__init__(tx_id, f"Missing required encumbrance {missing} in {direction}s")
        self.missing = missing
        self.direction = direction


class TransactionResolutionException(Exception):
    """An input StateRef points at a transaction we don't have
    (reference: Structures.kt TransactionResolutionException)."""

    def __init__(self, hash_: SecureHash):
        super().__init__(f"Transaction resolution failure for {hash_}")
        self.hash = hash_


class AttachmentResolutionException(Exception):
    def __init__(self, hash_: SecureHash):
        super().__init__(f"Attachment resolution failure for {hash_}")
        self.hash = hash_


@dataclass(frozen=True)
class InOutGroup:
    """Matched input/output states sharing a grouping key
    (TransactionVerification.kt:85)."""

    inputs: tuple[ContractState, ...]
    outputs: tuple[ContractState, ...]
    grouping_key: Any


@dataclass(frozen=True)
class TransactionForContract:
    """The stripped-down transaction view handed to Contract.verify
    (TransactionVerification.kt:15-84)."""

    inputs: tuple[ContractState, ...]
    outputs: tuple[ContractState, ...]
    attachments: tuple[Attachment, ...]
    commands: tuple[AuthenticatedObject, ...]
    id: SecureHash
    notary: Party | None
    timestamp: Timestamp | None = None
    in_states: tuple = field(default=())  # reserved

    def group_states(
        self, of_type: type, grouping_key: Callable[[ContractState], Any]
    ) -> list[InOutGroup]:
        """Fungible-state verification utility (TransactionVerification.kt:48-84):
        partition inputs and outputs by a key (e.g. (currency, issuer)) so each
        group can be conservation-checked independently."""
        in_groups: dict[Any, list[ContractState]] = {}
        out_groups: dict[Any, list[ContractState]] = {}
        for s in self.inputs:
            if isinstance(s, of_type):
                in_groups.setdefault(grouping_key(s), []).append(s)
        for s in self.outputs:
            if isinstance(s, of_type):
                out_groups.setdefault(grouping_key(s), []).append(s)
        result = []
        for k in dict.fromkeys(list(in_groups) + list(out_groups)):
            result.append(
                InOutGroup(tuple(in_groups.get(k, ())), tuple(out_groups.get(k, ())), k)
            )
        return result

    def get_timestamp_by(self, timestamp_authority: Party) -> Timestamp | None:
        """The timestamp, but only if this tx is notarised by the given
        authority (TransactionVerification.kt timestamp accessor)."""
        if self.notary == timestamp_authority:
            return self.timestamp
        return None
