"""L0 host-side cryptography: hashing, signing, key trees, Merkle proofs."""

from .hashes import SecureHash  # noqa: F401
from .keys import (  # noqa: F401
    DigitalSignature,
    KeyPair,
    NULL_PUBLIC_KEY,
    NULL_SIGNATURE,
    PrivateKey,
    PublicKey,
    SignatureError,
    by_keys,
)
from .composite import (  # noqa: F401
    CompositeKey,
    CompositeKeyLeaf,
    CompositeKeyNode,
    all_keys,
)
from .merkle import (  # noqa: F401
    MerkleDuplicatedLeaf,
    MerkleLeaf,
    MerkleNode,
    MerkleTree,
    MerkleTreeException,
    PartialMerkleTree,
)
from .party import Party, PartyAndReference  # noqa: F401
from .signed_data import SignedData  # noqa: F401
