"""L0 host-side cryptography: hashing, signing, key trees, Merkle proofs."""

from corda_tpu.crypto.hashes import SecureHash  # noqa: F401
