"""Asynchronous pipelined verification: the device feeder thread.

The run loop is single-threaded by design (the cooperative-pump race
discipline: socket/worker threads enqueue only, flow logic runs on the
loop). Verification used to run synchronously inside the per-round
db.batch() transaction, so Raft heartbeats, inbound messages and
checkpoints all stalled behind the verifier — and because every round
flushed its own accumulation, real flagship traffic almost never reached
device_min_sigs and the device sat idle (round-5 VERDICT: kernel 292k
sigs/s, end-to-end 3.9k with device_batches=0).

This module decouples the two with ONE owned crossing:

  run loop  --submit(jobs, context)-->  feeder thread  (owns the device)
  run loop  <--drain()---------------  completion queue (the only way back)

The run loop SUBMITS an accumulated batch and immediately continues; the
feeder thread calls ``verifier.verify_batch`` (the GIL is released inside
the native host tier and XLA dispatch, so the loop genuinely overlaps);
finished handles post to a thread-safe completion queue the NEXT round
drains to resume the parked flows. Flow state is never touched off-loop:
the feeder sees only VerifyJob tuples and writes only to its own handle.

Bounded in-flight depth (default 2 = double buffering: one batch on the
device, one filling) lets batches accumulate ACROSS rounds without the
backlog growing unboundedly, which is exactly what pushes real traffic
over the device crossover.

Crash contract: a submitted batch lives only in memory. The waiting flows
were parked WITHOUT recording a verify outcome, so a crash replays them
from their last durable checkpoint and they re-yield the verify — the
existing at-least-once replay path — meaning lost in-flight results cost
a re-verify, never a wrong answer.

AdaptiveCrossover replaces blind trust in the static device_min_sigs env
knob: it measures observed host-tier vs device-tier sigs/s from completed
handles and walks the verifier's effective crossover toward whichever
tier is actually faster on this host/backend (bounded both ways).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Sequence

from .provider import BatchVerifier, VerifyJob
from ..obs import trace as _obs
from ..testing import faults as _faults


class VerifyBatchHandle:
    """One submitted batch crossing the thread boundary. The submitting
    (run-loop) thread owns ``jobs``/``context``; the feeder thread fills
    ``ok``/``error``/timing and never touches the handle again after
    posting it to the completion queue."""

    __slots__ = ("jobs", "context", "submitted_at", "started_at",
                 "finished_at", "ok", "error", "tier")

    def __init__(self, jobs: Sequence[VerifyJob], context: Any):
        self.jobs = jobs
        self.context = context
        self.submitted_at = time.perf_counter()
        self.started_at = 0.0
        self.finished_at = 0.0
        self.ok = None  # bool[N] on success
        self.error: BaseException | None = None
        self.tier = "host"  # "device" when the verifier dispatched the kernel

    @property
    def queue_wait_s(self) -> float:
        """Time the batch sat behind earlier in-flight work."""
        return max(0.0, self.started_at - self.submitted_at)

    @property
    def verify_wall_s(self) -> float:
        """Wall time inside verify_batch on the feeder thread."""
        return max(0.0, self.finished_at - self.started_at)


class AdaptiveCrossover:
    """Tunes the verifier's effective device_min_sigs from OBSERVED rates.

    EWMA sigs/s per tier, fed at batch completion. With evidence on both
    tiers: a device measurably faster than the host lowers the crossover
    (feed it smaller batches); a device slower than the host raises it
    (stop paying the dispatch tax). Hysteresis bands (x1.25 up / x0.8
    down) and multiplicative steps keep it from oscillating; hard floor
    and ceiling keep a pathological sample from pinning routing."""

    ALPHA = 0.3  # EWMA weight for the newest observation
    MIN_SAMPLE_SIGS = 32  # tiny batches measure overhead, not throughput
    FLOOR = 64

    def __init__(self, verifier: BatchVerifier):
        self.verifier = verifier
        static = getattr(verifier, "device_min_sigs", None)
        self.enabled = static is not None
        self.static_min_sigs = static if static else 0
        self.ceiling = max(8 * (static or 0), 8192)
        self.host_rate = 0.0
        self.device_rate = 0.0
        self.adjustments = 0

    def observe(self, handle: VerifyBatchHandle) -> None:
        if not self.enabled or handle.error is not None:
            return
        n = len(handle.jobs)
        wall = handle.verify_wall_s
        if n < self.MIN_SAMPLE_SIGS or wall <= 0.0:
            return
        rate = n / wall
        if handle.tier == "device":
            self.device_rate = (rate if not self.device_rate else
                                self.ALPHA * rate
                                + (1 - self.ALPHA) * self.device_rate)
        else:
            self.host_rate = (rate if not self.host_rate else
                              self.ALPHA * rate
                              + (1 - self.ALPHA) * self.host_rate)
        self._retune()

    def _retune(self) -> None:
        if not (self.host_rate and self.device_rate):
            return  # no evidence on one tier yet: keep the static policy
        current = self.verifier.device_min_sigs
        if self.device_rate > 1.25 * self.host_rate:
            target = max(self.FLOOR, int(current * 0.75))
        elif self.device_rate < 0.8 * self.host_rate:
            target = min(self.ceiling, int(current * 1.5))
        else:
            return
        if target != current:
            self.verifier.device_min_sigs = target
            self.adjustments += 1

    @property
    def effective_min_sigs(self) -> int | None:
        return (self.verifier.device_min_sigs if self.enabled else None)


_SENTINEL = object()


class AsyncVerifyService:
    """The feeder-thread pipeline between the run loop and the verifier.

    Threading model (the ONLY sanctioned crossings):
      * submit(): run loop -> submit queue. Increments the run-loop-owned
        in-flight counter (no lock needed: only the loop reads/writes it).
      * feeder thread: pops, calls verify_batch, posts the finished handle
        to the completion queue. It never touches flow or node state.
      * drain(): run loop pops completed handles non-blocking, decrements
        in-flight, feeds the adaptive crossover, returns the handles for
        delivery on the loop.

    The feeder thread starts lazily on first submit (a sync-mode or idle
    node never carries a thread) and is a daemon joined with a bounded
    timeout at close() — a live thread inside XLA C++ at interpreter
    finalization aborts, the same hazard the boot warm thread documents.
    """

    def __init__(self, verifier: BatchVerifier, depth: int = 2,
                 adaptive: bool = True):
        if depth < 1:
            raise ValueError(f"async verify depth must be >= 1, got {depth}")
        self.verifier = verifier
        self.depth = depth
        self.adaptive = AdaptiveCrossover(verifier) if adaptive else None
        self._submit_q: queue.SimpleQueue = queue.SimpleQueue()
        self._done_q: queue.SimpleQueue = queue.SimpleQueue()
        self._thread: threading.Thread | None = None
        self._closed = False
        # Run-loop-owned (single-threaded) pipeline accounting:
        self.in_flight = 0
        self.submitted_batches = 0
        self.submitted_sigs = 0
        self.completed_batches = 0
        self.completed_sigs = 0
        self.failed_batches = 0
        self.queue_wait_s = 0.0
        self.verify_wall_s = 0.0

    # -- run-loop side -----------------------------------------------------

    def can_submit(self) -> bool:
        """Is there pipeline room? False = keep accumulating this round."""
        return not self._closed and self.in_flight < self.depth

    def target_sigs(self, max_sigs: int) -> int:
        """The submit threshold for the accumulate-across-rounds gate: a
        READY device verifier wants batches at the (possibly adaptively
        tuned) crossover so submitted work actually engages the kernel;
        everything else keeps the classic max_sigs policy. The max-wait
        deadline still bounds accumulation either way."""
        min_sigs = getattr(self.verifier, "device_min_sigs", None)
        if min_sigs is None:
            return max_sigs
        gate = getattr(self.verifier, "device_gate", None)
        if gate is not None and not gate.is_set():
            return max_sigs  # cold device: batches host-route anyway
        return max(1, min(max_sigs, min_sigs))

    def submit(self, jobs: Sequence[VerifyJob], context: Any) -> VerifyBatchHandle:
        if self._closed:
            raise RuntimeError("AsyncVerifyService is closed")
        handle = VerifyBatchHandle(jobs, context)
        self.in_flight += 1
        self.submitted_batches += 1
        self.submitted_sigs += len(jobs)
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._feeder, daemon=True, name="verify-feeder")
            self._thread.start()
        self._submit_q.put(handle)
        return handle

    def drain(self) -> list[VerifyBatchHandle]:
        """Pop every completed handle (non-blocking); caller delivers."""
        done: list[VerifyBatchHandle] = []
        while True:
            try:
                handle = self._done_q.get_nowait()
            except queue.Empty:
                break
            self.in_flight -= 1
            self.completed_batches += 1
            self.completed_sigs += len(handle.jobs)
            self.queue_wait_s += handle.queue_wait_s
            self.verify_wall_s += handle.verify_wall_s
            if handle.error is not None:
                self.failed_batches += 1
            elif self.adaptive is not None:
                self.adaptive.observe(handle)
            if _obs.ACTIVE is not None:
                self._record_batch_spans(handle)
            done.append(handle)
        return done

    def _record_batch_spans(self, handle: VerifyBatchHandle) -> None:
        """queue_wait + device_verify batch spans, fanned IN: one device
        batch serves many transactions, so the spans carry every member
        flow's trace id (attrs["member_traces"]) and the collector
        attributes the batch's wall time to each of them. The handle's
        perf_counter durations are re-anchored onto the epoch clock ending
        at drain time (the skew — the sub-ms the handle sat in the done
        queue — is noise next to a device batch)."""
        members = []
        for ctx in handle.context or ():
            fsm = ctx[0] if isinstance(ctx, tuple) else ctx
            tid = getattr(fsm, "trace_id", None)
            if tid is not None:
                members.append(tid.hex())
        if not members:
            return
        now = _obs.now()
        wall = handle.verify_wall_s
        wait = handle.queue_wait_s
        attrs = {"member_traces": members, "tier": handle.tier,
                 "sigs": len(handle.jobs)}
        _obs.record("queue_wait", now - wall - wait, now - wall, attrs=attrs)
        _obs.record("device_verify", now - wall, now, attrs=attrs)
        route_s = getattr(self.verifier, "last_route_s", None)
        if handle.tier == "device" and route_s is not None:
            # Federation tier: decompose the device window into the
            # routing decision and the winning host's round trip (which
            # itself contains that host's sidecar_wait/sidecar_verify).
            # Same newest-reply skew caveat as the sidecar spans below.
            route_s = min(float(route_s), wall)
            remote_s = min(float(getattr(self.verifier, "last_remote_s",
                                         0.0) or 0.0), wall)
            _obs.record("federation_route", now - wall,
                        now - wall + route_s, attrs=attrs)
            _obs.record("remote_verify", now - remote_s, now, attrs=attrs)
        sc_wait = getattr(self.verifier, "last_wait_s", None)
        if handle.tier == "device" and sc_wait is not None:
            # Sidecar tier: split the batch's device window into the
            # server-side coalesce wait and verify wall reported in the
            # newest reply (same fan-in attrs). With depth>1 the newest
            # reply can belong to a sibling batch — sub-ms skew on spans
            # whose job is attribution, not timing truth.
            sc_verify = float(getattr(self.verifier, "last_verify_s", 0.0)
                              or 0.0)
            sc_wait = min(float(sc_wait), max(wall - sc_verify, 0.0))
            _obs.record("sidecar_wait", now - wall, now - wall + sc_wait,
                        attrs=attrs)
            _obs.record("sidecar_verify", now - sc_verify, now, attrs=attrs)

    def stats(self) -> dict:
        """Pipeline counters for node_metrics / loadtest stamps."""
        out = {
            "depth": self.depth,
            "in_flight": self.in_flight,
            "submitted_batches": self.submitted_batches,
            "submitted_sigs": self.submitted_sigs,
            "completed_batches": self.completed_batches,
            "completed_sigs": self.completed_sigs,
            "failed_batches": self.failed_batches,
            "queue_wait_s": round(self.queue_wait_s, 6),
            "verify_wall_s": round(self.verify_wall_s, 6),
        }
        if self.adaptive is not None and self.adaptive.enabled:
            out["effective_min_sigs"] = self.adaptive.effective_min_sigs
            out["static_min_sigs"] = self.adaptive.static_min_sigs
            out["adaptive_adjustments"] = self.adaptive.adjustments
            out["host_sigs_per_sec"] = round(self.adaptive.host_rate, 1)
            out["device_sigs_per_sec"] = round(self.adaptive.device_rate, 1)
        return out

    def close(self, timeout: float = 30.0) -> bool:
        """Stop accepting work and join the feeder (bounded — close must
        never hang on a wedged device). Returns True when the thread is
        down (or never started). In-flight results may be lost; the
        at-least-once replay contract makes that safe."""
        self._closed = True
        thread = self._thread
        if thread is None:
            return True
        self._submit_q.put(_SENTINEL)
        thread.join(timeout=timeout)
        return not thread.is_alive()

    # -- feeder side -------------------------------------------------------

    def _feeder(self) -> None:
        while True:
            item = self._submit_q.get()
            if item is _SENTINEL:
                return
            item.started_at = time.perf_counter()
            # Tier attribution by counter delta: this thread is the only
            # verify_batch caller in async mode, so the delta is exact.
            before = getattr(self.verifier, "device_batches", 0) or 0
            try:
                if _faults.ACTIVE is not None:
                    act = _faults.ACTIVE.fire("verify.device")
                    if act is not None:
                        action, delay_s = act
                        if action == "slow" and delay_s > 0:
                            time.sleep(delay_s)
                        elif action in ("fail", "raise"):
                            raise RuntimeError(
                                "fault injected: device verifier failure")
                item.ok = self.verifier.verify_batch(item.jobs)
            except BaseException as e:  # noqa: BLE001 — crossed to the loop
                # The exception must cross back to the run loop and reject
                # the waiting flows; swallowing it would hang them forever.
                item.error = e
            after = getattr(self.verifier, "device_batches", 0) or 0
            item.tier = "device" if after > before else "host"
            item.finished_at = time.perf_counter()
            self._done_q.put(item)
