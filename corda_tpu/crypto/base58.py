"""Base58 encoding (Bitcoin alphabet).

Capability match for the reference's Base58.java (reference:
core/src/main/java/net/corda/core/crypto/Base58.java) — used for rendering
public keys and naming per-peer message queues
(reference: node/.../messaging/ArtemisMessagingComponent.kt:31-38).
"""

from __future__ import annotations

_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_INDEX = {c: i for i, c in enumerate(_ALPHABET)}


def encode(data: bytes) -> str:
    n = int.from_bytes(data, "big")
    out = []
    while n > 0:
        n, rem = divmod(n, 58)
        out.append(_ALPHABET[rem])
    # Preserve leading zero bytes as '1' characters.
    pad = 0
    for b in data:
        if b == 0:
            pad += 1
        else:
            break
    return "1" * pad + "".join(reversed(out))


def decode(s: str) -> bytes:
    n = 0
    for c in s:
        if c not in _INDEX:
            raise ValueError(f"invalid base58 character: {c!r}")
        n = n * 58 + _INDEX[c]
    pad = 0
    for c in s:
        if c == "1":
            pad += 1
        else:
            break
    body = n.to_bytes((n.bit_length() + 7) // 8, "big") if n else b""
    return b"\x00" * pad + body
