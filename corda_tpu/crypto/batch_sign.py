"""Columnar batch Ed25519 signing — the ingest mirror of the verify plane.

The verify direction already runs columnar (provider.py packs (N, 32)
key/msg/sig arrays and dispatches one batch to `_cverify.c` or the device);
the SUBMIT direction still paid per-item Python: one `fast_ed25519.sign`
call per signature, which on a host without the `cryptography` wheel
degrades to the ~250 ops/s pure-Python oracle — the measured core of the
~150 tx/s-per-process loadgen ceiling (ROADMAP item 2). This module packs
a whole corpus of (seed, message) jobs into two contiguous n*32-byte
buffers — the same word-major packing discipline as `_cverify.c`'s
pack_words, one layer up — and signs them in ONE native call with the GIL
released (`_cverify.sign_many`, pthread fan-out).

Byte-identity: RFC 8032 signing is fully deterministic, so libcrypto's
output is bit-identical to `fast_ed25519.sign` (and the `ref_ed25519`
oracle) — the same argument fast_ed25519 makes for OpenSSL, one batch
wider. There is no accept-set subtlety as in verify (no S < L corner on
the signing side); parity is conformance-tested per width in
tests/test_batch_sign.py. When the native module is unavailable (no
compiler, CORDA_TPU_NO_NATIVE=1) or a message is not 32 bytes, jobs fall
back to `fast_ed25519.sign` per item — identical bytes, reference speed.
"""

from __future__ import annotations

from . import fast_ed25519


def _native():
    # Deferred, memoised import: the firehose imports this module inside
    # node processes that may predate the compiler toolchain.
    global _NATIVE, _NATIVE_TRIED
    if not _NATIVE_TRIED:
        _NATIVE_TRIED = True
        try:
            from ..native import load_cverify

            mod = load_cverify()
            _NATIVE = getattr(mod, "sign_many", None)  # old .so: absent
        except Exception:
            _NATIVE = None
    return _NATIVE


_NATIVE = None
_NATIVE_TRIED = False


def pack_jobs(seeds, msgs) -> "tuple[bytes, bytes] | None":
    """Columnar packing: (seeds, msgs) job lists -> two contiguous
    n*32-byte buffers (the `_cverify.c`-parity layout, lane i at byte
    offset 32*i). Returns None when any job is ineligible for the
    fixed-width native path (seed or message not exactly 32 bytes) —
    ineligible batches take the per-item fallback, never a truncated
    buffer."""
    if any(len(s) != 32 for s in seeds) or any(len(m) != 32 for m in msgs):
        return None
    return b"".join(bytes(s) for s in seeds), b"".join(
        bytes(m) for m in msgs)


def sign_batch(seeds, msgs) -> list[bytes]:
    """Sign N (seed, message) jobs columnar; returns N 64-byte signatures
    in job order, byte-identical to calling fast_ed25519.sign per job.

    One native call signs the whole batch with the GIL released; the
    node's transport/bridge threads keep moving while the corpus signs.
    Any native failure (or ineligible job shapes) re-signs on the Python
    path — deterministic signing means the fallback is byte-identical,
    just slower, so a batch can never silently carry a wrong signature.
    """
    if len(seeds) != len(msgs):
        raise ValueError(
            f"sign_batch length mismatch: {len(seeds)} seeds, "
            f"{len(msgs)} msgs")
    n = len(seeds)
    if n == 0:
        return []
    native = _native()
    if native is not None:
        packed = pack_jobs(seeds, msgs)
        if packed is not None:
            try:
                sigs = native(packed[0], packed[1])
                return [sigs[64 * i:64 * i + 64] for i in range(n)]
            except ValueError:
                pass  # malformed batch or libcrypto fault: Python re-sign
    return [fast_ed25519.sign(seeds[i], msgs[i]) for i in range(n)]


def sign_builders(builders, keypairs_per_builder) -> int:
    """Batch-sign a corpus of TransactionBuilders: ONE columnar sign over
    every (builder, key) job, then attach signatures in exactly the order
    a per-builder `sign_with` loop would — the output SignedTransactions
    are byte-identical to the per-tx path (parity-tested).

    `keypairs_per_builder` is a parallel sequence: builders[i] is signed
    by every KeyPair in keypairs_per_builder[i], in order. Returns the
    number of signatures attached."""
    from .keys import DigitalSignature

    seeds: list[bytes] = []
    msgs: list[bytes] = []
    slots: list = []  # (builder, keypair) parallel to the job arrays
    for builder, keys in zip(builders, keypairs_per_builder):
        # Forces the wire build (Merkle id) exactly as sign_with's
        # `self._wire_cached().id` does; the dedupe check below mirrors
        # sign_with's "already signed by this key" guard.
        msg = builder._wire_cached().id.bytes
        for kp in keys:
            if any(s.by == kp.public for s in builder.current_sigs):
                continue
            seeds.append(kp.private.seed)
            msgs.append(msg)
            slots.append((builder, kp))
    sigs = sign_batch(seeds, msgs)
    for (builder, kp), sig in zip(slots, sigs):
        builder.current_sigs.append(
            DigitalSignature.WithKey(bytes=sig, by=kp.public))
    return len(slots)
