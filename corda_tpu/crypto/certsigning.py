"""Network permissioning: CSR submission, approval, and polling.

Capability match for the reference's certificate-signing utilities
(reference: node/src/main/kotlin/net/corda/node/utilities/certsigning/
CertificateSigner.kt:28-80 — submit a PKCS#10 CSR to the network's
permissioning server, poll until approved, install the returned chain;
HTTPCertificateSigningService.kt — the HTTP wire protocol: POST
/api/certificate -> request id, GET /api/certificate/<id> -> 204 until
approved / the chain once approved / 401 when rejected;
CertificateSigningService.kt — the service interface).

This module supplies BOTH halves so a dev network is self-contained:

- :class:`CertificateSigningServer` — the authority. Holds the (dev) CA key,
  queues CSRs for approval (auto-approve for dev networks, explicit
  ``approve``/``reject`` for the doorman workflow the reference polls
  against), and serves signed chains as a PEM bundle (client cert first,
  root last — the chain order CertificateSigner.kt assumes).
- :class:`HttpCertificateSigningService` — the client-side service.
- :class:`CertificateSigner` — the node-side driver: create-or-load the
  node's TLS key, submit a CSR for its legal name, poll, install
  ``tls-cert.pem`` + ``ca.pem`` into the node directory (the same file
  layout ``x509.generate_dev_tls_material`` produces, so a node can swap
  dev-mode self-provisioning for doorman-issued certificates without any
  other change).
"""

from __future__ import annotations

import datetime
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib import error as urlerror
from urllib import request as urlrequest

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID

_VALIDITY = datetime.timedelta(days=365)
CLIENT_VERSION = "1.0"


class CertificateRequestRejected(Exception):
    """The authority rejected the CSR (HTTP 401 in the reference protocol)."""


class CertificateSigningServer:
    """The permissioning authority (the reference's 'doorman' that
    HTTPCertificateSigningService talks to)."""

    def __init__(self, ca_cert_path: str | Path, ca_key_path: str | Path,
                 host: str = "127.0.0.1", port: int = 0,
                 auto_approve: bool = False):
        self._ca_cert = x509.load_pem_x509_certificate(
            Path(ca_cert_path).read_bytes())
        self._ca_key = serialization.load_pem_private_key(
            Path(ca_key_path).read_bytes(), password=None)
        self._lock = threading.Lock()
        self._pending: dict[str, x509.CertificateSigningRequest] = {}
        self._issued: dict[str, bytes] = {}
        self._rejected: set[str] = set()
        self.auto_approve = auto_approve
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_POST(self):
                if self.path != "/api/certificate":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    request_id = server.submit(self.rfile.read(length))
                except Exception as e:
                    self.send_error(400, str(e)[:200])
                    return
                body = request_id.encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                prefix = "/api/certificate/"
                if not self.path.startswith(prefix):
                    self.send_error(404)
                    return
                request_id = self.path[len(prefix):]
                status, body = server.poll(request_id)
                self.send_response(status)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.address = self._httpd.server_address
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.address[0]}:{self.address[1]}"

    # -- authority operations ---------------------------------------------

    def submit(self, csr_der: bytes) -> str:
        csr = x509.load_der_x509_csr(csr_der)
        if not csr.is_signature_valid:
            raise ValueError("CSR signature invalid")
        request_id = uuid.uuid4().hex
        with self._lock:
            self._pending[request_id] = csr
            if self.auto_approve:
                self._approve_locked(request_id)
        return request_id

    def approve(self, request_id: str) -> None:
        with self._lock:
            self._approve_locked(request_id)

    def reject(self, request_id: str) -> None:
        with self._lock:
            self._pending.pop(request_id, None)
            self._rejected.add(request_id)

    def pending_requests(self) -> dict[str, str]:
        """request id -> subject common name, for a doorman operator UI.
        A CSR without a CN (submit only checks the signature) lists as its
        full RFC4514 subject rather than crashing the whole listing."""
        out = {}
        with self._lock:
            for rid, csr in self._pending.items():
                cns = csr.subject.get_attributes_for_oid(NameOID.COMMON_NAME)
                out[rid] = cns[0].value if cns else csr.subject.rfc4514_string()
        return out

    def _approve_locked(self, request_id: str) -> None:
        csr = self._pending.pop(request_id, None)
        if csr is None:
            raise KeyError(f"unknown or already-handled request {request_id}")
        now = datetime.datetime.now(datetime.timezone.utc)
        cert = (
            x509.CertificateBuilder()
            .subject_name(csr.subject)
            .issuer_name(self._ca_cert.subject)
            .public_key(csr.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now).not_valid_after(now + _VALIDITY)
            .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                           critical=True)
            .add_extension(x509.ExtendedKeyUsage(
                [ExtendedKeyUsageOID.SERVER_AUTH,
                 ExtendedKeyUsageOID.CLIENT_AUTH]), critical=False)
            .sign(self._ca_key, hashes.SHA256())
        )
        # Chain order per CertificateSigner.kt: client first, root last.
        chain = cert.public_bytes(serialization.Encoding.PEM) \
            + self._ca_cert.public_bytes(serialization.Encoding.PEM)
        self._issued[request_id] = chain

    def poll(self, request_id: str) -> tuple[int, bytes]:
        """(http status, body) per the reference protocol."""
        with self._lock:
            if request_id in self._issued:
                return 200, self._issued[request_id]
            if request_id in self._rejected:
                return 401, b""
            if request_id in self._pending:
                return 204, b""
        return 404, b""

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)


class HttpCertificateSigningService:
    """Client half (HTTPCertificateSigningService.kt)."""

    def __init__(self, server_url: str):
        self.server_url = server_url.rstrip("/")

    def submit_request(self, csr_der: bytes) -> str:
        req = urlrequest.Request(
            f"{self.server_url}/api/certificate", data=csr_der,
            headers={"Content-Type": "application/octet-stream",
                     "Client-Version": CLIENT_VERSION}, method="POST")
        with urlrequest.urlopen(req, timeout=10) as resp:
            return resp.read().decode()

    def retrieve_certificates(self, request_id: str) -> list | None:
        """Signed chain once approved; None while pending; raises
        CertificateRequestRejected on 401."""
        try:
            with urlrequest.urlopen(
                    f"{self.server_url}/api/certificate/{request_id}",
                    timeout=10) as resp:
                if resp.status == 204:
                    return None
                return x509.load_pem_x509_certificates(resp.read())
        except urlerror.HTTPError as e:
            if e.code == 401:
                raise CertificateRequestRejected(
                    "certificate signing request has been rejected; contact "
                    "the network administrator") from None
            raise


class CertificateSigner:
    """Node-side provisioning loop (CertificateSigner.kt buildKeyStore)."""

    def __init__(self, node_dir: str | Path, legal_name: str,
                 service: HttpCertificateSigningService,
                 poll_interval: float = 1.0):
        self.node_dir = Path(node_dir)
        self.legal_name = legal_name
        self.service = service
        self.poll_interval = poll_interval

    def build_key_store(self, timeout: float = 60.0) -> dict[str, Path]:
        """Ensure tls-key/tls-cert/ca PEMs exist, obtaining the certificate
        from the signing service if absent. Idempotent across restarts."""
        self.node_dir.mkdir(parents=True, exist_ok=True)
        key_path = self.node_dir / "tls-key.pem"
        cert_path = self.node_dir / "tls-cert.pem"
        ca_path = self.node_dir / "ca.pem"
        if key_path.exists() and cert_path.exists() and ca_path.exists():
            return {"key": key_path, "cert": cert_path, "ca": ca_path}

        if key_path.exists():  # crashed mid-provisioning: reuse the key
            key = serialization.load_pem_private_key(
                key_path.read_bytes(), password=None)
        else:
            key = ec.generate_private_key(ec.SECP256R1())
            key_path.write_bytes(key.private_bytes(
                serialization.Encoding.PEM, serialization.PrivateFormat.PKCS8,
                serialization.NoEncryption()))

        csr = (
            x509.CertificateSigningRequestBuilder()
            .subject_name(x509.Name([
                x509.NameAttribute(NameOID.COMMON_NAME, self.legal_name),
                x509.NameAttribute(NameOID.ORGANIZATION_NAME, "corda_tpu"),
            ]))
            .sign(key, hashes.SHA256())
        )
        request_id = self.service.submit_request(
            csr.public_bytes(serialization.Encoding.DER))
        deadline = time.monotonic() + timeout
        while True:
            chain = self.service.retrieve_certificates(request_id)
            if chain is not None:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"certificate request {request_id} not approved within "
                    f"{timeout}s")
            time.sleep(self.poll_interval)
        cert_path.write_bytes(
            chain[0].public_bytes(serialization.Encoding.PEM))
        ca_path.write_bytes(
            chain[-1].public_bytes(serialization.Encoding.PEM))
        return {"key": key_path, "cert": cert_path, "ca": ca_path}
