"""Weighted-threshold composite keys.

Capability match for the reference's CompositeKey (reference:
core/src/main/kotlin/net/corda/core/crypto/CompositeKey.kt:22-145): a tree
whose leaves are public keys and whose interior nodes carry per-child weights
and a threshold. `is_fulfilled_by` checks whether a set of signing keys
reaches the threshold at every level — this is how "2-of-3 notary cluster" or
"CEO or 3 of 5 assistants" requirements are expressed.

Immutable and hashable so keys can live in sets/maps and serialize
canonically.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Iterator

from .keys import PublicKey


@dataclass(frozen=True)
class CompositeKey:
    """Base for the two node kinds; use CompositeKey.leaf / CompositeKey.node."""

    def is_fulfilled_by(self, keys: Iterable[PublicKey] | PublicKey) -> bool:
        if isinstance(keys, PublicKey):
            keys = {keys}
        return self._fulfilled(frozenset(keys))

    def _fulfilled(self, keys: frozenset[PublicKey]) -> bool:
        raise NotImplementedError

    @property
    def keys(self) -> frozenset[PublicKey]:
        raise NotImplementedError

    def contains_any(self, other_keys: Iterable[PublicKey]) -> bool:
        return bool(self.keys & set(other_keys))

    @property
    def single_key(self) -> PublicKey:
        ks = self.keys
        if len(ks) != 1:
            raise ValueError("The key is composed of more than one PublicKey primitive")
        return next(iter(ks))

    def to_base58_string(self) -> str:
        """Serialized form, base58-encoded (CompositeKey.kt:36-44)."""
        from . import base58
        from ..serialization.codec import serialize

        return base58.encode(serialize(self).bytes)

    @staticmethod
    def parse_from_base58(encoded: str) -> "CompositeKey":
        from . import base58
        from ..serialization.codec import deserialize

        key = deserialize(base58.decode(encoded))
        if not isinstance(key, CompositeKey):
            raise ValueError("encoded value is not a CompositeKey")
        return key

    @staticmethod
    def leaf(key: PublicKey) -> "CompositeKeyLeaf":
        return CompositeKeyLeaf(key)

    @staticmethod
    def node(
        threshold: int, children: list["CompositeKey"], weights: list[int]
    ) -> "CompositeKeyNode":
        return CompositeKeyNode(threshold, tuple(children), tuple(weights))

    class Builder:
        """Builder mirroring CompositeKey.Builder (CompositeKey.kt:110-135)."""

        def __init__(self):
            self._children: list[CompositeKey] = []
            self._weights: list[int] = []

        def add_key(self, key: "CompositeKey | PublicKey", weight: int = 1) -> "CompositeKey.Builder":
            if isinstance(key, PublicKey):
                key = CompositeKeyLeaf(key)
            self._children.append(key)
            self._weights.append(weight)
            return self

        def add_keys(self, *keys: "CompositeKey | PublicKey") -> "CompositeKey.Builder":
            for k in keys:
                self.add_key(k)
            return self

        def build(self, threshold: int | None = None) -> "CompositeKeyNode":
            t = threshold if threshold is not None else len(self._children)
            return CompositeKeyNode(t, tuple(self._children), tuple(self._weights))


@dataclass(frozen=True)
class CompositeKeyLeaf(CompositeKey):
    """A single public key at the leaf of the tree."""

    public_key: PublicKey

    def _fulfilled(self, keys: frozenset[PublicKey]) -> bool:
        return self.public_key in keys

    @property
    def keys(self) -> frozenset[PublicKey]:
        return frozenset({self.public_key})

    def __repr__(self) -> str:
        return self.public_key.to_string_short()


@dataclass(frozen=True)
class CompositeKeyNode(CompositeKey):
    """Interior node: children with weights; fulfilled when the summed weight
    of fulfilled children reaches the threshold (CompositeKey.kt:75-81)."""

    threshold: int
    children: tuple[CompositeKey, ...] = field(default_factory=tuple)
    weights: tuple[int, ...] = field(default_factory=tuple)

    def __post_init__(self):
        if len(self.children) != len(self.weights):
            raise ValueError("children and weights must have equal length")
        if not self.children:
            raise ValueError("composite key node must have at least one child")
        if self.threshold < 1:
            raise ValueError("threshold must be >= 1")
        if any(w < 1 for w in self.weights):
            raise ValueError("weights must be >= 1")

    def _fulfilled(self, keys: frozenset[PublicKey]) -> bool:
        total = sum(
            w for child, w in zip(self.children, self.weights) if child._fulfilled(keys)
        )
        return total >= self.threshold

    @property
    def keys(self) -> frozenset[PublicKey]:
        out: set[PublicKey] = set()
        for child in self.children:
            out |= child.keys
        return frozenset(out)

    def __repr__(self) -> str:
        return "(" + ", ".join(repr(c) for c in self.children) + ")"


def all_keys(composites: Iterable[CompositeKey]) -> frozenset[PublicKey]:
    """Union of leaf keys over several composite keys (CompositeKey.kt:143-145)."""
    out: set[PublicKey] = set()
    for ck in composites:
        out |= ck.keys
    return frozenset(out)


def iter_leaves(ck: CompositeKey) -> Iterator[CompositeKeyLeaf]:
    if isinstance(ck, CompositeKeyLeaf):
        yield ck
    elif isinstance(ck, CompositeKeyNode):
        for child in ck.children:
            yield from iter_leaves(child)
