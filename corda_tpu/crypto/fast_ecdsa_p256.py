"""OpenSSL-accelerated host ECDSA P-256 verify with oracle-exact semantics.

The pure-Python oracle (`ref_ecdsa_p256`) defines the authoritative
accept/reject set for the "ecdsa-p256" scheme tag but costs ~1 ms per
operation (textbook double-and-add), which would crawl on the mixed-scheme
batches the provider seam advertises (BASELINE.json north star; reference
scheme usage: core/src/main/kotlin/net/corda/core/crypto/
X509Utilities.kt:44-48). This is the host fast path, with a stricter
semantics argument than fast_ed25519 needs:

* **Structural gate is oracle-owned.** DER strictness differs between
  parsers in corner cases (long-form lengths, non-minimal integers,
  trailing bytes), and relying on OpenSSL's parser would make the accept
  set "whatever this OpenSSL build accepts". Instead every job is
  pre-parsed with the ORACLE's own parsers (`_parse_point`,
  `_parse_der_sig`, the [1, n-1] range checks). Anything they reject is
  rejected outright — bit-identical to the oracle, OpenSSL never consulted.

* **Scalar math is delegated.** Once the structure passed the oracle's
  gate, the remaining question is the ECDSA equation itself, on which both
  implementations agree by construction (same curve, same hash, no low-s
  rule on either side — JCA has none). An OpenSSL accept is therefore
  final. An OpenSSL reject *should* be authoritative too, but rejects are
  exceptional on honest traffic, so they re-check on the oracle anyway —
  the fallback costs nothing where it matters and makes the equivalence
  argument unconditional rather than resting on the no-divergence claim.

If the `cryptography` wheel is missing, every call degrades to the oracle —
same results, oracle speed (fast_ed25519 already warned loudly at import).
"""

from __future__ import annotations

import functools

from . import ref_ecdsa_p256

try:  # pragma: no cover - exercised implicitly by every test run
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives import hashes as _hashes

    _ECDSA_SHA256 = ec.ECDSA(_hashes.SHA256())  # reusable algorithm object
    _AVAILABLE = True
except Exception:  # pragma: no cover
    _AVAILABLE = False


def available() -> bool:
    """True when the OpenSSL fast path is active."""
    return _AVAILABLE


@functools.lru_cache(maxsize=65536)
def _public_key_cached(pub: bytes):
    # A node re-verifies the same small signer set (its peers' TLS identity
    # keys) all day; parsing is the dominant per-call cost after the math.
    # Raises on malformed input: lru_cache does not cache exceptions, and
    # callers only reach this after the oracle's point parser accepted.
    return ec.EllipticCurvePublicKey.from_encoded_point(ec.SECP256R1(), pub)


def verify(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    """Oracle-equivalent SHA256withECDSA verification (see module doc)."""
    pubkey, msg, sig = bytes(pubkey), bytes(msg), bytes(sig)
    if not _AVAILABLE:
        return ref_ecdsa_p256.verify(pubkey, msg, sig)
    # Oracle-owned structural gate: these three checks are exactly the
    # oracle's preamble, so any reject here IS the oracle's answer.
    if ref_ecdsa_p256._parse_point(pubkey) is None:
        return False
    parsed = ref_ecdsa_p256._parse_der_sig(sig)
    if parsed is None:
        return False
    r, s = parsed
    if not (1 <= r < ref_ecdsa_p256.N and 1 <= s < ref_ecdsa_p256.N):
        return False
    try:
        _public_key_cached(pubkey).verify(sig, msg, _ECDSA_SHA256)
        return True  # structure passed the oracle's gate; math is shared
    except Exception:
        # Exceptional path (honest traffic rarely rejects): let the oracle
        # give the authoritative answer rather than trusting OpenSSL's no.
        return ref_ecdsa_p256.verify(pubkey, msg, sig)
