"""OpenSSL-accelerated host Ed25519 with oracle-exact semantics.

The pure-Python oracle (`ref_ed25519`) defines corda_tpu's authoritative
accept/reject set, but costs ~4 ms per operation — which put host *signing*
(the notary's per-transaction signature, reference: NotaryFlow.kt:139) and
per-signature host checks on the framework's hot path at ~250 ops/s/core.
The reference's JVM stack ran the i2p EdDSA engine at 1-2k ops/s/core;
OpenSSL (via the `cryptography` wheel) does ~20k/s. This module is the host
fast path with semantics proofs:

* **sign / public_key** — RFC 8032 is fully deterministic, so OpenSSL's
  output is bit-identical to the oracle's; there is nothing to reconcile.
  (Conformance-tested in tests/test_crypto_host.py.)

* **verify** — OpenSSL's accept set is a *subset* of the oracle's: both run
  the same cofactorless ref10 procedure (recompute R' = [S]B - [h]A,
  byte-compare against R), but OpenSSL additionally enforces S < L, which
  the oracle (matching i2p-eddsa 0.1.0) deliberately does not. Therefore:
  OpenSSL-accept ⇒ oracle-accept, so a fast accept is final; an OpenSSL
  reject might be an oracle-accept corner (S ≥ L), so rejects FALL BACK to
  the oracle for the authoritative answer. Valid signatures — the
  overwhelming common case — pay only the OpenSSL cost; invalid ones pay
  the oracle cost, which is acceptable (rejections are exceptional and the
  slow path is the authority).

If the `cryptography` wheel is missing, every call degrades to the oracle —
same results, reference speed.
"""

from __future__ import annotations

import functools

from . import ref_ed25519

try:  # pragma: no cover - exercised implicitly by every test run
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )

    _AVAILABLE = True
except Exception:  # pragma: no cover
    _AVAILABLE = False
    import warnings

    # Degrading silently would be worse than crashing: host sign/verify
    # drops ~80x to the pure-Python oracle and nothing else would say why
    # (round-3 VERDICT item 5). `cryptography` is a declared dependency —
    # its absence means a broken install, and the operator should hear it
    # exactly once.
    warnings.warn(
        "the 'cryptography' package is unavailable; corda_tpu host "
        "signing/verification falls back to the pure-Python oracle "
        "(~80x slower) and TLS transport is disabled — run "
        "`pip install cryptography` (it is a declared dependency; a "
        "missing wheel means the install is broken)",
        RuntimeWarning,
        stacklevel=2,
    )


def available() -> bool:
    """True when the OpenSSL fast path is active."""
    return _AVAILABLE


def sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 signature, bit-identical to ref_ed25519.sign. The parsed
    OpenSSL key object is memoised per seed: key parsing was measured at
    ~20% of a width-32 multi-sig build (one from_private_bytes per
    signature), and a loadgen client signs with the same handful of keys
    thousands of times."""
    if _AVAILABLE and len(seed) == 32:
        return _private_key_cached(bytes(seed)).sign(bytes(msg))
    return ref_ed25519.sign(seed, msg)


@functools.lru_cache(maxsize=4096)
def _private_key_cached(seed: bytes):
    return Ed25519PrivateKey.from_private_bytes(seed)


def public_key(seed: bytes) -> bytes:
    """RFC 8032 public-key derivation, bit-identical to the oracle."""
    if _AVAILABLE and len(seed) == 32:
        return (
            Ed25519PrivateKey.from_private_bytes(seed)
            .public_key()
            .public_bytes_raw()
        )
    return ref_ed25519.public_key(seed)


def verify(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    """Oracle-equivalent verification: fast accepts, authoritative rejects.

    The parsed public-key object is memoised: a node re-verifies the same
    small signer set all day (a width-32 multisig re-parses 32 keys per
    transaction), and from_public_bytes was measured at a large share of
    host verify cost under load."""
    if _AVAILABLE and len(pubkey) == 32 and len(sig) == 64:
        try:
            _public_key_cached(bytes(pubkey)).verify(bytes(sig), bytes(msg))
            return True  # OpenSSL-accept is a subset of oracle-accept
        # lint: allow(no-silent-except) the fallthrough IS the handler: any OpenSSL reject (bad sig or oracle-only corner) re-verifies against the authoritative oracle below
        except Exception:
            pass  # genuinely bad, or an oracle-only corner — ask the oracle
    return ref_ed25519.verify(pubkey, msg, sig)


@functools.lru_cache(maxsize=65536)
def _public_key_cached(pk: bytes):
    # Raises on a malformed key: lru_cache does not cache exceptions, and
    # verify()'s except-path hands those to the oracle for the
    # authoritative reject.
    return Ed25519PublicKey.from_public_bytes(pk)
