"""FederatedVerifier — the multi-host router over per-host sidecars.

One ``SidecarServer`` (crypto/sidecar.py) owns the device(s) of ONE host;
PR 7 made every node process on that host feed it so micro-batches
coalesce across processes. This module adds the missing scale axis —
chips -> hosts: a router that owns N ``SidecarVerifier`` channels, one
per host-local sidecar, and spreads verify batches across them.

Why this scales near-linearly even before real multi-host hardware: each
channel serialises ONE framed round trip at a time (the client
``_io_lock``), and a sidecar's deadline scheduler anchors its coalesce
window on the oldest pending request — so a single-host feed is
window-limited (cycle = coalesce window + verify), not CPU-limited. K
federation channels run K windows CONCURRENTLY; aggregate sigs/s grows
with K until the verify work itself saturates the host(s). On one box
with K simulated hosts (the bench harness) that is latency-hiding; on a
real pod each channel's verify also lands on its own chips and the same
router is the seam (SNIPPETS [2]: "on multi-process platforms such as
TPU pods, pjit can be used to run computations across all available
devices across processes").

Routing policy (deterministic, so tests drive it directly):

  * interactive / unlabelled batches go to the healthy host with the
    LEAST client-tracked in-flight signatures — the earliest-served
    window, which is what an interactive deadline wants;
  * bulk batches (QoS lane hints from PR 9) COALESCE-STICK: prefer the
    healthy host already holding the most in-flight work below a cap,
    so bulk rides an already-open coalesce window instead of opening a
    fresh one on an idle host (bulk may wait; interactive may not);
  * ties break on channel index — two equal depths can never flap a
    test.

Hedged re-dispatch: a primary that has not answered within ``hedge_ms``
gets ONE secondary dispatch on the next-ranked healthy host; first
answer wins, the loser's verdicts are discarded (verification is pure —
a duplicate answer is identical, never double-applied). Hedges are
counted per host and globally (``federation_hedges_total``).

Failure policy — the sidecar contract, federated:

  * a channel failure quarantines THAT host (per-host gate + cooldown
    ping re-probe that re-admits it); the failed batch answers from
    the oracle-exact local host tier and every SUBSEQUENT batch routes
    around the quarantined host — the answer is always exact.
  * only when NO healthy host remains does ``_verify_ed25519`` demote
    the whole federation tier through ``provider.degrade_device`` (gate
    + cooldown re-probe via ``_verify_ed25519_device``, which re-opens
    as soon as ANY re-admitted host answers) and serve the batch from
    the oracle-exact local host tier. Infra faults degrade; they never
    reject and never produce a wrong answer.
  * ``_verify_ed25519_device`` therefore RAISES on total failure — the
    same raise-don't-fallback rule verify_client.py documents, because
    the degrade re-probe interprets "no exception" as healthy.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Sequence

import numpy as np

from ..obs import telemetry as _tm
from .provider import (CpuVerifier, DeviceRoutedVerifier, VerifyJob,
                       degrade_device)
from .sidecar import LANE_CODE_BULK

# Re-dispatch threshold: a primary slower than this is hedged on the
# next healthy host. Generous by default — a hedge costs a duplicate
# verify, so it should fire on a sick host, not on an ordinary coalesce
# window (which the deadline scheduler bounds well under a second).
FEDERATION_HEDGE_MS_DEFAULT = 1000.0

# A bulk batch sticks to the busiest open window only while that host's
# in-flight backlog stays under this many signatures; above it the
# window is full enough and bulk spreads like interactive traffic.
BULK_STICK_CAP_SIGS = 8192

# Per-host quarantine re-probe cadence (ping over a fresh frame).
HOST_REPROBE_COOLDOWN_S_DEFAULT = 5.0

# Bounded routing-decision ring for the flight recorder: enough to show
# the routing shape at an SLO breach, small enough to ride a stamp.
ROUTING_RING = 64


class HostChannel:
    """One host's sidecar channel plus the router's bookkeeping for it.

    ``in_flight_sigs`` is the client-tracked queue depth routing ranks
    on — signatures dispatched to this host and not yet answered
    (including callers parked on the channel's ``_io_lock``). Mutated
    only under the router lock; the counters are monitoring-grade."""

    def __init__(self, index: int, client):
        self.index = index
        self.client = client
        self.address = client.address
        self.healthy = threading.Event()
        self.healthy.set()
        self.in_flight_sigs = 0
        self.in_flight_batches = 0
        self.dispatches = 0
        self.hedges = 0
        self.hedge_wins = 0
        self.failures = 0
        self.quarantines = 0
        self.readmits = 0
        self.rpc_s_total = 0.0
        self._reprobe_thread: threading.Thread | None = None

    def stats(self) -> dict:
        return {
            "address": self.address,
            "healthy": self.healthy.is_set(),
            "in_flight_sigs": self.in_flight_sigs,
            "in_flight_batches": self.in_flight_batches,
            "dispatches": self.dispatches,
            "hedges": self.hedges,
            "hedge_wins": self.hedge_wins,
            "failures": self.failures,
            "quarantines": self.quarantines,
            "readmits": self.readmits,
            "rpc_s_total": round(self.rpc_s_total, 6),
            "server": self.client._server_stats_maybe(),
        }


class FederatedVerifier(DeviceRoutedVerifier):
    """Routes verify batches across N host-local sidecars (module doc)."""

    name = "federation"  # like "sidecar": must NOT start with "jax"

    def __init__(self, hosts: Sequence[str], deadline_ms: float = 2000.0,
                 device_min_sigs: int | None = None,
                 hedge_ms: float | None = None,
                 connect_timeout_s: float = 1.0,
                 reprobe_cooldown_s: float | None = None,
                 devices: int | None = None):
        from ..node.verify_client import (SIDECAR_MIN_SIGS_DEFAULT,
                                          SidecarVerifier)

        if not hosts:
            raise ValueError("federation needs at least one host address")
        if device_min_sigs is None:
            device_min_sigs = int(os.environ.get(
                "CORDA_TPU_SIDECAR_MIN_SIGS", SIDECAR_MIN_SIGS_DEFAULT))
        super().__init__(device_min_sigs=device_min_sigs)
        if hedge_ms is None:
            hedge_ms = float(os.environ.get(
                "CORDA_TPU_FEDERATION_HEDGE_MS",
                FEDERATION_HEDGE_MS_DEFAULT))
        self.hedge_s = float(hedge_ms) / 1e3
        self.deadline_s = float(deadline_ms) / 1e3
        self.reprobe_cooldown_s = reprobe_cooldown_s
        self.devices = devices or None
        self.channels = [
            HostChannel(i, SidecarVerifier(
                addr, deadline_ms=deadline_ms,
                device_min_sigs=0,  # routing is decided HERE, once
                connect_timeout_s=connect_timeout_s,
                devices=devices))
            for i, addr in enumerate(hosts)]
        # Router state lock: depth bookkeeping and the decision ring
        # only — never held across a socket round trip.
        self._lock = threading.Lock()
        # Pre-spawned dispatch pool: a fresh thread per batch costs tens
        # of ms at p90 on a loaded box (measured), which lands straight
        # in the request cycle; pool threads amortise the spawn. Sized
        # for one in-flight + one hedge per host.
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * len(self.channels)),
            thread_name_prefix="fed-dispatch")
        self.fallbacks = 0
        self.hedges = 0
        self.host_degraded = 0
        self.last_tier: str | None = None
        # Server-reported timings of the newest answered batch (the
        # async feeder's sidecar_wait/sidecar_verify spans), plus the
        # federation decomposition: routing-decision wall and the
        # remote round-trip wall (federation_route / remote_verify).
        self.last_wait_s: float | None = None
        self.last_verify_s: float | None = None
        self.last_route_s: float | None = None
        self.last_remote_s: float | None = None
        # Advisory QoS hint, same contract as SidecarVerifier.qos_hint:
        # set by the SMM right before a flush, racy-by-design (a stale
        # hint costs one routing choice, never correctness).
        self.qos_hint: tuple[int, int] | None = None
        self.routing_decisions: deque[dict] = deque(maxlen=ROUTING_RING)

    # -- routing policy ----------------------------------------------------

    def pick_host(self, n_sigs: int,
                  lane_code: int | None = None) -> HostChannel | None:
        """The deterministic routing choice (module doc). Returns None
        when no host is healthy. Pure ranking — depth accounting happens
        at dispatch."""
        healthy = [c for c in self.channels if c.healthy.is_set()]
        if not healthy:
            return None
        if lane_code == LANE_CODE_BULK:
            open_windows = [c for c in healthy if 0 < c.in_flight_sigs
                            and c.in_flight_sigs + n_sigs
                            <= BULK_STICK_CAP_SIGS]
            if open_windows:
                # Stick to the busiest open window (ties -> lowest index).
                return min(open_windows,
                           key=lambda c: (-c.in_flight_sigs, c.index))
        # Interactive / unlabelled / no window to stick to: least depth.
        return min(healthy, key=lambda c: (c.in_flight_sigs, c.index))

    def _record_decision(self, channel: HostChannel, n_sigs: int,
                         lane_code: int | None, hedged: bool) -> None:
        self.routing_decisions.append({
            "host": channel.address,
            "n_sigs": n_sigs,
            "lane": lane_code,
            "hedged": hedged,
            "depths": {c.address: c.in_flight_sigs
                       for c in self.channels},
        })

    # -- dispatch ----------------------------------------------------------

    def _channel_verify(self, channel: HostChannel,
                        jobs: Sequence[VerifyJob],
                        hint: tuple[int, int] | None) -> np.ndarray:
        """One channel round trip — the seam tests stub. The hint hand-
        off shares SidecarVerifier.qos_hint's advisory/racy contract."""
        channel.client.qos_hint = hint
        return channel.client._verify_ed25519_device(jobs)

    def _dispatch(self, channel: HostChannel, jobs: Sequence[VerifyJob],
                  hint: tuple[int, int] | None, slot: dict,
                  slot_lock: threading.Lock, done: threading.Event,
                  pending: list[int]) -> None:
        """Run one attempt and publish the outcome. First success wins
        ``slot``; ``done`` fires on success OR when every launched
        attempt has failed (so the waiter never hangs)."""
        n = len(jobs)
        with self._lock:
            channel.dispatches += 1
            channel.in_flight_batches += 1
            channel.in_flight_sigs += n
        _tm.inc("federation_dispatches_total")
        t0 = time.perf_counter()
        try:
            out = self._channel_verify(channel, jobs, hint)
        except Exception as exc:
            self._quarantine(channel, exc)
            with slot_lock:
                pending[0] -= 1
                exhausted = pending[0] <= 0
            if exhausted:
                done.set()
        else:
            with slot_lock:
                pending[0] -= 1
                if "ok" not in slot:
                    slot["ok"] = out
                    slot["winner"] = channel
                    slot["wall_s"] = time.perf_counter() - t0
            done.set()
        finally:
            with self._lock:
                channel.in_flight_batches -= 1
                channel.in_flight_sigs -= n
                channel.rpc_s_total += time.perf_counter() - t0

    def _verify_ed25519_device(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        """Route one batch across the federation. Raises (the channel
        client's SidecarError) only when no host answered — this method
        doubles as the whole-tier degrade re-probe, so an internal
        fallback would re-open the gate while every host is still dead."""
        from ..node.verify_client import SidecarError

        hint = self.qos_hint
        lane_code = hint[0] if hint is not None else None
        t_route = time.perf_counter()
        primary = self.pick_host(len(jobs), lane_code)
        if primary is None:
            raise SidecarError("federation: no healthy host")
        route_s = time.perf_counter() - t_route
        self._record_decision(primary, len(jobs), lane_code, hedged=False)
        slot: dict = {}
        slot_lock = threading.Lock()
        done = threading.Event()
        pending = [1]
        t0 = time.perf_counter()
        self._pool.submit(self._dispatch, primary, jobs, hint, slot,
                          slot_lock, done, pending)
        hedged_to: HostChannel | None = None
        if not done.wait(self.hedge_s):
            # Slow primary: one hedged re-dispatch on the next-ranked
            # healthy host (never the primary itself). Runs inline —
            # this thread was going to block on the result anyway.
            with self._lock:
                candidates = [c for c in self.channels
                              if c is not primary and c.healthy.is_set()]
            if candidates:
                hedged_to = min(candidates,
                                key=lambda c: (c.in_flight_sigs, c.index))
                with self._lock:
                    primary.hedges += 1
                    self.hedges += 1
                _tm.inc("federation_hedges_total")
                self._record_decision(hedged_to, len(jobs), lane_code,
                                      hedged=True)
                with slot_lock:
                    pending[0] += 1
                self._dispatch(hedged_to, jobs, hint, slot, slot_lock,
                               done, pending)
        # Bounded: every attempt's socket carries the client deadline,
        # so the slowest attempt resolves within deadline_s.
        done.wait(self.deadline_s + 1.0)
        with slot_lock:
            out = slot.get("ok")
            winner = slot.get("winner")
        if out is None:
            raise SidecarError(
                f"federation: every dispatched host failed "
                f"(primary {primary.address}"
                + (f", hedge {hedged_to.address}" if hedged_to else "")
                + ")")
        if hedged_to is not None and winner is hedged_to:
            with self._lock:
                hedged_to.hedge_wins += 1
        self.last_route_s = route_s
        self.last_remote_s = time.perf_counter() - t0
        self.last_wait_s = winner.client.last_wait_s
        self.last_verify_s = winner.client.last_verify_s
        self.last_tier = winner.client.last_tier
        return out

    # -- the DeviceRoutedVerifier routing override -------------------------

    def _verify_ed25519(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        if (len(jobs) < self.device_min_sigs
                or (self.device_gate is not None
                    and not self.device_gate.is_set())):
            self.host_batches += 1
            return CpuVerifier._verify_ed25519_host(jobs)
        try:
            out = self._verify_ed25519_device(jobs)
        except Exception:
            # Every dispatched host failed. The batch still answers
            # exactly (oracle host tier); the WHOLE tier only degrades
            # when no healthy host remains — a transient single-host
            # loss must not close the gate on the survivors.
            self.fallbacks += 1
            if not any(c.healthy.is_set() for c in self.channels):
                degrade_device(self, cooldown_s=self.reprobe_cooldown_s)
            self.host_batches += 1
            return CpuVerifier._verify_ed25519_host(jobs)
        self.device_batches += 1
        return out

    # -- per-host quarantine + re-admission --------------------------------

    def _quarantine(self, channel: HostChannel, exc: Exception) -> None:
        """Demote ONE host and schedule its cooldown ping re-probe.
        Idempotent while a re-probe is already pending."""
        with self._lock:
            channel.failures += 1
            was_healthy = channel.healthy.is_set()
            channel.healthy.clear()
            probing = (channel._reprobe_thread is not None
                       and channel._reprobe_thread.is_alive())
            if not was_healthy and probing:
                return
            channel.quarantines += 1
            self.host_degraded += 1
        _tm.inc("federation_host_degraded_total")
        cooldown = self.reprobe_cooldown_s
        if cooldown is None:
            cooldown = float(os.environ.get(
                "CORDA_TPU_DEVICE_REPROBE_COOLDOWN_S",
                HOST_REPROBE_COOLDOWN_S_DEFAULT))

        def _reprobe() -> None:
            while not channel.healthy.is_set():
                time.sleep(cooldown)
                try:
                    channel.client.warm()  # one ping round trip
                except Exception:
                    continue
                with self._lock:
                    channel.readmits += 1
                    channel.healthy.set()

        t = threading.Thread(target=_reprobe, daemon=True,
                             name=f"fed-reprobe-{channel.index}")
        channel._reprobe_thread = t
        t.start()

    # -- warm + stamping ----------------------------------------------------

    def warm(self) -> None:
        """Ping every host; healthy if ANY answers (the cluster can boot
        while one simulated host is still coming up). Raises only when
        the whole federation is unreachable."""
        from ..node.verify_client import SidecarError

        errors = []
        reached = 0
        for channel in self.channels:
            try:
                channel.client.warm()
                reached += 1
            except SidecarError as exc:
                errors.append(str(exc))
        if not reached:
            raise SidecarError(
                f"federation: no host reachable: {'; '.join(errors)}")

    def sidecar_stats(self) -> dict:
        """Rides the same node_metrics seam the single-sidecar client
        does (rpc.py duck-types on this method); the ``federation``
        block is what stamp_attribution and the flight recorder read."""
        gate = self.device_gate
        return {
            "address": ",".join(c.address for c in self.channels),
            "deadline_ms": self.deadline_s * 1e3,
            "min_sigs": self.device_min_sigs,
            "batches": sum(c.client.sidecar_batches for c in self.channels),
            "sigs": sum(c.client.sidecar_sigs for c in self.channels),
            "fallbacks": self.fallbacks,
            "connects": sum(c.client.connects for c in self.channels),
            "rpc_s_total": round(
                sum(c.rpc_s_total for c in self.channels), 6),
            "last_wait_s": self.last_wait_s,
            "last_verify_s": self.last_verify_s,
            "last_tier": self.last_tier,
            "gate_open": gate.is_set() if gate is not None else None,
            "degraded": self.degraded,
            "reprobes_ok": self.reprobes_ok,
            "reprobes_failed": self.reprobes_failed,
            "devices": self.devices,
            "federation": self.federation_stats(),
        }

    def federation_stats(self) -> dict:
        """Per-host occupancy/queue-depth/routing counters + the bounded
        decision ring — node_metrics, the Prometheus cluster merge's
        per-node context, and the SLO-breach flight capture."""
        with self._lock:
            decisions = list(self.routing_decisions)
        # Channel counters are monitoring-grade ints (torn reads are
        # harmless), and stats() fetches a cached SERVER snapshot over
        # the wire — neither may run under the router lock.
        hosts = {c.address: c.stats() for c in self.channels}
        total = sum(h["dispatches"] for h in hosts.values())
        return {
            "hosts": hosts,
            "n_hosts": len(self.channels),
            "healthy_hosts": sum(1 for h in hosts.values() if h["healthy"]),
            "dispatches": total,
            "routing_share_by_host": (
                {a: round(h["dispatches"] / total, 4)
                 for a, h in hosts.items()} if total else {}),
            "hedges": self.hedges,
            "host_degraded": self.host_degraded,
            "hedge_ms": self.hedge_s * 1e3,
            "recent_decisions": decisions,
        }
