"""Secure hashes — capability match for the reference's SecureHash.

Reference: core/src/main/kotlin/net/corda/core/crypto/SecureHash.kt:33 —
SHA-256 content addressing used for transaction ids, attachment ids and Merkle
leaves. Host-side single hashes live here; the batched/tree-structured hashing
used on the notary hot path is the JAX kernel in corda_tpu/ops/sha256_jax.py.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class SecureHash:
    """An immutable 32-byte SHA-256 digest."""

    bytes: bytes

    def __post_init__(self):
        if len(self.bytes) != 32:
            raise ValueError(f"SHA-256 digest must be 32 bytes, got {len(self.bytes)}")

    @staticmethod
    def sha256(data: bytes) -> "SecureHash":
        return SecureHash(hashlib.sha256(data).digest())

    @staticmethod
    def sha256_twice(data: bytes) -> "SecureHash":
        return SecureHash.sha256(hashlib.sha256(data).digest())

    @staticmethod
    def parse(hex_str: str) -> "SecureHash":
        return SecureHash(bytes.fromhex(hex_str))

    @staticmethod
    def zero() -> "SecureHash":
        return SecureHash(b"\x00" * 32)

    @staticmethod
    def random() -> "SecureHash":
        import os

        return SecureHash(os.urandom(32))

    def hex(self) -> str:
        return self.bytes.hex()

    def prefix_chars(self, n: int = 6) -> str:
        return self.hex()[:n].upper()

    def hash_concat(self, other: "SecureHash") -> "SecureHash":
        """Node hash for Merkle trees: sha256(left || right)."""
        return SecureHash.sha256(self.bytes + other.bytes)

    def __str__(self) -> str:
        return self.hex().upper()

    def __repr__(self) -> str:
        return f"SecureHash({self.hex()[:16]}…)"
