"""Key pairs and digital signatures (host control plane).

Capability match for the reference's signing helpers (reference:
core/src/main/kotlin/net/corda/core/crypto/CryptoUtilities.kt:27-110). As in
the reference snapshot, transaction signing is hardwired to Ed25519 — the
reference's helpers are (confusingly) named signWithECDSA/verifyWithECDSA but
construct an EdDSA engine (CryptoUtilities.kt:63-96). Here the naming is
honest: sign/verify, Ed25519.

The *batched* verification path — the notary hot loop — does not live here; it
is the JAX kernel in corda_tpu/ops/ed25519_jax.py behind the provider seam in
corda_tpu/crypto/provider.py. This module is the per-signature host path and
shares its accept/reject semantics with the kernel via the common oracle
(corda_tpu/crypto/ref_ed25519.py).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import TYPE_CHECKING, Iterable

from ..utils.bytes import OpaqueBytes
from . import fast_ed25519
from . import base58

if TYPE_CHECKING:  # circular: party -> composite -> keys
    from .party import Party


from ..utils.excheckpoint import register_flow_exception


@register_flow_exception
class SignatureError(Exception):
    """Raised when a signature fails to verify (reference: SignatureException)."""


@dataclass(frozen=True, order=True)
class PublicKey:
    """An Ed25519 public key: the 32-byte point encoding.

    Reference equivalent: EdDSAPublicKey (i2p) as used throughout
    CryptoUtilities.kt.
    """

    encoded: bytes
    algorithm: str = "Ed25519"

    def __post_init__(self):
        if self.algorithm == "Ed25519" and len(self.encoded) != 32:
            raise ValueError(f"Ed25519 public key must be 32 bytes, got {len(self.encoded)}")

    def to_base58(self) -> str:
        return base58.encode(self.encoded)

    def to_string_short(self) -> str:
        """'DL' + base58, as the reference renders keys (CryptoUtilities.kt:104-108)."""
        return "DL" + self.to_base58()

    @property
    def composite(self):
        """Wrap in a single-leaf CompositeKey (CryptoUtilities.kt:110)."""
        from .composite import CompositeKey

        return CompositeKey.leaf(self)

    def verify(self, content: bytes, signature: "DigitalSignature") -> None:
        """Verify or raise SignatureError (CryptoUtilities.kt:96-101 semantics).

        Host fast path (fast_ed25519: OpenSSL accept, oracle-authoritative
        reject) — bit-identical accept/reject to the ref_ed25519 oracle."""
        if not fast_ed25519.verify(self.encoded, content, signature.bytes):
            raise SignatureError("Signature did not match")

    def is_valid(self, content: bytes, signature: "DigitalSignature") -> bool:
        return fast_ed25519.verify(self.encoded, content, signature.bytes)

    def __repr__(self) -> str:
        return self.to_string_short()


NULL_PUBLIC_KEY = PublicKey(b"\x00", algorithm="NULL")


@dataclass(frozen=True)
class PrivateKey:
    """An Ed25519 private key (32-byte RFC 8032 seed)."""

    seed: bytes

    def __post_init__(self):
        if len(self.seed) != 32:
            raise ValueError(f"Ed25519 seed must be 32 bytes, got {len(self.seed)}")

    def sign(self, content: bytes) -> "DigitalSignature":
        # fast_ed25519.sign is bit-identical to the oracle (RFC 8032 is
        # deterministic) at ~50x the speed — the notary's per-commit
        # signature is on the framework hot path.
        return DigitalSignature(fast_ed25519.sign(self.seed, content))

    def sign_with_key(self, content: bytes, public_key: PublicKey) -> "DigitalSignature.WithKey":
        return DigitalSignature.WithKey(by=public_key, bytes=self.sign(content).bytes)

    def __repr__(self) -> str:
        return "PrivateKey(…)"


@dataclass(frozen=True)
class KeyPair:
    """A public/private Ed25519 key pair."""

    public: PublicKey
    private: PrivateKey

    @staticmethod
    def generate(entropy: bytes | None = None) -> "KeyPair":
        seed = entropy if entropy is not None else os.urandom(32)
        if len(seed) != 32:
            raise ValueError("entropy must be 32 bytes")
        return KeyPair(PublicKey(fast_ed25519.public_key(seed)), PrivateKey(seed))

    def sign(self, content: bytes) -> "DigitalSignature.WithKey":
        return self.private.sign_with_key(
            content if isinstance(content, bytes) else bytes(content), self.public
        )

    def sign_as(self, content: bytes, party: "Party") -> "DigitalSignature.LegallyIdentifiable":
        """Sign identifying the signing Party (CryptoUtilities.kt:85-90)."""
        if self.public not in party.owning_key.keys:
            raise ValueError("key pair does not belong to party")
        return DigitalSignature.LegallyIdentifiable(
            by=self.public, bytes=self.sign(content).bytes, signer=party
        )


@dataclass(frozen=True)
class DigitalSignature(OpaqueBytes):
    """A raw 64-byte Ed25519 signature (CryptoUtilities.kt:27-36)."""

    @dataclass(frozen=True)
    class WithKey(OpaqueBytes):
        """A signature together with the public key that (allegedly) made it."""

        by: PublicKey = None  # type: ignore[assignment]

        def verify(self, content: bytes) -> None:
            self.by.verify(
                content if isinstance(content, bytes) else bytes(content),
                DigitalSignature(self.bytes),
            )

        def is_valid(self, content: bytes) -> bool:
            return self.by.is_valid(
                content if isinstance(content, bytes) else bytes(content),
                DigitalSignature(self.bytes),
            )

    @dataclass(frozen=True)
    class LegallyIdentifiable(WithKey):
        """A signature attributed to a named Party (CryptoUtilities.kt:37)."""

        signer: "Party" = None  # type: ignore[assignment]


NULL_SIGNATURE = DigitalSignature.WithKey(bytes=b"\x00" * 32, by=NULL_PUBLIC_KEY)


def by_keys(sigs: Iterable[DigitalSignature.WithKey]) -> set[PublicKey]:
    """The set of public keys behind a collection of signatures."""
    return {sig.by for sig in sigs}
