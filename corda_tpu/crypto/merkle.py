"""Merkle trees and partial (branch) Merkle trees.

Capability match for the reference's MerkleTree (reference:
core/src/main/kotlin/net/corda/core/transactions/MerkleTransaction.kt:62-99)
and PartialMerkleTree (core/.../crypto/PartialMerkleTree.kt:69-143):

  * full tree built bottom-up from leaf hashes; an odd node at any level is
    hashed with itself, recorded as a DuplicatedLeaf so partial-tree filtering
    can tell the duplicate from a real leaf;
  * node hash = sha256(left || right);
  * a partial tree keeps IncludedLeaf markers for the leaves being proven,
    bare hashes for pruned subtrees, and verifies by recomputing the root and
    set-comparing the included hashes.

Transaction ids are Merkle roots over the serialized components, so this is
on the notary hot path; the batched/tree-reduction variant for large batches
is the JAX kernel in corda_tpu/ops/sha256_jax.py. This host version is the
authoritative structure (and works for any leaf count).
"""

from __future__ import annotations

from collections import Counter
from dataclasses import dataclass

from .hashes import SecureHash


class MerkleTreeException(Exception):
    def __init__(self, reason: str):
        super().__init__(f"Partial Merkle Tree exception. Reason: {reason}")
        self.reason = reason


@dataclass(frozen=True)
class MerkleTree:
    """A node in a full Merkle tree; `hash` is the subtree root."""

    hash: SecureHash

    @staticmethod
    def build(leaf_hashes: list[SecureHash]) -> "MerkleTree":
        """Bottom-up construction (MerkleTransaction.kt:66-99)."""
        if not leaf_hashes:
            raise MerkleTreeException("Cannot calculate Merkle root on empty hash list.")
        level: list[MerkleTree] = [MerkleLeaf(h) for h in leaf_hashes]
        while len(level) > 1:
            nxt: list[MerkleTree] = []
            for i in range(0, len(level), 2):
                left = level[i]
                right = (
                    level[i + 1]
                    if i + 1 < len(level)
                    else MerkleDuplicatedLeaf(level[-1].hash)
                )
                nxt.append(MerkleNode(left.hash.hash_concat(right.hash), left, right))
            level = nxt
        return level[0]


@dataclass(frozen=True)
class MerkleLeaf(MerkleTree):
    pass


@dataclass(frozen=True)
class MerkleDuplicatedLeaf(MerkleTree):
    """The rightmost node hashed with itself to pad an odd level."""


@dataclass(frozen=True)
class MerkleNode(MerkleTree):
    left: MerkleTree = None  # type: ignore[assignment]
    right: MerkleTree = None  # type: ignore[assignment]


# ---------------------------------------------------------------------------
# Partial trees
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PartialTree:
    """Base of the three partial-tree node kinds."""


@dataclass(frozen=True)
class PartialIncludedLeaf(PartialTree):
    hash: SecureHash


@dataclass(frozen=True)
class PartialLeaf(PartialTree):
    hash: SecureHash


@dataclass(frozen=True)
class PartialNode(PartialTree):
    left: PartialTree
    right: PartialTree


@dataclass(frozen=True)
class PartialMerkleTree:
    """A Merkle branch proving a subset of leaves against a root
    (PartialMerkleTree.kt:69-143)."""

    root: PartialTree

    @staticmethod
    def build(merkle_root: MerkleTree, include_hashes: list[SecureHash]) -> "PartialMerkleTree":
        used: list[SecureHash] = []
        _, tree = PartialMerkleTree._build(merkle_root, frozenset(include_hashes), used)
        if len(include_hashes) != len(used):
            raise MerkleTreeException("Some of the provided hashes are not in the tree.")
        return PartialMerkleTree(tree)

    @staticmethod
    def _build(
        node: MerkleTree, include: frozenset[SecureHash], used: list[SecureHash]
    ) -> tuple[bool, PartialTree]:
        if isinstance(node, MerkleDuplicatedLeaf):
            return False, PartialLeaf(node.hash)
        if isinstance(node, MerkleLeaf):
            if node.hash in include:
                used.append(node.hash)
                return True, PartialIncludedLeaf(node.hash)
            return False, PartialLeaf(node.hash)
        assert isinstance(node, MerkleNode)
        l_in, l_tree = PartialMerkleTree._build(node.left, include, used)
        r_in, r_tree = PartialMerkleTree._build(node.right, include, used)
        if l_in or r_in:
            return True, PartialNode(l_tree, r_tree)
        return False, PartialLeaf(node.hash)

    def verify(self, merkle_root_hash: SecureHash, hashes_to_check: list[SecureHash]) -> bool:
        used: list[SecureHash] = []
        root = self._root_hash(self.root, used)
        if Counter(hashes_to_check) != Counter(used):
            return False
        return root == merkle_root_hash

    @staticmethod
    def _root_hash(node: PartialTree, used: list[SecureHash]) -> SecureHash:
        if isinstance(node, PartialIncludedLeaf):
            used.append(node.hash)
            return node.hash
        if isinstance(node, PartialLeaf):
            return node.hash
        assert isinstance(node, PartialNode)
        left = PartialMerkleTree._root_hash(node.left, used)
        right = PartialMerkleTree._root_hash(node.right, used)
        return left.hash_concat(right)

    def included_hashes(self) -> list[SecureHash]:
        used: list[SecureHash] = []
        self._root_hash(self.root, used)
        return used
