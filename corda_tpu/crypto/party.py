"""Network identities.

Capability match for the reference's Party (reference:
core/src/main/kotlin/net/corda/core/crypto/Party.kt): an entity identified by
a legal name and a CompositeKey owning key, used both for node identities and
for (possibly distributed) service identities — a notary cluster advertises
one Party whose composite key contains every member's key.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..utils.bytes import OpaqueBytes
from .composite import CompositeKey
from .keys import PublicKey


@dataclass(frozen=True)
class Party:
    """A named on-network identity that signs under a composite key."""

    name: str
    owning_key: CompositeKey

    @staticmethod
    def of(name: str, key: "PublicKey | CompositeKey") -> "Party":
        if isinstance(key, PublicKey):
            key = key.composite
        return Party(name, key)

    def ref(self, data: bytes | OpaqueBytes) -> "PartyAndReference":
        if isinstance(data, bytes):
            data = OpaqueBytes(data)
        return PartyAndReference(self, data)

    def __str__(self) -> str:
        return self.name


@dataclass(frozen=True)
class PartyAndReference:
    """A Party plus an opaque reference it chose — e.g. an issuer plus its
    internal account id (reference: core/.../contracts/Structures.kt:331)."""

    party: Party
    reference: OpaqueBytes

    def __str__(self) -> str:
        return f"{self.party}{self.reference}"
