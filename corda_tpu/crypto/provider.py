"""Pluggable signature-verification provider — the batching seam.

The reference hardwires per-signature verification into a sequential loop
(reference: core/.../transactions/SignedTransaction.kt:83-87, engine built at
core/.../crypto/CryptoUtilities.kt:63-96) and its whitepaper calls signature
checking the embarrassingly-parallel hotspot (docs/source/whitepaper/
corda-technical-whitepaper.tex:1597-1604). This module introduces the seam the
reference lacks: everything that checks signatures goes through a
BatchVerifier, so swapping the CPU oracle for the vmap'd JAX/TPU kernel
(corda_tpu/ops/ed25519_jax.py) is a provider change, not a call-site change —
the capability the reference gates behind CordaPluginRegistry-style plugins.

Providers:
  CpuVerifier  — per-signature pure-Python oracle; the conformance authority.
  JaxVerifier  — batched JAX kernel (CPU backend in tests, TPU in prod), with
                 optional shadow sampling: a fraction of batch results is
                 re-checked against the oracle so TPU divergence is detected
                 in production (SURVEY.md §7 hard part #5).

ECDSA — an EXPLICIT deferral, not an oversight. The reference snapshot
hardwires Ed25519 for every ledger signature: its "ECDSA"-named helpers
construct EdDSAEngine (reference: core/src/main/kotlin/net/corda/core/crypto/
CryptoUtilities.kt:63-96; there is no pluggable SignatureScheme SPI at 0.7).
ECDSA secp256r1 appears ONLY in TLS/X.509 certificate plumbing
(core/.../crypto/X509Utilities.kt:44-48), never on the transaction hot path,
so a batched ECDSA verify kernel would have zero reference workload to serve.
If later parity targets need it (TLS transport or post-0.7 Crypto SPI), the
BatchVerifier seam is where it plugs in: VerifyJob grows a scheme tag and a
secp256r1/k1 kernel joins ed25519_jax behind the same provider.
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass
from typing import Sequence

import numpy as np

from . import fast_ed25519, ref_ed25519


@dataclass(frozen=True)
class VerifyJob:
    """One signature check: does `sig` by `pubkey` cover `message`?"""

    pubkey: bytes
    message: bytes
    sig: bytes


class BatchVerifier:
    """Interface: verify many independent Ed25519 signatures at once."""

    name = "abstract"

    def verify_batch(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        """Returns bool[N]; malformed input rejects (False), never raises."""
        raise NotImplementedError


class CpuVerifier(BatchVerifier):
    """Sequential host loop with oracle-exact semantics.

    Uses the OpenSSL fast path (fast_ed25519: fast accepts, oracle-
    authoritative rejects) — bit-identical accept/reject to ref_ed25519 at
    a realistic CPU baseline (~10-20k sigs/s/core, the rate BASELINE.md
    expects of the era's JVM) instead of the pure-Python oracle's ~250/s."""

    name = "cpu-openssl"

    def verify_batch(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        return np.array(
            [fast_ed25519.verify(j.pubkey, j.message, j.sig) for j in jobs],
            bool,
        )


class OracleVerifier(BatchVerifier):
    """Pure-Python oracle loop — THE accept/reject conformance authority.
    Deliberately slow; for conformance tests and shadow checks."""

    name = "cpu-oracle"

    def verify_batch(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        return np.array(
            [ref_ed25519.verify(j.pubkey, j.message, j.sig) for j in jobs], bool
        )


class JaxVerifier(BatchVerifier):
    """Batched JAX kernel with shadow-sampled oracle cross-checks.

    shadow_rate: fraction of results re-verified on the CPU oracle; a mismatch
    raises RuntimeError (divergence must never be silent).
    """

    name = "jax-batch"

    def __init__(self, shadow_rate: float = 0.0, rng: random.Random | None = None):
        self.shadow_rate = shadow_rate
        self._rng = rng or random.Random(0)

    def verify_batch(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        from ..ops import ed25519_jax

        if not jobs:
            return np.zeros(0, bool)
        out = ed25519_jax.verify_batch(
            [j.pubkey for j in jobs], [j.message for j in jobs], [j.sig for j in jobs]
        )
        if self.shadow_rate > 0.0:
            for i in range(len(jobs)):
                if self._rng.random() < self.shadow_rate:
                    want = ref_ed25519.verify(
                        jobs[i].pubkey, jobs[i].message, jobs[i].sig
                    )
                    if bool(out[i]) != want:
                        raise RuntimeError(
                            f"TPU/CPU verify divergence at index {i}: "
                            f"kernel={bool(out[i])} oracle={want}"
                        )
        return out


_default: BatchVerifier | None = None


def get_verifier() -> BatchVerifier:
    """The process-wide verifier. Defaults from CORDA_TPU_VERIFIER
    (cpu | jax | jax-shadow); cpu if unset."""
    global _default
    if _default is None:
        choice = os.environ.get("CORDA_TPU_VERIFIER", "cpu")
        if choice == "jax":
            _default = JaxVerifier()
        elif choice == "jax-shadow":
            _default = JaxVerifier(shadow_rate=0.05)
        else:
            _default = CpuVerifier()
    return _default


def set_verifier(verifier: BatchVerifier | None) -> None:
    """Install a provider (None resets to environment default)."""
    global _default
    _default = verifier
