"""Pluggable signature-verification provider — the batching seam.

The reference hardwires per-signature verification into a sequential loop
(reference: core/.../transactions/SignedTransaction.kt:83-87, engine built at
core/.../crypto/CryptoUtilities.kt:63-96) and its whitepaper calls signature
checking the embarrassingly-parallel hotspot (docs/source/whitepaper/
corda-technical-whitepaper.tex:1597-1604). This module introduces the seam the
reference lacks: everything that checks signatures goes through a
BatchVerifier, so swapping the CPU oracle for the vmap'd JAX/TPU kernel
(corda_tpu/ops/ed25519_jax.py) is a provider change, not a call-site change —
the capability the reference gates behind CordaPluginRegistry-style plugins.

Providers:
  CpuVerifier  — per-signature pure-Python oracle; the conformance authority.
  JaxVerifier  — batched JAX kernel (CPU backend in tests, TPU in prod), with
                 optional shadow sampling: a fraction of batch results is
                 re-checked against the oracle so TPU divergence is detected
                 in production (SURVEY.md §7 hard part #5).

ECDSA P-256: the reference snapshot hardwires Ed25519 for every ledger
signature (its "ECDSA"-named helpers construct EdDSAEngine, reference:
core/src/main/kotlin/net/corda/core/crypto/CryptoUtilities.kt:63-96; no
pluggable SignatureScheme SPI at 0.7); secp256r1 appears ONLY in TLS/X.509
plumbing (core/.../crypto/X509Utilities.kt:44-48). The seam nonetheless
exists here: VerifyJob carries a `scheme` tag, mixed batches split by scheme
(ed25519 → the batched kernel path, ecdsa-p256 → the OpenSSL host fast path
in crypto/fast_ecdsa_p256.py, whose accept set is pinned to the oracle in
crypto/ref_ecdsa_p256.py by an oracle-owned structural gate) and recombine
in order. A device ECDSA kernel can slot behind the same tag if a workload
ever warrants it — today none does.
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass
from typing import Sequence

import numpy as np

from . import fast_ed25519, ref_ed25519


@dataclass(frozen=True)
class VerifyJob:
    """One signature check: does `sig` by `pubkey` cover `message`?

    scheme routes the job: "ed25519" (every ledger signature — the batched
    kernel path) or "ecdsa-p256" (the TLS/X.509 scheme, reference:
    core/.../crypto/X509Utilities.kt:44-48 — host oracle path). Mixed-scheme
    batches split by scheme and recombine in order; unknown schemes reject.
    """

    pubkey: bytes
    message: bytes
    sig: bytes
    scheme: str = "ed25519"


def _dispatch_mixed(jobs: Sequence[VerifyJob], ed25519_fn,
                    p256_fn=None) -> np.ndarray:
    """Split a mixed-scheme batch: the ed25519 subset goes to `ed25519_fn`
    (each provider's batched path); ecdsa-p256 jobs verify through
    `p256_fn` (default: the OpenSSL fast path with oracle-exact semantics,
    crypto/fast_ecdsa_p256.py); unknown schemes reject. Results recombine
    in input order."""
    if p256_fn is None:
        from . import fast_ecdsa_p256

        p256_fn = fast_ecdsa_p256.verify
    out = np.zeros(len(jobs), bool)
    ed_idx = [i for i, j in enumerate(jobs) if j.scheme == "ed25519"]
    if ed_idx:
        ed_ok = ed25519_fn([jobs[i] for i in ed_idx])
        for k, i in enumerate(ed_idx):
            out[i] = ed_ok[k]
    for i, job in enumerate(jobs):
        if job.scheme == "ecdsa-p256":
            out[i] = p256_fn(job.pubkey, job.message, job.sig)
    return out


class BatchVerifier:
    """Interface: verify many independent signatures at once."""

    name = "abstract"

    def verify_batch(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        """Returns bool[N]; malformed input rejects (False), never raises."""
        raise NotImplementedError


class CpuVerifier(BatchVerifier):
    """Batched host path with oracle-exact semantics.

    Fast tier: the native libcrypto core (`native/_cverify.c`) verifies the
    whole ed25519 batch in C with the GIL RELEASED — transport readers,
    bridges and the round's sqlite work keep running during a flush, which
    the per-signature Python loop (holding the GIL throughout) prevented.
    Accept-fast only: anything it rejects is re-checked through
    fast_ed25519 (OpenSSL retry, then the authoritative pure-Python
    oracle), so accept/reject stays bit-identical to ref_ed25519 — e.g.
    S >= L signatures, which OpenSSL rejects and the oracle accepts by
    design. Falls back to the Python loop when no toolchain/libcrypto."""

    name = "cpu-openssl"

    def verify_batch(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        return _dispatch_mixed(jobs, self._verify_ed25519_host)

    @staticmethod
    def _verify_ed25519_host(ed: Sequence[VerifyJob]) -> np.ndarray:
        native = _cverify_module()
        if native is None:
            return np.array(
                [fast_ed25519.verify(j.pubkey, j.message, j.sig)
                 for j in ed], bool)
        accepted = native.verify_many([j.pubkey for j in ed],
                                      [j.message for j in ed],
                                      [j.sig for j in ed])
        out = np.frombuffer(accepted, np.uint8).astype(bool)
        for i in np.flatnonzero(~out):
            # Native-reject is not authoritative: the oracle owns the
            # accept set (rejects are rare on honest traffic, so this
            # stays off the hot path).
            out[i] = fast_ed25519.verify(
                ed[i].pubkey, ed[i].message, ed[i].sig)
        return out


_CVERIFY_CACHE: list = []


def _cverify_module():
    if not _CVERIFY_CACHE:
        try:
            from ..native import load_cverify

            _CVERIFY_CACHE.append(load_cverify())
        except Exception:
            _CVERIFY_CACHE.append(None)
    return _CVERIFY_CACHE[0]


class OracleVerifier(BatchVerifier):
    """Pure-Python oracle loop — THE accept/reject conformance authority.
    Deliberately slow; for conformance tests and shadow checks."""

    name = "cpu-oracle"

    def verify_batch(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        from . import ref_ecdsa_p256

        return _dispatch_mixed(jobs, lambda ed: np.array(
            [ref_ed25519.verify(j.pubkey, j.message, j.sig) for j in ed],
            bool,
        ), p256_fn=ref_ecdsa_p256.verify)


def _shadow_check(jobs: Sequence[VerifyJob], out: np.ndarray,
                  shadow_rate: float, rng: random.Random) -> None:
    """Re-verify a sample of kernel results on the CPU oracle; a mismatch
    raises RuntimeError (divergence must never be silent)."""
    if shadow_rate <= 0.0:
        return
    for i in range(len(jobs)):
        if rng.random() < shadow_rate:
            want = ref_ed25519.verify(
                jobs[i].pubkey, jobs[i].message, jobs[i].sig)
            if bool(out[i]) != want:
                raise RuntimeError(
                    f"TPU/CPU verify divergence at index {i}: "
                    f"kernel={bool(out[i])} oracle={want}")


# Below this many ed25519 jobs a device round trip loses to the host path:
# the kernel pads every batch to >=1024 lanes and pays ~ms of pack+dispatch
# +readback per call (worse over the tunnel), while the native/OpenSSL host
# tier verifies small batches in tens of microseconds. Measured on the v5e
# tunnel host (see bench trader_dvp: 0.79 trades/s device-always vs ~120
# host — each 2-6-sig flow batch paid the device tax). Overridable per
# verifier or via CORDA_TPU_DEVICE_MIN_SIGS; 0 forces device-always.
DEVICE_MIN_SIGS_DEFAULT = 512


def _resolve_device_min_sigs(value: int | None) -> int:
    """Shared constructor policy for the size crossover (JaxVerifier and
    MeshVerifier): explicit argument wins, else CORDA_TPU_DEVICE_MIN_SIGS,
    else the measured default."""
    if value is not None:
        return value
    return int(os.environ.get(
        "CORDA_TPU_DEVICE_MIN_SIGS", DEVICE_MIN_SIGS_DEFAULT))


class DeviceRoutedVerifier(BatchVerifier):
    """Shared routing policy for the device-backed verifiers: the size
    crossover (batches under device_min_sigs take the host tier), the
    boot-warm device_gate (batches host-route while a warm-up is in
    flight — the first kernel call in a process pays backend init +
    compile, measured stalling a notary ~100 s in-loop), and the
    host/device batch counters every stamp reads. Subclasses implement
    the device dispatch (_verify_ed25519_device) and warm()."""

    def __init__(self, shadow_rate: float = 0.0,
                 rng: random.Random | None = None,
                 device_min_sigs: int | None = None):
        self.shadow_rate = shadow_rate
        self._rng = rng or random.Random(0)
        # Runtime-tunable: async_verify.AdaptiveCrossover rewrites this
        # from observed host- vs device-tier sigs/s; the resolved value is
        # only the starting point. Reads/writes stay single-threaded (the
        # run loop owns routing policy; the feeder thread only reads it
        # inside verify_batch — a stale read routes one batch, never
        # corrupts state).
        self.device_min_sigs = _resolve_device_min_sigs(device_min_sigs)
        self.host_batches = 0
        self.device_batches = 0
        # node.py _warm_verifier_maybe installs its done-event here;
        # None (the default) means no gate. degrade_device() reuses the
        # same gate to host-route while the device tier is suspect.
        self.device_gate = None
        # Degrade bookkeeping (degrade_device): times the device tier was
        # demoted after a failure, and re-probe outcomes.
        self.degraded = 0
        self.reprobes_ok = 0
        self.reprobes_failed = 0
        self._reprobe_thread = None

    def verify_batch(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        if not jobs:
            return np.zeros(0, bool)
        return _dispatch_mixed(jobs, self._verify_ed25519)

    def _verify_ed25519(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        if (len(jobs) < self.device_min_sigs
                or (self.device_gate is not None
                    and not self.device_gate.is_set())):
            # Host tier is oracle-exact by construction (CpuVerifier doc);
            # no shadow sampling needed on this route.
            self.host_batches += 1
            return CpuVerifier._verify_ed25519_host(jobs)
        self.device_batches += 1
        out = self._verify_ed25519_device(jobs)
        _shadow_check(jobs, out, self.shadow_rate, self._rng)
        return out

    def _verify_ed25519_device(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        raise NotImplementedError

    def pack_device(self, jobs: Sequence[VerifyJob]):
        """Split seam for pipelined callers (the sidecar's double-buffered
        executor): host-side columnar packing of a batch, separable from the
        device dispatch, so batch N+1 packs while batch N runs on the
        device. Returns an opaque handle for :meth:`verify_packed`, or None
        when this batch would NOT take the device tier (size/gate routing
        says host, mixed schemes, nothing well-formed) — the caller then
        falls back to the ordinary verify_batch path, which routes
        identically. Base verifiers don't support the split."""
        return None

    def verify_packed(self, packed) -> np.ndarray:
        """Dispatch a handle produced by :meth:`pack_device`. Counts as a
        device batch (routing was already decided at pack time)."""
        raise NotImplementedError

    def warm(self) -> None:
        """Compile THIS verifier's device path at both pump bucket sizes,
        bypassing the gate/size routing. Blocking and exception-raising —
        the caller (node.py boot warm-up) owns gating and error policy."""
        raise NotImplementedError


# Warm batch sizes covering the pump's REAL bucket ladder on every backend:
# 513 -> bucket 1024 (the smallest batch the size crossover sends to the
# device, with or without the Pallas >=1024 pad) and 1025 -> bucket 4096
# (backlogged rounds reach max_sigs=4096). A 1-sig warm would compile
# bucket 64 under plain XLA — a graph the pump never uses — leaving the
# 1024 bucket cold exactly when Pallas is unavailable.
WARM_SIZES = (513, 1025)


class JaxVerifier(DeviceRoutedVerifier):
    """Batched JAX kernel with shadow-sampled oracle cross-checks.

    shadow_rate: fraction of results re-verified on the CPU oracle; a mismatch
    raises RuntimeError (divergence must never be silent).

    Batches below device_min_sigs route to the HOST tier (same semantics:
    CpuVerifier's accept-fast + oracle-authoritative path) — the per-batch
    backend choice by size, mirroring hash_many_auto's crossover constant.
    host_batches/device_batches count where work actually went so bench
    stamps and node metrics can attribute every number.
    """

    name = "jax-batch"

    def _verify_ed25519_device(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        from ..ops import ed25519_jax

        return ed25519_jax.verify_batch(
            [j.pubkey for j in jobs], [j.message for j in jobs],
            [j.sig for j in jobs])

    def warm(self) -> None:
        from ..ops import ed25519_jax

        for n in WARM_SIZES:
            ed25519_jax.verify_batch([bytes(32)] * n, [bytes(32)] * n,
                                     [bytes(64)] * n)


class MeshVerifier(DeviceRoutedVerifier):
    """SPMD verify over a device mesh: the batch axis of every verify batch
    is sharded across the local devices with shard_map (ops/sharded.py), so
    a multi-chip slice verifies one notary batch cooperatively — the
    whitepaper's "signatures can easily be verified in parallel" realised
    across chips (reference: docs/source/whitepaper/
    corda-technical-whitepaper.tex:1597-1604).

    Selectable as ``verifier = "jax-sharded"`` in node config or
    CORDA_TPU_VERIFIER. The mesh spans all local devices by default
    (n_devices limits it); construction is lazy so importing the provider
    costs nothing on hosts without an initialised backend.
    """

    name = "jax-sharded"

    def __init__(self, n_devices: int | None = None,
                 shadow_rate: float = 0.0,
                 rng: random.Random | None = None,
                 device_min_sigs: int | None = None):
        super().__init__(shadow_rate=shadow_rate, rng=rng,
                         device_min_sigs=device_min_sigs)
        self.n_devices = n_devices
        self._mesh = None

    @property
    def mesh(self):
        if self._mesh is None:
            from ..ops import sharded

            # lint: allow(no-jit-in-hotpath) lazy one-time constructor: the mesh is built once and memoised on self._mesh; per-batch calls only read the cached object
            self._mesh = sharded.make_mesh(self.n_devices)
        return self._mesh

    def _verify_ed25519_device(self, jobs: Sequence[VerifyJob]) -> np.ndarray:
        from ..ops import sharded

        return sharded.verify_batch_sharded(
            [j.pubkey for j in jobs], [j.message for j in jobs],
            [j.sig for j in jobs], self.mesh)

    def pack_device(self, jobs: Sequence[VerifyJob]):
        """Host half of the mesh dispatch, routed EXACTLY like
        _verify_ed25519: batches the size/gate crossover would host-route
        return None (so the pipelined caller's fallback lands on the same
        tier this verifier would have chosen), as do mixed-scheme batches
        (the split path only accelerates the pure-ed25519 firehose shape)
        and all-malformed batches (the host tier answers those for free)."""
        if (not jobs
                or len(jobs) < self.device_min_sigs
                or (self.device_gate is not None
                    and not self.device_gate.is_set())
                or any(j.scheme != "ed25519" for j in jobs)):
            return None
        from ..ops import sharded

        return sharded.pack_batch_sharded(
            [j.pubkey for j in jobs], [j.message for j in jobs],
            [j.sig for j in jobs], self.mesh)

    def verify_packed(self, packed) -> np.ndarray:
        from ..ops import sharded

        self.device_batches += 1
        return sharded.dispatch_packed(packed)

    def warm(self) -> None:
        """Compile the SHARDED graphs this verifier actually dispatches
        (warming the single-chip kernel would open the gate without the
        mesh path ever compiling)."""
        from ..ops import sharded

        for n in WARM_SIZES:
            sharded.verify_batch_sharded([bytes(32)] * n, [bytes(32)] * n,
                                         [bytes(64)] * n, self.mesh)


def host_verify(jobs: Sequence[VerifyJob]) -> np.ndarray:
    """Verify a batch on the host tier regardless of any verifier's routing
    state — the degrade path's re-verify (oracle-exact accept set, so a
    batch the device would have accepted is accepted here too)."""
    return _dispatch_mixed(jobs, CpuVerifier._verify_ed25519_host)


# Seconds a degraded device tier stays demoted before the background
# re-probe tries the device path again.
DEVICE_REPROBE_COOLDOWN_S_DEFAULT = 5.0


def degrade_device(verifier, cooldown_s: float | None = None) -> bool:
    """Demote a device-backed verifier to its host tier after a device-path
    failure, and schedule a cooldown re-probe that re-opens the gate once
    the device answers again.

    Closes (or installs) ``verifier.device_gate`` — every future batch
    host-routes — then starts a daemon thread that sleeps ``cooldown_s``
    (default ``CORDA_TPU_DEVICE_REPROBE_COOLDOWN_S`` or 5 s), runs the
    verifier's own device path on a throwaway batch, and sets the gate on
    success; on failure it keeps the gate closed and retries after another
    cooldown. Returns False (no-op) for verifiers without a device tier.
    Safe to call repeatedly: a second failure while a re-probe is pending
    only bumps the counter."""
    if getattr(verifier, "device_min_sigs", None) is None:
        return False
    import threading
    import time as _t

    gate = getattr(verifier, "device_gate", None)
    if gate is None:
        gate = threading.Event()
        verifier.device_gate = gate
    probing = getattr(verifier, "_reprobe_thread", None)
    already_probing = (not gate.is_set() and probing is not None
                       and probing.is_alive())
    gate.clear()
    verifier.degraded = getattr(verifier, "degraded", 0) + 1
    if already_probing:
        return True
    if cooldown_s is None:
        cooldown_s = float(os.environ.get(
            "CORDA_TPU_DEVICE_REPROBE_COOLDOWN_S",
            DEVICE_REPROBE_COOLDOWN_S_DEFAULT))

    def _reprobe() -> None:
        # Garbage jobs: the probe cares that the device path ANSWERS (an
        # all-False result is fine), not that signatures validate.
        n = max(2, int(getattr(verifier, "device_min_sigs", 2) or 2))
        probe = [VerifyJob(bytes(32), bytes(32), bytes(64))] * n
        while not gate.is_set():
            _t.sleep(cooldown_s)
            try:
                verifier._verify_ed25519_device(probe)
            except Exception:
                verifier.reprobes_failed = getattr(
                    verifier, "reprobes_failed", 0) + 1
                continue
            verifier.reprobes_ok = getattr(verifier, "reprobes_ok", 0) + 1
            gate.set()

    t = threading.Thread(target=_reprobe, daemon=True, name="verify-reprobe")
    verifier._reprobe_thread = t
    t.start()
    return True


_default: BatchVerifier | None = None


def get_verifier() -> BatchVerifier:
    """The process-wide verifier. Defaults from CORDA_TPU_VERIFIER
    (cpu | jax | jax-shadow); cpu if unset."""
    global _default
    if _default is None:
        choice = os.environ.get("CORDA_TPU_VERIFIER", "cpu")
        _default = make_verifier(choice)
    return _default


def make_verifier(kind: str) -> BatchVerifier:
    """Provider factory shared by the env default and NodeConfig.verifier:
    cpu | jax | jax-shadow | jax-sharded. Unknown names raise — a typo
    must not silently demote a notary to the CPU path."""
    if kind == "jax":
        return JaxVerifier()
    if kind == "jax-shadow":
        return JaxVerifier(shadow_rate=0.05)
    if kind == "jax-sharded":
        return MeshVerifier()
    if kind == "cpu":
        return CpuVerifier()
    raise ValueError(
        f"unknown verifier {kind!r}: expected cpu | jax | jax-shadow | "
        "jax-sharded")


def set_verifier(verifier: BatchVerifier | None) -> None:
    """Install a provider (None resets to environment default)."""
    global _default
    _default = verifier
