"""Pure-Python ECDSA secp256r1 (P-256) verification — the conformance oracle.

Reference scope: the snapshot uses SHA256withECDSA on secp256r1 ONLY for
TLS/X.509 certificate signatures (reference: core/src/main/kotlin/net/corda/
core/crypto/X509Utilities.kt:44-48,223-233); every ledger signature is
Ed25519. BASELINE.json's north star nonetheless names mixed-scheme batches,
so the provider seam (crypto/provider.py VerifyJob.scheme) routes
"ecdsa-p256" jobs here. This module is the authoritative accept set —
dependency-free, like ref_ed25519 — with the OpenSSL path (when the
`cryptography` wheel is present) serving as an interop cross-check in tests.

Wire formats match the JCA/BouncyCastle usage the reference implies:
  * public key: SEC1 uncompressed point, 65 bytes 0x04 || X || Y;
  * signature: strict DER SEQUENCE { INTEGER r, INTEGER s } (the encoding
    JCA emits); any malformation REJECTS — never raises;
  * message: hashed with SHA-256 (SHA256withECDSA).
Any s in [1, n-1] is accepted (no low-s rule — JCA has none).
"""

from __future__ import annotations

import hashlib

# NIST P-256 / secp256r1 domain parameters (FIPS 186-4 D.1.2.3).
P = 0xffffffff00000001000000000000000000000000ffffffffffffffffffffffff
N = 0xffffffff00000000ffffffffffffffffbce6faada7179e84f3b9cac2fc632551
A = P - 3
B = 0x5ac635d8aa3a93e7b3ebbd55769886bc651d06b0cc53b0f63bce3c3e27d2604b
GX = 0x6b17d1f2e12c4247f8bce6e563a440f277037d812deb33a0f4a13945d898c296
GY = 0x4fe342e2fe1a7f9b8ee7eb4a7c0f9e162bce33576b315ececbb6406837bf51f5

_INF = None  # point at infinity


def _on_curve(x: int, y: int) -> bool:
    return (y * y - (x * x * x + A * x + B)) % P == 0


def _add(p1, p2):
    if p1 is _INF:
        return p2
    if p2 is _INF:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return _INF
        m = (3 * x1 * x1 + A) * pow(2 * y1, P - 2, P) % P
    else:
        m = (y2 - y1) * pow(x2 - x1, P - 2, P) % P
    x3 = (m * m - x1 - x2) % P
    return (x3, (m * (x1 - x3) - y1) % P)


def _mul(k: int, point):
    acc = _INF
    addend = point
    while k:
        if k & 1:
            acc = _add(acc, addend)
        addend = _add(addend, addend)
        k >>= 1
    return acc


def _parse_point(pub: bytes):
    """SEC1 uncompressed point -> (x, y), or None if malformed/off-curve."""
    if len(pub) != 65 or pub[0] != 0x04:
        return None
    x = int.from_bytes(pub[1:33], "big")
    y = int.from_bytes(pub[33:65], "big")
    if x >= P or y >= P or not _on_curve(x, y):
        return None
    return (x, y)


def _parse_der_sig(sig: bytes):
    """Strict DER SEQUENCE{INTEGER r, INTEGER s} -> (r, s), or None."""

    def parse_int(buf: bytes, at: int):
        if at + 2 > len(buf) or buf[at] != 0x02:
            return None
        length = buf[at + 1]
        if length & 0x80 or length == 0:  # no long/empty form for 256-bit ints
            return None
        start = at + 2
        end = start + length
        if end > len(buf):
            return None
        body = buf[start:end]
        if body[0] & 0x80:
            return None  # negative: invalid for r/s
        if len(body) > 1 and body[0] == 0 and not body[1] & 0x80:
            return None  # non-minimal encoding
        return int.from_bytes(body, "big"), end

    if len(sig) < 8 or sig[0] != 0x30:
        return None
    total = sig[1]
    if total & 0x80 or 2 + total != len(sig):
        return None
    got = parse_int(sig, 2)
    if got is None:
        return None
    r, at = got
    got = parse_int(sig, at)
    if got is None:
        return None
    s, at = got
    if at != len(sig):
        return None
    return (r, s)


def verify(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    """SHA256withECDSA verification; malformed anything rejects."""
    try:
        q = _parse_point(bytes(pubkey))
        if q is None:
            return False
        parsed = _parse_der_sig(bytes(sig))
        if parsed is None:
            return False
        r, s = parsed
        if not (1 <= r < N and 1 <= s < N):
            return False
        e = int.from_bytes(hashlib.sha256(bytes(msg)).digest(), "big")
        w = pow(s, N - 2, N)
        u1 = (e * w) % N
        u2 = (r * w) % N
        point = _add(_mul(u1, (GX, GY)), _mul(u2, q))
        if point is _INF:
            return False
        return point[0] % N == r
    except Exception:
        return False
