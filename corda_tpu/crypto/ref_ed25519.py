"""Pure-Python Ed25519 reference implementation — the conformance oracle.

This module defines the *authoritative* accept/reject semantics for signature
verification in corda_tpu. The TPU kernel (corda_tpu/ops/ed25519.py) must match
this oracle bit-for-bit; golden-vector tests enforce that.

Semantics mirror the reference framework's signing stack: the reference signs and
verifies Ed25519 via the i2p EdDSA engine (reference:
core/src/main/kotlin/net/corda/core/crypto/CryptoUtilities.kt:63-96 — helpers are
named signWithECDSA/verifyWithECDSA but construct EdDSAEngine over curve
Ed25519-SHA512). That library follows the classic ref10 verification procedure:

  * *cofactorless* verify:  recompute R' = [S]B - [h]A  and byte-compare
    encode(R') with the first 32 bytes of the signature,
  * h = SHA-512(R_enc || A_enc || M) reduced mod L. We hash the *original*
    A encoding (ref10/SUPERCOP semantics: the pk bytes go straight into the
    hash). Caveat: the i2p library may re-encode A canonically before hashing
    (its 0.1.0 source is not available here to confirm); the two differ only
    for crafted non-canonical A encodings, which exist only for y < 19 — a
    measure-zero adversarial corner, documented as a known ambiguity. This
    oracle is the authority for corda_tpu either way,
  * S is taken as a 256-bit little-endian integer with **no** S < L range
    check (the range check only appeared in later versions of the library),
  * point decompression reduces y mod p silently, so a non-canonical A encoding
    (y >= p) is accepted; a non-canonical R encoding is effectively rejected by
    the final byte-compare (the recomputed encoding is always canonical),
  * a y with no valid x on the curve rejects; x == 0 with sign bit 1 is NOT
    special-cased (ref10 behaviour, unlike strict RFC 8032).

Signing follows RFC 8032 (identical to what the reference's library produces).

This is deliberately slow, simple Python-integer math: it exists for
correctness, golden-vector generation, and as the CPU conformance path that
shadows the TPU kernel.
"""

from __future__ import annotations

import hashlib

__all__ = [
    "P", "L", "D", "B",
    "sign", "verify", "public_key", "decompress", "compress",
    "point_add", "point_double", "scalar_mult", "double_scalar_mult_sub",
]

# Curve constants (edwards25519): -x^2 + y^2 = 1 + d x^2 y^2 over F_p.
P = 2 ** 255 - 19
L = 2 ** 252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p

# Base point B: y = 4/5, x recovered with even parity.
_BY = (4 * pow(5, P - 2, P)) % P


def _recover_x(y: int, sign: int) -> int | None:
    """Recover x from y on edwards25519; None if y^2-1/(d y^2+1) is a non-residue.

    Mirrors ref10 ge_frombytes: candidate root via exponentiation by (p+3)/8,
    fix-up by sqrt(-1), no x==0/sign special case.
    """
    u = (y * y - 1) % P
    v = (D * y * y + 1) % P
    # x = u/v ^ ((p+3)/8) computed as u * v^3 * (u * v^7)^((p-5)/8)
    x = (u * pow(v, 3, P) * pow(u * pow(v, 7, P), (P - 5) // 8, P)) % P
    vxx = (v * x * x) % P
    if vxx == u:
        pass
    elif vxx == (-u) % P:
        x = (x * SQRT_M1) % P
    else:
        return None
    if x & 1 != sign:
        x = (-x) % P
    return x


_BX = _recover_x(_BY, 0)
B = (_BX, _BY)


# Extended coordinates (X:Y:Z:T) with x=X/Z, y=Y/Z, T=XY/Z — the same
# complete unified formulas the TPU kernel uses (a=-1 twisted Edwards,
# complete because -1 is a square and d a non-square mod p).


def _to_ext(pt):
    x, y = pt
    return (x, y, 1, (x * y) % P)


def _from_ext(e):
    x, y, z, _ = e
    zi = pow(z, P - 2, P)
    return ((x * zi) % P, (y * zi) % P)


_EXT_ID = (0, 1, 1, 0)


def _ext_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = ((y1 - x1) * (y2 - x2)) % P
    b = ((y1 + x1) * (y2 + x2)) % P
    c = (2 * D * t1 * t2) % P
    dd = (2 * z1 * z2) % P
    e, f, g, h = (b - a) % P, (dd - c) % P, (dd + c) % P, (b + a) % P
    return ((e * f) % P, (g * h) % P, (f * g) % P, (e * h) % P)


def _ext_double(p):
    return _ext_add(p, p)


def point_add(p1, p2):
    """Affine twisted-Edwards addition (complete for edwards25519)."""
    return _from_ext(_ext_add(_to_ext(p1), _to_ext(p2)))


def point_double(p1):
    return point_add(p1, p1)


def scalar_mult(k: int, pt):
    """Double-and-add [k]pt; k may exceed L (reduced implicitly by group order)."""
    q = _EXT_ID
    e = _to_ext(pt)
    while k > 0:
        if k & 1:
            q = _ext_add(q, e)
        e = _ext_double(e)
        k >>= 1
    return _from_ext(q)


def double_scalar_mult_sub(s: int, h: int, a_pt):
    """[s]B - [h]A, the ref10 verification combination."""
    neg_a = ((-a_pt[0]) % P, a_pt[1])
    acc = _EXT_ID
    eb, ea = _to_ext(B), _to_ext(neg_a)
    while s > 0 or h > 0:
        if s & 1:
            acc = _ext_add(acc, eb)
        if h & 1:
            acc = _ext_add(acc, ea)
        eb, ea = _ext_double(eb), _ext_double(ea)
        s >>= 1
        h >>= 1
    return _from_ext(acc)


def compress(pt) -> bytes:
    x, y = pt
    return int.to_bytes(y | ((x & 1) << 255), 32, "little")


def decompress(enc: bytes) -> tuple | None:
    """Decode a 32-byte point; reduces y mod p silently (ref10 semantics)."""
    if len(enc) != 32:
        return None
    n = int.from_bytes(enc, "little")
    sign = n >> 255
    y = (n & ((1 << 255) - 1)) % P
    x = _recover_x(y, sign)
    if x is None:
        return None
    return (x, y)


def _sha512_mod_l(data: bytes) -> int:
    return int.from_bytes(hashlib.sha512(data).digest(), "little") % L


def public_key(seed: bytes) -> bytes:
    """RFC 8032 public key derivation from a 32-byte seed."""
    if len(seed) != 32:
        raise ValueError(f"Ed25519 seed must be 32 bytes, got {len(seed)}")
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    return compress(scalar_mult(a, B))


def sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 Ed25519 signature (R_enc || S), 64 bytes."""
    if len(seed) != 32:
        raise ValueError(f"Ed25519 seed must be 32 bytes, got {len(seed)}")
    h = hashlib.sha512(seed).digest()
    a = int.from_bytes(h[:32], "little")
    a &= (1 << 254) - 8
    a |= 1 << 254
    prefix = h[32:]
    a_enc = compress(scalar_mult(a, B))
    r = _sha512_mod_l(prefix + msg)
    r_enc = compress(scalar_mult(r, B))
    s = (r + _sha512_mod_l(r_enc + a_enc + msg) * a) % L
    return r_enc + int.to_bytes(s, 32, "little")


def verify(pubkey: bytes, msg: bytes, sig: bytes) -> bool:
    """Cofactorless ref10-style verification. Never raises on malformed input.

    Matches the accept set of the reference's EdDSAEngine.verify (reference:
    core/.../crypto/CryptoUtilities.kt:90-96 wraps it; a `false`/exception both
    surface as rejection at SignedTransaction.verifySignatures, reference:
    core/.../transactions/SignedTransaction.kt:83-87).
    """
    if len(sig) != 64 or len(pubkey) != 32:
        return False
    a_pt = decompress(pubkey)
    if a_pt is None:
        return False
    r_enc, s_enc = sig[:32], sig[32:]
    s = int.from_bytes(s_enc, "little")  # deliberately NO s < L check
    h = _sha512_mod_l(r_enc + pubkey + msg)
    r_check = double_scalar_mult_sub(s, h, a_pt)
    return compress(r_check) == r_enc
