"""Device-owning verification sidecar: cross-process batch coalescing.

The round-5 flagship gap: the Pallas kernel streams ~292k sigs/s, but the
raft-validating multiprocess loadtest delivered 3.9k sigs/s with
``device_batches=0`` — each node PROCESS accumulates its own micro-batches,
every one below device_min_sigs, so all traffic host-routed and the device
sat idle on exactly the path BASELINE.json measures. Per-process batching
cannot fix this: the batches are small because each run loop only sees its
own flows.

This module is the missing seam the north-star design prescribes (PAPER §7:
micro-batches ship "over a JNI/gRPC bridge to a JAX sidecar" owning the
accelerator): ONE verification server per host, fed by every node process
over a local socket, coalescing requests ACROSS processes before dispatch —
clipper/serving-style adaptive batching (PAPERS.md).

Server structure (mirrors async_verify.py's pipeline, one level up):
  reader threads   — one per client connection; decode framed requests into
                     a shared pending queue.
  scheduler thread — deadline-based coalescing: holds the queue open from
                     the FIRST pending request for up to coalesce_us,
                     flushing early when pending sigs reach max_sigs
                     (bucket capacity). Whole requests only — a request is
                     never split across batches, so per-client replies stay
                     one frame. After forming a batch it also runs the
                     HOST half of the device dispatch (pack_device:
                     columnar packing into padded kernel arrays), so
                     packing batch N+1 overlaps device execution of
                     batch N on the executor thread.
  executor thread  — dispatches the pre-packed arrays (verify_packed) or,
                     for host-routed/unpackable batches, one verify_batch
                     call on the server's verifier (the
                     DeviceRoutedVerifier size/gate routing and the padded
                     pick_bucket executable cache in ops/ed25519_jax are
                     reused unchanged), then splits results per request.
  depth-2 buffering: a BoundedSemaphore(depth) between scheduler and
                     executor lets the scheduler coalesce AND pack the
                     NEXT batch while the current one runs on the device.

Mesh ownership (round 10): ``devices=N`` makes the server own a JAX device
mesh instead of one chip — the verifier becomes a MeshVerifier whose
coalesced buckets are sharded data-parallel across the N local devices
(ops/sharded.py shard_map with fixed in/out shardings, so repeated
dispatches reuse one executable per bucket and never re-partition). The
bucket ladder is rounded up to a multiple of the mesh size
(pad_to_devices), every device gets an equal slice, and the pad waste is
attributed in stats (pad_fraction / per_device_occupancy /
per_device_batch_sigs_hist). devices=1 keeps the exact single-device
verifier; a mesh that cannot be built (fewer local devices than asked)
leaves the boot-warm gate closed so every batch takes the oracle-exact
host tier — degraded throughput, never a wrong answer.

Wire protocol — length-prefixed frames over a stream socket (unix path or
host:port), little-endian throughout:
  frame    := u32(len) payload
  request  := u8(op) u32(req_id) body
  OP_VERIFY  body:  u32(n)  pubkeys n*32  sigs n*64  u32 msg_len[n]  msgs
  OP_VERIFY  reply: u8(op) u32(req_id) u8(status) u8(tier)
                    f32(wait_s) f32(verify_s)  u8 ok[n]     (tier: 1=device)
  OP_STATS   reply: u8(op) u32(req_id) u8(status)  json(stats) utf-8
  OP_METRICS reply: u8(op) u32(req_id) u8(status)  prometheus text utf-8
  OP_PING    reply: u8(op) u32(req_id) u8(status)
Only well-formed ed25519 jobs ride the fixed-width arrays; the client
rejects wrong-length keys/sigs locally (same semantics as the kernel path:
malformed input rejects, never raises).

Crash contract: the sidecar holds NO durable state. A dead sidecar is an
infra fault — clients degrade to their local host tier (oracle-exact accept
set) through provider.degrade_device and re-probe on a cooldown; flows
in-flight at the moment of death replay at-least-once like any other verify
infra failure. The sidecar can never make a node commit a wrong answer.
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import socket
import struct
import threading
import time
from collections import deque
from typing import Sequence

import numpy as np

from ..obs import telemetry as _tm
from .provider import VerifyJob, make_verifier

OP_VERIFY = 1
OP_STATS = 2
OP_PING = 3
# OP_VERIFY with a QoS prefix (lane code + interactive deadline in epoch
# ns): same columnar body, same OP_VERIFY reply. Sent only when the
# client's QoS plane is armed AND its micro-batch carried an interactive
# deadline — a disarmed cluster never emits this op, and a pre-QoS server
# rejects it loudly (unknown op drops the connection, the client degrades
# to its host tier) instead of silently mis-scheduling.
OP_VERIFY_QOS = 4
# Prometheus text exposition of this process's telemetry registry
# (obs/export.py render): the sidecar's /metrics — same framing as
# OP_STATS with a text body instead of JSON.
OP_METRICS = 5

STATUS_OK = 0
STATUS_ERR = 1

# Lane codes on the wire (mirrors qos/context.py; this module stays
# importable without the qos package on pre-QoS peers).
LANE_CODE_INTERACTIVE = 0
LANE_CODE_BULK = 1

# One frame bounds one coalesced request: 64 MiB covers max_sigs=65536 jobs
# of pubkey+sig+len plus ~900-byte messages — far beyond any pump batch.
MAX_FRAME = 64 * 1024 * 1024

_FRAME_HDR = struct.Struct("<I")
_REQ_HDR = struct.Struct("<BI")
_VERIFY_REQ_HDR = struct.Struct("<BII")
# op, req_id, n, lane code, deadline_ns (epoch; 0 = no deadline).
_VERIFY_QOS_REQ_HDR = struct.Struct("<BIIBQ")
_REPLY_HDR = struct.Struct("<BIB")
_VERIFY_REPLY_HDR = struct.Struct("<BIBBff")

# The kernel's padded-bucket ladder (ops/ed25519_jax.pick_bucket), mirrored
# here so the batch-size histogram keys by executable bucket without this
# module ever importing jax (stats must work on host-only processes).
BUCKETS = (64, 256, 1024, 4096, 16384, 65536)


def bucket_for(n: int) -> int:
    for b in BUCKETS:
        if n <= b:
            return b
    return BUCKETS[-1]


def pad_to_devices(n: int, n_devices: int) -> int:
    """Smallest multiple of n_devices >= max(n, n_devices) — mirrored from
    ops/sharded.py (pure arithmetic) for the same reason BUCKETS mirrors
    pick_bucket: pad attribution must work without importing jax."""
    return -(-max(n, 1) // max(n_devices, 1)) * max(n_devices, 1)


# Adaptive coalesce_us policy (ROADMAP item 1: grow the deadline from the
# observed batch-size histogram so the mesh sees full buckets; shrink it
# when batches fill early so p99 never pays for an idle window). Same
# hysteresis/multiplicative-step idiom as async_verify.AdaptiveCrossover.
ADAPT_WINDOW = 8        # executed batches per decision
ADAPT_GROW = 1.5
ADAPT_SHRINK = 0.75
ADAPT_SEED_US = 200     # first growth step out of coalesce_us=0
ADAPT_CEILING_US = 20_000


# ---------------------------------------------------------------------------
# Framing + codec (shared by server and node/verify_client.py)
# ---------------------------------------------------------------------------


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_FRAME_HDR.pack(len(payload)) + payload)


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("sidecar connection closed")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> bytes:
    (ln,) = _FRAME_HDR.unpack(recv_exact(sock, _FRAME_HDR.size))
    if ln > MAX_FRAME:
        raise ConnectionError(f"sidecar frame too large: {ln}")
    return recv_exact(sock, ln)


def _encode_jobs(jobs: Sequence[VerifyJob]) -> bytes:
    """Columnar job body shared by both verify ops: the server decodes
    with numpy slices, mirroring the native/_cverify packers."""
    n = len(jobs)
    return b"".join((
        b"".join(bytes(j.pubkey) for j in jobs),
        b"".join(bytes(j.sig) for j in jobs),
        np.fromiter((len(j.message) for j in jobs), "<u4", n).tobytes(),
        b"".join(bytes(j.message) for j in jobs),
    ))


def _decode_jobs(payload: bytes, off: int, n: int) -> list[VerifyJob]:
    pks = payload[off:off + 32 * n]
    off += 32 * n
    sigs = payload[off:off + 64 * n]
    off += 64 * n
    lens = np.frombuffer(payload, "<u4", n, off)
    off += 4 * n
    if len(pks) != 32 * n or len(sigs) != 64 * n:
        raise ValueError("short sidecar verify request")
    jobs = []
    for i in range(n):
        ln = int(lens[i])
        msg = payload[off:off + ln]
        if len(msg) != ln:
            raise ValueError("short sidecar verify request")
        off += ln
        jobs.append(VerifyJob(pks[32 * i:32 * i + 32], msg,
                              sigs[64 * i:64 * i + 64]))
    return jobs


def encode_verify_request(req_id: int, jobs: Sequence[VerifyJob]) -> bytes:
    """Pack well-formed ed25519 jobs (32-byte keys, 64-byte sigs) into one
    OP_VERIFY payload."""
    return _VERIFY_REQ_HDR.pack(OP_VERIFY, req_id, len(jobs)) \
        + _encode_jobs(jobs)


def decode_verify_request(payload: bytes):
    """-> (req_id, [VerifyJob...]); raises on a malformed frame (the reader
    drops the connection — a corrupt stream cannot be resynchronised)."""
    _op, req_id, n = _VERIFY_REQ_HDR.unpack_from(payload)
    return req_id, _decode_jobs(payload, _VERIFY_REQ_HDR.size, n)


def encode_verify_request_qos(req_id: int, jobs: Sequence[VerifyJob],
                              lane: int, deadline_ns: int) -> bytes:
    """OP_VERIFY_QOS: the OP_VERIFY body prefixed with the micro-batch's
    lane and earliest interactive deadline (epoch ns; 0 = none)."""
    return _VERIFY_QOS_REQ_HDR.pack(
        OP_VERIFY_QOS, req_id, len(jobs), lane,
        deadline_ns & 0xFFFFFFFFFFFFFFFF) + _encode_jobs(jobs)


def decode_verify_request_qos(payload: bytes):
    """-> (req_id, [VerifyJob...], lane, deadline_ns); raises on junk."""
    _op, req_id, n, lane, deadline_ns = \
        _VERIFY_QOS_REQ_HDR.unpack_from(payload)
    if lane not in (LANE_CODE_INTERACTIVE, LANE_CODE_BULK):
        raise ValueError(f"unknown sidecar lane code {lane}")
    return (req_id, _decode_jobs(payload, _VERIFY_QOS_REQ_HDR.size, n),
            lane, deadline_ns)


def parse_address(address: str):
    """'host:port' -> ("tcp", (host, port)); anything else is a unix
    socket path."""
    if ":" in address and "/" not in address:
        host, port = address.rsplit(":", 1)
        return "tcp", (host or "127.0.0.1", int(port))
    return "unix", address


def connect(address: str, timeout: float | None = None) -> socket.socket:
    kind, addr = parse_address(address)
    if kind == "tcp":
        sock = socket.create_connection(addr, timeout=timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    else:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(addr)
    return sock


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class _Client:
    """One accepted connection. The write lock serialises replies: verify
    replies come from the executor thread while stats/ping replies come
    from the connection's own reader thread."""

    __slots__ = ("conn", "lock")

    def __init__(self, conn: socket.socket):
        self.conn = conn
        self.lock = threading.Lock()

    def reply(self, payload: bytes) -> None:
        # lint: allow(no-blocking-under-lock) this per-client lock EXISTS to serialize frames on one socket (executor vs reader thread); nothing else ever contends on it
        with self.lock:
            send_frame(self.conn, payload)


class _Pending:
    __slots__ = ("client", "req_id", "jobs", "received_at", "lane",
                 "deadline_ns")

    def __init__(self, client: _Client, req_id: int,
                 jobs: list[VerifyJob], lane: int | None = None,
                 deadline_ns: int = 0):
        self.client = client
        self.req_id = req_id
        self.jobs = jobs
        self.received_at = time.perf_counter()
        # QoS prefix from OP_VERIFY_QOS; None/0 for plain OP_VERIFY
        # requests, which schedule exactly as before.
        self.lane = lane
        self.deadline_ns = deadline_ns


_STOP = object()


class SidecarServer:
    """The per-host verification server. One instance owns the device (via
    its verifier); every node process on the host connects as a client."""

    def __init__(self, address: str, verifier=None, verifier_kind: str = "cpu",
                 coalesce_us: int = 2000, max_sigs: int = 4096,
                 depth: int = 2, device_min_sigs: int | None = None,
                 devices: int | None = None,
                 adaptive_coalesce: bool = False,
                 qos_guard_us: int = 2000):
        self.address = address
        self.devices = int(devices or 0)
        if verifier is None:
            verifier = self._make_server_verifier(verifier_kind, self.devices)
        self.verifier = verifier
        if not self.devices:
            self.devices = int(getattr(verifier, "n_devices", None) or 0)
        if device_min_sigs is not None and hasattr(
                self.verifier, "device_min_sigs"):
            self.verifier.device_min_sigs = device_min_sigs
        self.coalesce_us = int(coalesce_us)
        self.coalesce_us_initial = int(coalesce_us)
        self.adaptive_coalesce = bool(adaptive_coalesce)
        self.coalesce_adjustments = 0
        self._win_batches = 0
        self._win_requests = 0
        self._win_sigs = 0
        self.max_sigs = int(max_sigs)
        self.depth = int(depth)
        # Mesh bookkeeping: mesh_devices is the PROVEN mesh size (set by the
        # warm thread once make_mesh succeeds); warm_error records why a
        # device/mesh tier never opened. Pad attribution prefers the packed
        # handle's exact numbers and falls back to arithmetic on these.
        self.mesh_devices: int | None = None
        self.warm_error: str | None = None

        self._pending: deque[_Pending] = deque()
        self._cv = threading.Condition()
        self._exec_q: queue.SimpleQueue = queue.SimpleQueue()
        # Depth-2 double buffering: the scheduler may have up to `depth`
        # batches formed-or-running, so it keeps coalescing the next batch
        # while the executor holds the device.
        self._slots = threading.BoundedSemaphore(self.depth)
        self._stop = threading.Event()
        self._listener: socket.socket | None = None
        self._clients: list[_Client] = []
        self._threads: list[threading.Thread] = []
        self._lock = threading.Lock()  # stats counters
        self.requests = 0
        self.batches = 0
        self.sigs = 0
        self.cross_request_batches = 0
        self.errors = 0
        self.batch_sigs_hist: dict[int, int] = {}
        self.wait_s_total = 0.0
        self.verify_s_total = 0.0
        # Mesh/pipeline accounting: packed_batches took the split
        # pack-then-dispatch path (packing overlapped the previous batch's
        # device execution); device_lanes counts lanes actually DISPATCHED
        # on the device tier (bucket-padded), pad_lanes the subset carrying
        # no real signature; the per-device histogram keys by each device's
        # lane share per dispatch.
        self.packed_batches = 0
        self.pack_s_total = 0.0
        self.device_lanes = 0
        self.pad_lanes = 0
        self.per_device_batch_sigs_hist: dict[int, int] = {}
        # QoS (OP_VERIFY_QOS): flush when the earliest interactive
        # deadline is this close (converted to ns once), and count how the
        # deadline scheduler behaved.
        self.qos_guard_ns = int(qos_guard_us) * 1000
        self.qos_early_flushes = 0
        self.qos_interactive_requests = 0
        self.qos_bulk_requests = 0

    @staticmethod
    def _make_server_verifier(kind: str, devices: int):
        """devices > 1 upgrades any jax-tier verifier to a mesh-owning
        MeshVerifier over exactly that many local devices; devices <= 1
        keeps the PR-5 single-device tiers bit-identical (``jax`` stays
        JaxVerifier). A cpu verifier ignores devices — there is no device
        tier to shard."""
        if devices > 1 and kind.startswith("jax"):
            from .provider import MeshVerifier

            return MeshVerifier(
                n_devices=devices,
                shadow_rate=0.05 if kind == "jax-shadow" else 0.0)
        return make_verifier(kind)

    # -- lifecycle ----------------------------------------------------------

    def start(self, warm: bool = True) -> "SidecarServer":
        kind, addr = parse_address(self.address)
        if kind == "unix":
            try:
                os.unlink(addr)
            except FileNotFoundError:
                pass
            listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            listener.bind(addr)
        else:
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            listener.bind(addr)
            host, port = listener.getsockname()[:2]
            self.address = f"{host}:{port}"  # resolve port 0
        listener.listen(64)
        self._listener = listener
        if warm:
            self._warm_maybe()
        for target, name in ((self._accept_loop, "sidecar-accept"),
                             (self._scheduler, "sidecar-scheduler"),
                             (self._executor, "sidecar-executor")):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        return self

    def _warm_maybe(self) -> None:
        """Same boot-warm contract as node._warm_verifier_maybe: install a
        closed device_gate, compile in the background, open the gate when
        the device answers. Host traffic flows (host-routed) meanwhile."""
        verifier = self.verifier
        if not getattr(verifier, "name", "").startswith("jax"):
            return
        gate = threading.Event()
        verifier.device_gate = gate
        # Class-level lookup on purpose: `mesh` is a LAZY property that
        # builds the mesh (and raises when the host can't) — probing the
        # instance would pull that raise into start() instead of the warm
        # thread, where it belongs.
        is_mesh = hasattr(type(verifier), "mesh")

        def _warm() -> None:
            ok = False
            try:
                if is_mesh:
                    # The mesh must be PROVEN before the gate opens:
                    # make_mesh raises when fewer local devices exist than
                    # asked for, and an open gate would route every batch
                    # into that raise.
                    self.mesh_devices = int(verifier.mesh.devices.size)
                import jax

                if jax.default_backend() != "cpu":
                    verifier.warm()
                # else: CPU-backend compiles are cheap; no warm needed
                ok = True
            except Exception as exc:
                self.warm_error = f"{type(exc).__name__}: {exc}"
            if ok or not is_mesh:
                # Non-mesh verifiers keep the PR-5 contract: the gate opens
                # even after a failed warm, the first failing dispatch
                # produces an error REPLY, and the client degrades. A mesh
                # that could not be built must never open the gate — every
                # batch host-routes to the oracle-exact tier instead of
                # raising per batch (degraded throughput, right answers).
                gate.set()

        threading.Thread(target=_warm, daemon=True,
                         name="sidecar-warm").start()

    def stop(self) -> None:
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        self._exec_q.put(_STOP)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._lock:
            clients = list(self._clients)
        for c in clients:
            try:
                c.conn.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        kind, addr = parse_address(self.address)
        if kind == "unix":
            try:
                os.unlink(addr)
            except OSError:
                pass

    # -- connection handling ------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed
            try:
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass  # unix sockets have no TCP options
            client = _Client(conn)
            with self._lock:
                self._clients.append(client)
            t = threading.Thread(target=self._serve_conn, args=(client,),
                                 daemon=True, name="sidecar-conn")
            t.start()

    def _serve_conn(self, client: _Client) -> None:
        try:
            while not self._stop.is_set():
                payload = recv_frame(client.conn)
                op, req_id = _REQ_HDR.unpack_from(payload)
                if op in (OP_VERIFY, OP_VERIFY_QOS):
                    if op == OP_VERIFY:
                        _, jobs = decode_verify_request(payload)
                        pend = _Pending(client, req_id, jobs)
                    else:
                        _, jobs, lane, deadline_ns = \
                            decode_verify_request_qos(payload)
                        pend = _Pending(client, req_id, jobs, lane=lane,
                                        deadline_ns=deadline_ns)
                    # Stats counters mutate under _lock (the lock stats()
                    # reads them under) — never under _cv, so the two locks
                    # are never held together and reader threads can't
                    # lose increments against other stats writers.
                    with self._lock:
                        self.requests += 1
                        if pend.lane == LANE_CODE_INTERACTIVE:
                            self.qos_interactive_requests += 1
                        elif pend.lane == LANE_CODE_BULK:
                            self.qos_bulk_requests += 1
                    if _tm.ACTIVE is not None:
                        _tm.inc("sidecar_requests_total")
                    with self._cv:
                        self._pending.append(pend)
                        self._cv.notify_all()
                elif op == OP_STATS:
                    body = json.dumps(self.stats()).encode()
                    client.reply(
                        _REPLY_HDR.pack(OP_STATS, req_id, STATUS_OK) + body)
                elif op == OP_METRICS:
                    from ..obs.export import render_prometheus

                    client.reply(
                        _REPLY_HDR.pack(OP_METRICS, req_id, STATUS_OK)
                        + render_prometheus().encode())
                elif op == OP_PING:
                    client.reply(_REPLY_HDR.pack(OP_PING, req_id, STATUS_OK))
                else:
                    raise ValueError(f"unknown sidecar op {op}")
        except (ConnectionError, OSError, ValueError, struct.error):
            pass  # client went away or sent garbage: drop the connection
        finally:
            try:
                client.conn.close()
            except OSError:
                pass
            with self._lock:
                if client in self._clients:
                    self._clients.remove(client)

    # -- coalescing scheduler ----------------------------------------------

    def _pending_sigs(self) -> int:
        return sum(len(p.jobs) for p in self._pending)

    def _min_interactive_deadline_ns(self) -> int:
        """Earliest interactive deadline among pending requests (0 = none).
        Called under _cv."""
        dl = 0
        for p in self._pending:
            if (p.lane == LANE_CODE_INTERACTIVE and p.deadline_ns > 0
                    and (dl == 0 or p.deadline_ns < dl)):
                dl = p.deadline_ns
        return dl

    def _form_batch(self) -> tuple[list[_Pending], bool]:
        """Take up to max_sigs from pending. With no bulk requests waiting
        this is exactly the old FIFO popleft loop (bit-identical order);
        when both classes wait, interactive (and unlabelled) requests pack
        first — FIFO within each class — so a full batch is cut from the
        latency-sensitive end and bulk rides the next one. Returns (batch,
        any bulk was deferred behind interactive). Called under _cv."""
        if not any(p.lane == LANE_CODE_BULK for p in self._pending):
            batch: list[_Pending] = []
            total = 0
            while self._pending and total < self.max_sigs:
                p = self._pending.popleft()
                batch.append(p)
                total += len(p.jobs)
            return batch, False
        pending = list(self._pending)
        ordered = ([p for p in pending if p.lane != LANE_CODE_BULK]
                   + [p for p in pending if p.lane == LANE_CODE_BULK])
        batch, taken, total = [], set(), 0
        for p in ordered:
            if total >= self.max_sigs:
                break
            batch.append(p)
            taken.add(id(p))
            total += len(p.jobs)
        self._pending = deque(p for p in pending if id(p) not in taken)
        reordered = any(p.lane == LANE_CODE_BULK for p in self._pending)
        return batch, reordered

    def _scheduler(self) -> None:
        while True:
            qos_flush = False
            with self._cv:
                while not self._pending:
                    if self._stop.is_set():
                        return
                    self._cv.wait(0.1)
                # The deadline anchors on the OLDEST pending request: no
                # request waits longer than coalesce_us for company.
                deadline = (self._pending[0].received_at
                            + self.coalesce_us / 1e6)
                while (self._pending_sigs() < self.max_sigs
                       and not self._stop.is_set()):
                    limit = deadline
                    dl_ns = self._min_interactive_deadline_ns()
                    if dl_ns:
                        # Translate the epoch-ns interactive deadline onto
                        # the perf_counter timeline: flush guard_ns before
                        # it so verify+reply still fit inside the SLO.
                        qos_limit = (time.perf_counter()
                                     + (dl_ns - self.qos_guard_ns
                                        - time.time_ns()) / 1e9)
                        if qos_limit < limit:
                            limit = qos_limit
                    remaining = limit - time.perf_counter()
                    if remaining <= 0:
                        # Early only on the QoS clock? (coalesce window
                        # still open = a deadline-triggered flush.)
                        qos_flush = deadline - time.perf_counter() > 0
                        break
                    self._cv.wait(remaining)
                batch, _reordered = self._form_batch()
            if qos_flush:
                with self._lock:
                    self.qos_early_flushes += 1
            # Blocks while `depth` batches are in flight — backpressure
            # that keeps the executor at most one batch ahead. Timed so
            # shutdown can't wedge this thread if the executor exited
            # without releasing.
            while not self._slots.acquire(timeout=0.2):
                if self._stop.is_set():
                    return
            if self._stop.is_set():
                self._slots.release()
                return
            # Host half of the device dispatch runs HERE, on the scheduler
            # thread: while the executor holds the device with batch N,
            # this packs batch N+1's kernel arrays (limb decompression,
            # radix split, bucket padding) — the depth-2 slot already
            # admitted it. pack_device routes exactly like verify_batch
            # would (size/gate/scheme), returning None for batches the
            # verifier would host-route; the executor then takes the
            # ordinary unsplit path, so routing semantics never fork.
            jobs = [j for p in batch for j in p.jobs]
            packed = None
            pack_s = 0.0
            pack_fn = getattr(self.verifier, "pack_device", None)
            if pack_fn is not None:
                t_pack = time.perf_counter()
                try:
                    packed = pack_fn(jobs)
                except Exception:
                    packed = None  # unsplit path decides (and may reply ERR)
                pack_s = time.perf_counter() - t_pack
            self._exec_q.put((batch, jobs, packed, pack_s))

    # -- executor -----------------------------------------------------------

    def _executor(self) -> None:
        while True:
            item = self._exec_q.get()
            if item is _STOP:
                return
            batch, jobs, packed, pack_s = item
            before_dev = getattr(self.verifier, "device_batches", 0) or 0
            t0 = time.perf_counter()
            err = None
            try:
                if packed is not None:
                    # Pre-packed by the scheduler (overlapped with the
                    # previous batch's device execution): dispatch only.
                    ok = self.verifier.verify_packed(packed)
                else:
                    ok = self.verifier.verify_batch(jobs)
            except Exception as exc:  # noqa: BLE001
                # Providers reject-never-raise, but a dying device backend
                # can still throw; an error REPLY (not silence) lets the
                # client degrade immediately instead of eating a deadline.
                ok, err = None, exc
            verify_s = time.perf_counter() - t0
            tier = 1 if (getattr(self.verifier, "device_batches", 0)
                         or 0) > before_dev else 0
            if _tm.ACTIVE is not None:
                _tm.inc("sidecar_batches_total")
                _tm.inc("sidecar_sigs_total", len(jobs))
                _tm.observe("sidecar_batch_sigs", len(jobs))
            with self._lock:
                self.batches += 1
                self.sigs += len(jobs)
                if len(batch) > 1:
                    self.cross_request_batches += 1
                if err is not None:
                    self.errors += 1
                b = bucket_for(len(jobs))
                self.batch_sigs_hist[b] = self.batch_sigs_hist.get(b, 0) + 1
                self.verify_s_total += verify_s
                self.wait_s_total += sum(t0 - p.received_at for p in batch)
                if packed is not None:
                    self.packed_batches += 1
                    self.pack_s_total += pack_s
                if tier == 1 and err is None:
                    # Pad attribution: the packed handle knows the exact
                    # dispatched bucket and mesh width; the unsplit device
                    # path is reconstructed arithmetically (same ladder).
                    ndev = (packed.n_devices if packed is not None
                            else (self.mesh_devices or self.devices or 1))
                    lanes = (packed.bucket if packed is not None
                             else pad_to_devices(bucket_for(len(jobs)), ndev))
                    real = (len(packed.good) if packed is not None
                            else len(jobs))
                    self.device_lanes += lanes
                    self.pad_lanes += lanes - real
                    share = lanes // ndev
                    self.per_device_batch_sigs_hist[share] = (
                        self.per_device_batch_sigs_hist.get(share, 0) + 1)
                if self.adaptive_coalesce:
                    self._adapt_observe(len(batch), len(jobs))
            offset = 0
            for p in batch:
                n = len(p.jobs)
                head = _VERIFY_REPLY_HDR.pack(
                    OP_VERIFY, p.req_id,
                    STATUS_OK if err is None else STATUS_ERR, tier,
                    t0 - p.received_at, verify_s)
                if err is None:
                    body = np.asarray(ok[offset:offset + n],
                                      bool).astype(np.uint8).tobytes()
                else:
                    body = repr(err).encode()[:512]
                offset += n
                try:
                    p.client.reply(head + body)
                except OSError:
                    pass  # client died mid-batch: its flows replay
            self._slots.release()

    # -- adaptive coalescing ------------------------------------------------

    def _adapt_observe(self, n_requests: int, n_sigs: int) -> None:
        """Retune coalesce_us from the observed batch fill — called under
        self._lock per executed batch when adaptive_coalesce is on. Every
        ADAPT_WINDOW batches: if batches fill to >= max_sigs/2 the deadline
        is pure added latency, shrink it multiplicatively; if they run
        below max_sigs/4 WHILE multiple requests are coalescing per batch
        (more company would actually arrive), grow it toward the ceiling so
        the mesh sees fuller buckets. The band between the thresholds is
        hysteresis — no change. Only the WINDOW LENGTH ever changes: the
        scheduler still anchors the deadline on the oldest pending request
        and still flushes early at max_sigs, so the p99 contract (no
        request waits more than coalesce_us for company) holds at the new
        value from the next batch on."""
        self._win_batches += 1
        self._win_requests += n_requests
        self._win_sigs += n_sigs
        if self._win_batches < ADAPT_WINDOW:
            return
        mean = self._win_sigs / self._win_batches
        coalescing = self._win_requests > self._win_batches
        self._win_batches = self._win_requests = self._win_sigs = 0
        cur = self.coalesce_us
        if mean >= self.max_sigs / 2:
            new = int(cur * ADAPT_SHRINK)
        elif mean < self.max_sigs / 4 and coalescing:
            new = min(ADAPT_CEILING_US,
                      max(ADAPT_SEED_US, int(cur * ADAPT_GROW)))
        else:
            return
        if new != cur:
            self.coalesce_us = new
            self.coalesce_adjustments += 1

    def reset_window(self) -> None:
        """Cross-candidate seam (the autotune controller calls this
        between back-to-back sweep candidates, and the runtime leg's
        revert guard calls it to undo a bad tune): restore the
        CONFIGURED coalesce window and zero the adaptation window, so
        the next candidate's first ADAPT_WINDOW batches are judged on
        its own traffic, not the previous candidate's adapted state.
        Cumulative stats (batches/sigs/coalesce_adjustments) survive —
        this resets the control state, not the audit trail."""
        with self._lock:
            self.coalesce_us = self.coalesce_us_initial
            self._win_batches = self._win_requests = self._win_sigs = 0

    # -- stats --------------------------------------------------------------

    def stats(self) -> dict:
        from ..ops import last_backend_if_loaded

        v = self.verifier
        gate = getattr(v, "device_gate", None)
        dev_b = getattr(v, "device_batches", None)
        host_b = getattr(v, "host_batches", None)
        occupancy = None
        if dev_b is not None and host_b is not None:
            total = dev_b + host_b
            occupancy = round(dev_b / total, 3) if total else 0.0
        with self._lock:
            hist = {str(k): self.batch_sigs_hist[k]
                    for k in sorted(self.batch_sigs_hist)}
            per_dev_hist = {str(k): self.per_device_batch_sigs_hist[k]
                            for k in sorted(self.per_device_batch_sigs_hist)}
            lanes, pad = self.device_lanes, self.pad_lanes
            return {
                "address": self.address,
                "verifier": getattr(v, "name", None),
                "kernel_backend": last_backend_if_loaded(),
                "requests": self.requests,
                "batches": self.batches,
                "sigs": self.sigs,
                "cross_request_batches": self.cross_request_batches,
                "errors": self.errors,
                "batch_sigs_hist": hist,
                "device_batches": dev_b,
                "host_batches": host_b,
                "device_min_sigs": getattr(v, "device_min_sigs", None),
                "device_ready": (gate.is_set() if gate is not None
                                 else None),
                "device_occupancy": occupancy,
                # Mesh ownership: configured width, the PROVEN mesh size
                # (None until the warm thread builds it), why the warm/mesh
                # failed, and the pad/occupancy attribution per dispatched
                # device lane. per_device_occupancy is the fraction of each
                # device's lane share carrying a real signature (identical
                # across devices — the batch axis shards equally).
                "devices": self.devices or None,
                "mesh_devices": self.mesh_devices,
                "warm_error": self.warm_error,
                "packed_batches": self.packed_batches,
                "pack_s_total": round(self.pack_s_total, 6),
                "device_lanes": lanes,
                "pad_lanes": pad,
                "pad_fraction": (round(pad / lanes, 4) if lanes else 0.0),
                "per_device_occupancy": (
                    round((lanes - pad) / lanes, 4) if lanes else 0.0),
                "per_device_batch_sigs_hist": per_dev_hist,
                "coalesce_us": self.coalesce_us,
                "coalesce_us_initial": self.coalesce_us_initial,
                "adaptive_coalesce": self.adaptive_coalesce,
                "coalesce_adjustments": self.coalesce_adjustments,
                "max_sigs": self.max_sigs,
                "depth": self.depth,
                "wait_s_total": round(self.wait_s_total, 6),
                "verify_s_total": round(self.verify_s_total, 6),
                # QoS deadline scheduler (OP_VERIFY_QOS clients).
                "qos_guard_us": self.qos_guard_ns // 1000,
                "qos_early_flushes": self.qos_early_flushes,
                "qos_interactive_requests": self.qos_interactive_requests,
                "qos_bulk_requests": self.qos_bulk_requests,
            }


def main(argv: Sequence[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        description="corda_tpu verification sidecar: one device-owning "
                    "verify server per host")
    parser.add_argument("--socket", required=True,
                        help="unix socket path or host:port to listen on")
    parser.add_argument("--verifier", default="jax",
                        help="server-side provider (cpu | jax | jax-shadow "
                             "| jax-sharded)")
    parser.add_argument("--coalesce-us", type=int, default=2000,
                        help="max time the oldest request waits for "
                             "cross-client company")
    parser.add_argument("--max-sigs", type=int, default=4096,
                        help="flush a coalesced batch early at this many "
                             "signatures (bucket capacity)")
    parser.add_argument("--depth", type=int, default=2,
                        help="batches formed-or-in-flight (double buffer)")
    parser.add_argument("--device-min-sigs", type=int, default=None,
                        help="override the server verifier's size crossover")
    parser.add_argument("--devices", type=int, default=None,
                        help="own a JAX device mesh of this many local "
                             "devices (data-parallel sharded verify); 1 or "
                             "unset keeps the single-device tier")
    parser.add_argument("--adaptive-coalesce", action="store_true",
                        help="retune coalesce_us from the observed batch "
                             "fill (grow toward full buckets, shrink when "
                             "batches fill early)")
    parser.add_argument("--qos-guard-us", type=int, default=2000,
                        help="flush a coalescing batch this long before "
                             "the earliest interactive deadline "
                             "(OP_VERIFY_QOS clients)")
    args = parser.parse_args(argv)

    if args.verifier.startswith("jax"):
        from ..ops import enable_persistent_compile_cache

        enable_persistent_compile_cache()
    server = SidecarServer(
        args.socket, verifier_kind=args.verifier,
        coalesce_us=args.coalesce_us, max_sigs=args.max_sigs,
        depth=args.depth, device_min_sigs=args.device_min_sigs,
        devices=args.devices, adaptive_coalesce=args.adaptive_coalesce,
        qos_guard_us=args.qos_guard_us)
    server.start()
    # The driver's wait_up parses this banner, like the node's.
    print(f"sidecar up at {server.address}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
