"""A serialized payload plus a signature over it.

Capability match for the reference's SignedData (reference:
core/src/main/kotlin/net/corda/core/crypto/SignedData.kt): deserialization is
gated behind signature verification, so callers can only ever observe payloads
whose signature checked out. Used for network-map registrations, and
subclassable for extra payload validation.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Generic, TypeVar

from ..serialization.codec import SerializedBytes
from .keys import DigitalSignature

T = TypeVar("T")


@dataclass(frozen=True)
class SignedData(Generic[T]):
    """Raw serialized data and an (unverified) signature over it."""

    raw: SerializedBytes
    sig: DigitalSignature.WithKey

    def verified(self) -> T:
        """Verify the signature, deserialize, run verify_data, return payload.

        Raises SignatureError if the signature is bad (reference:
        SignedData.kt:22-27).
        """
        self.sig.verify(self.raw.bytes)
        data = self.raw.deserialize()
        self.verify_data(data)
        return data

    def verify_data(self, data: Any) -> None:
        """Extension point for subclasses; default accepts anything."""
