"""X.509 certificates + dev-mode TLS material for the transport.

Capability match for the reference's X509Utilities (reference:
core/src/main/kotlin/net/corda/core/crypto/X509Utilities.kt:44-48,223-309 —
ECDSA secp256r1 self-signed CA + TLS server certs, with dev-mode keystore
auto-generation at config/ConfigUtilities.kt configureWithDevSSLCertificate).
Here the same shape on Python's `cryptography`: a per-node self-signed CA
signs a TLS cert for the node's legal name; PEMs land in the node's base_dir
and feed ssl.SSLContext on both ends of the TCP transport.

Note the deliberate split the reference also has: ledger signatures are
Ed25519 (corda_tpu/crypto/keys.py); ECDSA P-256 appears ONLY here, in the
transport-security layer.
"""

from __future__ import annotations

import datetime
import ipaddress
from pathlib import Path

from cryptography import x509
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric import ec
from cryptography.x509.oid import NameOID


def _name(common_name: str) -> x509.Name:
    return x509.Name([
        x509.NameAttribute(NameOID.COMMON_NAME, common_name),
        x509.NameAttribute(NameOID.ORGANIZATION_NAME, "corda_tpu"),
    ])


_VALIDITY = datetime.timedelta(days=3650)


def _write_atomic(path: Path, data: bytes) -> None:
    tmp = path.with_suffix(path.suffix + ".tmp")
    tmp.write_bytes(data)
    tmp.replace(path)


def ensure_dev_ca(shared_dir: str | Path) -> tuple[Path, Path]:
    """Create (once) the network's shared dev root CA; returns
    (ca_cert_pem, ca_key_pem). All nodes of a dev network chain to this one
    root — the reference ships a well-known dev root the same way."""
    import os
    import time

    shared = Path(shared_dir)
    shared.mkdir(parents=True, exist_ok=True)
    ca_cert_path = shared / "dev-ca.pem"
    ca_key_path = shared / "dev-ca-key.pem"
    if ca_cert_path.exists() and ca_key_path.exists():
        return ca_cert_path, ca_key_path
    # Exactly ONE process may generate the CA: concurrent node starts racing
    # here would mint different roots and brick every TLS handshake. O_EXCL
    # elects the generator; losers wait for the files to appear.
    lock_path = shared / "dev-ca.lock"
    try:
        fd = os.open(lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        os.close(fd)
    except FileExistsError:
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            if ca_cert_path.exists() and ca_key_path.exists():
                return ca_cert_path, ca_key_path
            time.sleep(0.05)
        raise TimeoutError(
            f"dev CA generation by another process never finished "
            f"(stale {lock_path}? delete it to retry)")
    try:
        return _generate_ca(ca_cert_path, ca_key_path)
    except BaseException:
        # A crashed generation must not brick every later node start: drop
        # the lock so the next starter retries.
        try:
            os.unlink(lock_path)
        except OSError:
            pass
        raise


def _generate_ca(ca_cert_path: Path, ca_key_path: Path) -> tuple[Path, Path]:
    now = datetime.datetime.now(datetime.timezone.utc)
    ca_key = ec.generate_private_key(ec.SECP256R1())
    ca_name = _name("corda_tpu Dev Root CA")
    ca_cert = (
        x509.CertificateBuilder()
        .subject_name(ca_name).issuer_name(ca_name)
        .public_key(ca_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now).not_valid_after(now + _VALIDITY)
        .add_extension(x509.BasicConstraints(ca=True, path_length=0),
                       critical=True)
        .sign(ca_key, hashes.SHA256())
    )
    _write_atomic(ca_key_path, ca_key.private_bytes(
        serialization.Encoding.PEM, serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()))
    _write_atomic(ca_cert_path, ca_cert.public_bytes(
        serialization.Encoding.PEM))  # cert last: waiters key off it
    return ca_cert_path, ca_key_path


def generate_dev_tls_material(node_dir: str | Path, shared_dir: str | Path,
                              legal_name: str,
                              host: str = "127.0.0.1") -> dict[str, Path]:
    """Dev-mode TLS for one node: a cert for `legal_name` signed by the
    network's shared dev CA. Returns PEM paths {ca, cert, key}. Idempotent —
    existing files are reused (configureWithDevSSLCertificate capability)."""
    ca_cert_path, ca_key_path = ensure_dev_ca(shared_dir)
    base = Path(node_dir) / "certificates"
    base.mkdir(parents=True, exist_ok=True)
    paths = {"ca": ca_cert_path, "cert": base / "tls-cert.pem",
             "key": base / "tls-key.pem"}
    if paths["cert"].exists() and paths["key"].exists():
        return paths

    ca_cert = x509.load_pem_x509_certificate(ca_cert_path.read_bytes())
    ca_key = serialization.load_pem_private_key(
        ca_key_path.read_bytes(), password=None)
    now = datetime.datetime.now(datetime.timezone.utc)
    tls_key = ec.generate_private_key(ec.SECP256R1())
    san = [x509.IPAddress(ipaddress.ip_address(host))
           if _is_ip(host) else x509.DNSName(host)]
    tls_cert = (
        x509.CertificateBuilder()
        .subject_name(_name(legal_name)).issuer_name(ca_cert.subject)
        .public_key(tls_key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now).not_valid_after(now + _VALIDITY)
        .add_extension(x509.SubjectAlternativeName(san), critical=False)
        .sign(ca_key, hashes.SHA256())
    )
    _write_atomic(paths["cert"],
                  tls_cert.public_bytes(serialization.Encoding.PEM))
    _write_atomic(paths["key"], tls_key.private_bytes(
        serialization.Encoding.PEM, serialization.PrivateFormat.PKCS8,
        serialization.NoEncryption()))
    return paths


def _is_ip(host: str) -> bool:
    try:
        ipaddress.ip_address(host)
        return True
    except ValueError:
        return False
