"""Finance layer: fungible asset contracts and trading flows.

Capability match for the reference's finance module (reference: finance/
src/main/kotlin/net/corda/contracts/...): Amount arithmetic, the Cash
contract, and TwoPartyTradeFlow delivery-versus-payment.
"""

from .amount import Amount
from .cash import Cash, CashExit, CashIssue, CashMove, CashState
from .commodity import Commodity, CommodityContract, CommodityState
from .on_ledger_asset import OnLedgerAsset
from .trade import BuyerFlow, SellerFlow, SellerTradeInfo

__all__ = [
    "Amount", "Cash", "CashState", "CashIssue", "CashMove", "CashExit",
    "Commodity", "CommodityContract", "CommodityState", "OnLedgerAsset",
    "SellerFlow", "BuyerFlow", "SellerTradeInfo",
]
