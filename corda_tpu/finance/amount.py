"""Amount: integer quantities of a token, with mixing protection.

Capability match for the reference's Amount (reference:
core/src/main/kotlin/net/corda/core/contracts/FinanceTypes.kt:32-98):
quantities are non-negative longs counted in the token's smallest unit
(pennies, cents); arithmetic refuses to mix tokens; `token` is any
codec-serializable value — a currency code string, or an Issued wrapping one.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Iterable

from ..serialization.codec import register


@register
@dataclass(frozen=True, order=True)
class Amount:
    quantity: int
    token: Any

    def __post_init__(self):
        if self.quantity < 0:
            raise ValueError(f"Negative amounts are not allowed: {self.quantity}")

    def _check(self, other: "Amount") -> None:
        if not isinstance(other, Amount) or other.token != self.token:
            raise ValueError(f"Token mismatch: {self.token!r} vs "
                             f"{getattr(other, 'token', other)!r}")

    def __add__(self, other: "Amount") -> "Amount":
        self._check(other)
        return Amount(self.quantity + other.quantity, self.token)

    def __sub__(self, other: "Amount") -> "Amount":
        self._check(other)
        return Amount(self.quantity - other.quantity, self.token)

    def __mul__(self, k: int) -> "Amount":
        return Amount(self.quantity * k, self.token)

    def __str__(self) -> str:
        return f"{self.quantity} {self.token}"


def sum_or_zero(amounts: Iterable[Amount], token: Any) -> Amount:
    """Sum amounts of one token; empty -> zero of that token
    (FinanceTypes.kt sumOrZero)."""
    total = Amount(0, token)
    for a in amounts:
        total = total + a
    return total
