"""Cash: the fungible currency-claim contract.

Capability match for the reference's Cash contract (reference:
finance/src/main/kotlin/net/corda/contracts/asset/Cash.kt:42 — there built
from the clause framework; here the same rules as direct requireThat groups,
which is the idiomatic Python shape of GroupClauseVerifier):

  * states are amounts of `Issued(issuer&ref, currency)` owned by a key;
  * verification groups by issued-token and conservation-checks each group;
  * Issue: outputs exceed inputs, no nonsense, issuer signs (anti-replay
    nonce in the command);
  * Move: quantities conserved, every input owner signs;
  * Exit: quantity removed matches the Exit command, issuer + owners sign.

Transaction generation (generate_issue/generate_spend/generate_exit) mirrors
finance/.../asset/Cash.kt:153-221 and OnLedgerAsset, including greedy coin
selection with a change output.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..contracts.dsl import RequirementFailed, require_that, select_command
from ..contracts.structures import (
    Command,
    CommandData,
    Contract,
    FungibleAsset,
    Issued,
    StateAndRef,
    TypeOnlyCommandData,
)
from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.party import Party, PartyAndReference
from ..serialization.codec import register
from ..transactions.builder import TransactionBuilder
from .amount import Amount, sum_or_zero


@register
@dataclass(frozen=True)
class CashIssue(CommandData):
    """Issue cash onto the ledger; nonce prevents replay across transactions
    (reference: Cash.kt Commands.Issue / Structures.kt:375)."""

    nonce: int


@register
@dataclass(frozen=True)
class CashMove(TypeOnlyCommandData):
    """Change ownership (reference: Cash.kt Commands.Move)."""


@register
@dataclass(frozen=True)
class CashExit(CommandData):
    """Remove cash from the ledger, e.g. off-ledger redemption
    (reference: Cash.kt Commands.Exit)."""

    amount: Amount  # of Issued token


@register
@dataclass(frozen=True)
class CashState(FungibleAsset):
    """An amount of issued currency owned by a key (Cash.kt State).

    Also queryable: projects to the `cash_states` table (the reference's
    CashSchemaV1, finance/.../schemas/CashSchemaV1.kt)."""

    amount: Amount = None  # type: ignore[assignment]  # Amount of Issued
    owner: CompositeKey = None  # type: ignore[assignment]

    def to_schema_row(self):
        return ("cash_states", {
            "currency": str(self.amount.token.product),
            "quantity": self.amount.quantity,
            "issuer": self.amount.token.issuer.party.name,
            "owner_key": self.owner.to_base58_string(),
        })

    @property
    def contract(self) -> Contract:
        return CASH_PROGRAM_ID

    @property
    def participants(self) -> list[CompositeKey]:
        return [self.owner]

    @property
    def exit_keys(self) -> list[CompositeKey]:
        return [self.owner, self.amount.token.issuer.party.owning_key]

    @property
    def issuer(self) -> PartyAndReference:
        return self.amount.token.issuer

    def with_new_owner(self, new_owner: CompositeKey):
        return CashMove(), replace(self, owner=new_owner)

    def __str__(self) -> str:
        return f"{self.amount} owned by {self.owner!r}"


class Cash(Contract):
    def verify(self, tx) -> None:
        groups = tx.group_states(CashState, lambda s: s.amount.token)
        if not groups:
            raise RequirementFailed("Cash transaction has no cash states")
        for group in groups:
            token = group.grouping_key
            issuer_key = token.issuer.party.owning_key
            input_sum = sum_or_zero((s.amount for s in group.inputs), token)
            output_sum = sum_or_zero((s.amount for s in group.outputs), token)
            signers = set()
            for cmd in tx.commands:
                signers.update(cmd.signers)

            issue_cmds = [c for c in tx.commands if isinstance(c.value, CashIssue)]
            exit_cmds = [c for c in tx.commands if isinstance(c.value, CashExit)
                         and c.value.amount.token == token]
            if issue_cmds and not group.inputs:
                with require_that() as req:
                    req("output values sum to more than the inputs",
                        output_sum.quantity > input_sum.quantity)
                    req("the issue command has the issuer as a signer",
                        any(issuer_key in c.signers for c in issue_cmds))
            elif exit_cmds:
                exit_amount = exit_cmds[0].value.amount
                with require_that() as req:
                    req("the amounts balance minus the exit amount",
                        input_sum.quantity - output_sum.quantity
                        == exit_amount.quantity)
                    req("the exit command is signed by the issuer",
                        any(issuer_key in c.signers for c in exit_cmds))
                    req("the exit command is signed by every input owner",
                        all(any(s.owner in c.signers for c in exit_cmds)
                            for s in group.inputs))
            else:
                move = select_command(tx.commands, CashMove)
                with require_that() as req:
                    req("there are input states in a move", bool(group.inputs))
                    req("the amounts balance",
                        input_sum.quantity == output_sum.quantity)
                    req("every input owner has signed the move",
                        all(s.owner in move.signers for s in group.inputs))

    @property
    def legal_contract_reference(self) -> SecureHash:
        return SecureHash.sha256(b"corda_tpu.finance.Cash")

    # -- transaction generation (Cash.kt:153-221 capability) ---------------

    @staticmethod
    def generate_issue(
        amount: Amount, issuer: PartyAndReference, owner: CompositeKey,
        notary: Party, nonce: int = 0,
    ) -> TransactionBuilder:
        token = Issued(issuer, amount.token)
        state = CashState(Amount(amount.quantity, token), owner)
        tx = TransactionBuilder(notary=notary)
        tx.add_output_state(state)
        tx.add_command(Command(CashIssue(nonce), (issuer.party.owning_key,)))
        return tx

    @staticmethod
    def generate_spend(
        tx: TransactionBuilder,
        amount: Amount,  # plain-currency amount; any acceptable issuer
        recipient: CompositeKey,
        cash_states: list[StateAndRef],
        change_owner: CompositeKey | None = None,
    ) -> list[CompositeKey]:
        """Greedy coin selection: consume vault cash states until `amount`
        of the currency is covered; pay the recipient, return change. Returns
        the keys that must sign (input owners)."""
        currency = amount.token
        gathered: list[StateAndRef] = []
        covered = 0
        for sar in cash_states:
            state = sar.state.data
            if not isinstance(state, CashState):
                continue
            if state.amount.token.product != currency:
                continue
            gathered.append(sar)
            covered += state.amount.quantity
            if covered >= amount.quantity:
                break
        if covered < amount.quantity:
            raise InsufficientBalanceException(
                Amount(amount.quantity - covered, currency))
        for sar in gathered:
            tx.add_input_state(sar)
        # Pay by issuer bucket, largest first, to minimise outputs.
        by_token: dict = {}
        for sar in gathered:
            st = sar.state.data
            by_token[st.amount.token] = (
                by_token.get(st.amount.token, 0) + st.amount.quantity)
        remaining = amount.quantity
        change_key = change_owner or gathered[0].state.data.owner
        for token, qty in sorted(by_token.items(),
                                 key=lambda kv: -kv[1]):
            pay = min(qty, remaining)
            if pay:
                tx.add_output_state(
                    CashState(Amount(pay, token), recipient))
            if qty > pay:  # change stays with the spender
                tx.add_output_state(
                    CashState(Amount(qty - pay, token), change_key))
            remaining -= pay
        owners = list({sar.state.data.owner for sar in gathered})
        tx.add_command(Command(CashMove(), tuple(owners)))
        return owners

    @staticmethod
    def generate_exit(
        tx: TransactionBuilder, amount: Amount,  # Amount of Issued token
        cash_states: list[StateAndRef],
    ) -> list[CompositeKey]:
        """Consume states of the exact issued token and burn `amount`,
        returning any remainder to its owner."""
        token = amount.token
        gathered = [s for s in cash_states
                    if isinstance(s.state.data, CashState)
                    and s.state.data.amount.token == token]
        covered = sum(s.state.data.amount.quantity for s in gathered)
        if covered < amount.quantity:
            raise InsufficientBalanceException(
                Amount(amount.quantity - covered, token))
        for sar in gathered:
            tx.add_input_state(sar)
        if covered > amount.quantity:
            tx.add_output_state(
                CashState(Amount(covered - amount.quantity, token),
                          gathered[0].state.data.owner))
        owners = list({s.state.data.owner for s in gathered})
        signers = owners + [token.issuer.party.owning_key]
        tx.add_command(Command(CashExit(amount), tuple(signers)))
        return signers


class InsufficientBalanceException(Exception):
    def __init__(self, amount_missing: Amount):
        super().__init__(f"Insufficient balance, missing {amount_missing}")
        self.amount_missing = amount_missing


CASH_PROGRAM_ID = Cash()
