"""Cash: the fungible currency-claim contract.

Capability match for the reference's Cash contract (reference:
finance/src/main/kotlin/net/corda/contracts/asset/Cash.kt:42 — there built
from the clause framework; here the same rules as direct requireThat groups,
which is the idiomatic Python shape of GroupClauseVerifier):

  * states are amounts of `Issued(issuer&ref, currency)` owned by a key;
  * verification groups by issued-token and conservation-checks each group;
  * Issue: outputs exceed inputs, no nonsense, issuer signs (anti-replay
    nonce in the command);
  * Move: quantities conserved, every input owner signs;
  * Exit: quantity removed matches the Exit command, issuer + owners sign.

Transaction generation (generate_issue/generate_spend/generate_exit) mirrors
finance/.../asset/Cash.kt:153-221 and OnLedgerAsset, including greedy coin
selection with a change output.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..contracts.structures import (
    CommandData,
    Contract,
    FungibleAsset,
    StateAndRef,
    TypeOnlyCommandData,
)
from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.party import Party, PartyAndReference
from ..serialization.codec import register
from ..transactions.builder import TransactionBuilder
from .amount import Amount
from .on_ledger_asset import InsufficientBalanceException, OnLedgerAsset

__all__ = ["Cash", "CashState", "CashIssue", "CashMove", "CashExit",
           "InsufficientBalanceException", "CASH_PROGRAM_ID"]


@register
@dataclass(frozen=True)
class CashIssue(CommandData):
    """Issue cash onto the ledger; nonce prevents replay across transactions
    (reference: Cash.kt Commands.Issue / Structures.kt:375)."""

    nonce: int


@register
@dataclass(frozen=True)
class CashMove(TypeOnlyCommandData):
    """Change ownership (reference: Cash.kt Commands.Move)."""


@register
@dataclass(frozen=True)
class CashExit(CommandData):
    """Remove cash from the ledger, e.g. off-ledger redemption
    (reference: Cash.kt Commands.Exit)."""

    amount: Amount  # of Issued token


@register
@dataclass(frozen=True)
class CashState(FungibleAsset):
    """An amount of issued currency owned by a key (Cash.kt State).

    Also queryable: projects to the `cash_states` table (the reference's
    CashSchemaV1, finance/.../schemas/CashSchemaV1.kt)."""

    amount: Amount = None  # type: ignore[assignment]  # Amount of Issued
    owner: CompositeKey = None  # type: ignore[assignment]

    def to_schema_row(self):
        return ("cash_states", {
            "currency": str(self.amount.token.product),
            "quantity": self.amount.quantity,
            "issuer": self.amount.token.issuer.party.name,
            "owner_key": self.owner.to_base58_string(),
        })

    @property
    def contract(self) -> Contract:
        return CASH_PROGRAM_ID

    @property
    def participants(self) -> list[CompositeKey]:
        return [self.owner]

    @property
    def exit_keys(self) -> list[CompositeKey]:
        return [self.owner, self.amount.token.issuer.party.owning_key]

    @property
    def issuer(self) -> PartyAndReference:
        return self.amount.token.issuer

    def with_new_owner(self, new_owner: CompositeKey):
        return CashMove(), replace(self, owner=new_owner)

    def __str__(self) -> str:
        return f"{self.amount} owned by {self.owner!r}"


class Cash(OnLedgerAsset):
    """Cash instantiates the generic OnLedgerAsset scaffolding (reference:
    Cash.kt extends OnLedgerAsset; the shared conservation rules and coin
    selection live in finance/on_ledger_asset.py). The generate_* methods
    keep their historical staticmethod call shape."""

    state_type = CashState
    issue_command_type = CashIssue
    move_command_type = CashMove
    exit_command_type = CashExit
    asset_noun = "cash"

    def make_issue_command(self, nonce: int) -> CashIssue:
        return CashIssue(nonce)

    def make_move_command(self) -> CashMove:
        return CashMove()

    def make_exit_command(self, amount: Amount) -> CashExit:
        return CashExit(amount)

    def derive_state(self, template, amount: Amount,
                     owner: CompositeKey) -> "CashState":
        return CashState(amount, owner)

    @property
    def legal_contract_reference(self) -> SecureHash:
        return SecureHash.sha256(b"corda_tpu.finance.Cash")

    # -- transaction generation (Cash.kt:153-221 call shape) ---------------

    @staticmethod
    def generate_issue(
        amount: Amount, issuer: PartyAndReference, owner: CompositeKey,
        notary: Party, nonce: int = 0,
    ) -> TransactionBuilder:
        return OnLedgerAsset.generate_issue(
            CASH_PROGRAM_ID, amount, issuer, owner, notary, nonce=nonce)

    @staticmethod
    def generate_spend(
        tx: TransactionBuilder,
        amount: Amount,  # plain-currency amount; any acceptable issuer
        recipient: CompositeKey,
        cash_states: list[StateAndRef],
        change_owner: CompositeKey | None = None,
    ) -> list[CompositeKey]:
        return OnLedgerAsset.generate_spend(
            CASH_PROGRAM_ID, tx, amount, recipient, cash_states,
            change_owner=change_owner)

    @staticmethod
    def generate_exit(
        tx: TransactionBuilder, amount: Amount,  # Amount of Issued token
        cash_states: list[StateAndRef],
    ) -> list[CompositeKey]:
        return OnLedgerAsset.generate_exit(
            CASH_PROGRAM_ID, tx, amount, cash_states)


CASH_PROGRAM_ID = Cash()
