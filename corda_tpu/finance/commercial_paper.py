"""CommercialPaper: issue / trade / redeem short-term debt.

Capability match for the reference's CommercialPaper contract (reference:
finance/src/main/kotlin/net/corda/contracts/CommercialPaper.kt, clause-based;
same rules expressed as direct requireThat groups):

  * Issue: the issuer signs, face value is positive, maturity is in the
    future (measured against the transaction's notarised timestamp);
  * Move: the owner signs, the paper's terms are unchanged;
  * Redeem: at/after maturity (notarised timestamp), the paper is consumed
    and the transaction moves cash covering the face value to the owner.

The reference's TwoPartyTradeFlow sells exactly this asset; here too —
CPState is an OwnableState, so finance/trade.py handles it unchanged.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..contracts.dsl import RequirementFailed, require_that, select_command
from ..contracts.structures import (
    Command,
    CommandData,
    Contract,
    Issued,
    OwnableState,
    StateAndRef,
    TypeOnlyCommandData,
)
from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.party import Party, PartyAndReference
from ..serialization.codec import register
from ..transactions.builder import TransactionBuilder
from .amount import Amount
from .cash import Cash, CashState


@register
@dataclass(frozen=True)
class CPIssue(TypeOnlyCommandData):
    pass


@register
@dataclass(frozen=True)
class CPMove(TypeOnlyCommandData):
    pass


@register
@dataclass(frozen=True)
class CPRedeem(TypeOnlyCommandData):
    pass


@register
@dataclass(frozen=True)
class CPState(OwnableState):
    """A claim on the issuer for face_value at maturity (CommercialPaper.kt
    State)."""

    issuance: PartyAndReference = None  # type: ignore[assignment]
    owner: CompositeKey = None  # type: ignore[assignment]
    face_value: Amount = None  # type: ignore[assignment]  # of Issued token
    maturity_micros: int = 0

    @property
    def contract(self) -> Contract:
        return CP_PROGRAM_ID

    @property
    def participants(self) -> list[CompositeKey]:
        return [self.owner]

    def with_new_owner(self, new_owner: CompositeKey):
        return CPMove(), replace(self, owner=new_owner)

    def without_owner(self) -> "CPState":
        return replace(self, owner=None)


class CommercialPaper(Contract):
    def verify(self, tx) -> None:
        groups = tx.group_states(
            CPState, lambda s: (s.issuance, s.face_value, s.maturity_micros))
        if not groups:
            raise RequirementFailed(
                "CommercialPaper transaction has no CP states")
        timestamp = tx.timestamp
        # Both bounds are needed to compare against a maturity instant; the
        # platform allows one-sided windows, so reject them here rather than
        # crash in midpoint.
        midpoint = (timestamp.midpoint
                    if timestamp is not None
                    and timestamp.after is not None
                    and timestamp.before is not None else None)
        # Cash paid per owner is a transaction-wide pool each redeemed paper
        # CLAIMS from — naive per-paper sums would let N identical papers
        # redeem against one payment.
        cash_pool: dict = {}
        for out in tx.outputs:
            if isinstance(out, CashState):
                key = (out.owner, out.amount.token)
                cash_pool[key] = cash_pool.get(key, 0) + out.amount.quantity
        for group in groups:
            issuance, face_value, maturity = group.grouping_key
            # Classify by the GROUP's own shape (per-group clause matching,
            # as the reference's GroupClauseVerifier does) — commands are
            # transaction-wide and may serve other groups.
            if not group.inputs:
                issue = select_command(tx.commands, CPIssue)
                with require_that() as req:
                    req("the issue is signed by the issuer",
                        issuance.party.owning_key in issue.signers)
                    req("the face value is positive",
                        all(o.face_value.quantity > 0 for o in group.outputs))
                    req("the issue has a fully-bounded timestamp",
                        midpoint is not None)
                    req("the maturity date is in the future",
                        midpoint is not None and maturity > midpoint)
            elif not group.outputs:
                redeem = select_command(tx.commands, CPRedeem)
                with require_that() as req:
                    req("the redemption has a fully-bounded timestamp",
                        midpoint is not None)
                    req("the paper must have matured",
                        midpoint is not None and maturity <= midpoint)
                    req("the redemption is signed by the owner",
                        all(s.owner in redeem.signers for s in group.inputs))
                    for paper in group.inputs:
                        key = (paper.owner, paper.face_value.token)
                        req("the received amount equals the face value",
                            cash_pool.get(key, 0)
                            >= paper.face_value.quantity)
                        cash_pool[key] = (cash_pool.get(key, 0)
                                          - paper.face_value.quantity)
            else:
                move = select_command(tx.commands, CPMove)
                with require_that() as req:
                    req("the move is signed by the owner",
                        all(s.owner in move.signers for s in group.inputs))
                    req("the paper's terms are unchanged (only ownership moves)",
                        [s.without_owner() for s in group.inputs]
                        == [o.without_owner() for o in group.outputs])

    @property
    def legal_contract_reference(self) -> SecureHash:
        return SecureHash.sha256(b"corda_tpu.finance.CommercialPaper")

    # -- generation (CommercialPaper.kt:140-178 capability) ----------------

    @staticmethod
    def generate_issue(issuance: PartyAndReference, face_value: Amount,
                       maturity_micros: int, notary: Party) -> TransactionBuilder:
        state = CPState(issuance, issuance.party.owning_key, face_value,
                        maturity_micros)
        tx = TransactionBuilder(notary=notary)
        tx.add_output_state(state)
        tx.add_command(Command(CPIssue(), (issuance.party.owning_key,)))
        return tx

    @staticmethod
    def generate_move(tx: TransactionBuilder, paper: StateAndRef,
                      new_owner: CompositeKey) -> None:
        tx.add_input_state(paper)
        tx.add_output_state(replace(paper.state.data, owner=new_owner))
        tx.add_command(Command(CPMove(), (paper.state.data.owner,)))

    @staticmethod
    def generate_redeem(tx: TransactionBuilder, paper: StateAndRef,
                        cash_states: list[StateAndRef]) -> None:
        """Consume the paper; pay its face value to the owner from the
        redeemer's (issuer's) cash."""
        state = paper.state.data
        Cash.generate_spend(
            tx, Amount(state.face_value.quantity,
                       state.face_value.token.product),
            state.owner, cash_states)
        tx.add_input_state(paper)
        tx.add_command(Command(CPRedeem(), (state.owner,)))


CP_PROGRAM_ID = CommercialPaper()
