"""CommodityContract: a non-cash fungible asset over OnLedgerAsset.

Capability match for the reference's CommodityContract (reference:
finance/src/main/kotlin/net/corda/contracts/asset/CommodityContract.kt:36 —
"intentionally similar to the Cash contract, and the same commands (issue,
move, exit) apply"; Commodity token in core FinanceTypes). The issuer is
the party responsible for delivering the commodity on demand; the deposit
reference is their internal accounting handle (e.g. a warehouse location).
All conservation rules and transaction generation come from the shared
OnLedgerAsset scaffolding — this module only names the types.
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..contracts.structures import (
    CommandData,
    Contract,
    FungibleAsset,
    TypeOnlyCommandData,
)
from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.party import PartyAndReference
from ..serialization.codec import register
from .amount import Amount
from .on_ledger_asset import OnLedgerAsset


@register
@dataclass(frozen=True)
class Commodity:
    """The thing being tracked (reference: core FinanceTypes Commodity):
    a ticker-style code plus display metadata."""

    commodity_code: str
    display_name: str = ""
    default_fraction_digits: int = 0


@register
@dataclass(frozen=True)
class CommodityIssue(CommandData):
    nonce: int


@register
@dataclass(frozen=True)
class CommodityMove(TypeOnlyCommandData):
    pass


@register
@dataclass(frozen=True)
class CommodityExit(CommandData):
    amount: Amount  # of Issued[Commodity]


@register
@dataclass(frozen=True)
class CommodityState(FungibleAsset):
    """An amount of issued commodity owned by a key."""

    amount: Amount = None  # type: ignore[assignment]
    owner: CompositeKey = None  # type: ignore[assignment]

    @property
    def contract(self) -> Contract:
        return COMMODITY_PROGRAM_ID

    @property
    def participants(self) -> list[CompositeKey]:
        return [self.owner]

    @property
    def exit_keys(self) -> list[CompositeKey]:
        return [self.owner, self.amount.token.issuer.party.owning_key]

    @property
    def issuer(self) -> PartyAndReference:
        return self.amount.token.issuer

    def with_new_owner(self, new_owner: CompositeKey):
        return CommodityMove(), replace(self, owner=new_owner)


class CommodityContract(OnLedgerAsset):
    state_type = CommodityState
    issue_command_type = CommodityIssue
    move_command_type = CommodityMove
    exit_command_type = CommodityExit
    asset_noun = "commodity"

    def make_issue_command(self, nonce: int) -> CommodityIssue:
        return CommodityIssue(nonce)

    def make_move_command(self) -> CommodityMove:
        return CommodityMove()

    def make_exit_command(self, amount: Amount) -> CommodityExit:
        return CommodityExit(amount)

    def derive_state(self, template, amount: Amount,
                     owner: CompositeKey) -> CommodityState:
        return CommodityState(amount, owner)

    @property
    def legal_contract_reference(self) -> SecureHash:
        return SecureHash.sha256(b"corda_tpu.finance.Commodity")


COMMODITY_PROGRAM_ID = CommodityContract()
