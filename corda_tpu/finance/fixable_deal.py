"""Fixable deals: scheduled oracle fixings on a bilateral contract.

Capability match for the irs-demo's fixing machinery (reference:
samples/irs-demo/src/main/kotlin/net/corda/irs/contract/IRS.kt — the
FixableDealState shape — and flows/FixingFlow.kt + api/NodeInterestRates.kt:
when a fixing date arrives the scheduler launches a flow that queries the
rate oracle, embeds the Fix as a command, collects the counterparty's and
the oracle's signatures over a commands-only tear-off, and notarises).

This is the full composition the reference's flagship demo exercises:
SchedulableState -> NodeSchedulerService -> oracle query -> Fix command ->
tear-off signature -> bilateral signing -> notarisation -> broadcast.
The cashflow maths of a real swap is out of scope (simm/OpenGamma tier);
the deal simply records its fixed rate.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ..contracts.dsl import require_that, select_command
from ..contracts.structures import (
    Command,
    Contract,
    DealState,
    SchedulableState,
    StateRef,
    UniqueIdentifier,
)
from ..crypto.hashes import SecureHash
from ..crypto.party import Party
from ..flows.api import FlowException, FlowLogic, register_flow
from ..flows.finality import FinalityFlow
from ..flows.oracle import Fix, FixOf, RatesFixQueryFlow, RatesFixSignFlow
from ..serialization.codec import register
from ..transactions.builder import TransactionBuilder
from ..transactions.signed import SignedTransaction


class FixableDealContract(Contract):
    def verify(self, tx) -> None:
        deals_in = [s for s in tx.inputs if isinstance(s, FixableDealState)]
        deals_out = [s for s in tx.outputs if isinstance(s, FixableDealState)]
        all_signers = {k for c in tx.commands for k in c.signers}
        if not deals_in:
            # Deal CREATION: the agreement tx puts unfixed deals on ledger;
            # every participant must be a DECLARED signer (the builder
            # chooses the signer list, so the contract — not must_sign —
            # is what forces both parties' signatures onto the tx).
            with require_that() as req:
                req("a new deal starts unfixed",
                    all(d.fixed_value is None for d in deals_out))
                req("a deal-creation produces at least one deal",
                    bool(deals_out))
                req("every participant signs the deal creation",
                    all(k in all_signers for d in deals_out
                        for k in d.participants))
            return
        fix_cmd = select_command(tx.commands, Fix)
        with require_that() as req:
            req("a fixing consumes exactly one unfixed deal",
                len(deals_in) == 1 and deals_in[0].fixed_value is None)
            req("a fixing produces exactly one fixed deal",
                len(deals_out) == 1 and deals_out[0].fixed_value is not None)
            if deals_in and deals_out:
                before, after = deals_in[0], deals_out[0]
                req("the fixed value equals the oracle's Fix command",
                    after.fixed_value == fix_cmd.value.value
                    and fix_cmd.value.of == before.fix_of)
                req("terms other than the fixed value are unchanged",
                    replace(after, fixed_value=None) == before)
                # Signer rule: both parties AND the oracle must be declared
                # Fix-command signers — listing the oracle makes must_sign
                # demand its transaction signature, so a unilateral
                # fabricated rate cannot commit.
                req("both parties sign the fixing",
                    before.party_a.owning_key in fix_cmd.signers
                    and before.party_b.owning_key in fix_cmd.signers)
                req("the oracle attests the fixing",
                    before.oracle.owning_key in fix_cmd.signers)

    @property
    def legal_contract_reference(self) -> SecureHash:
        return SecureHash.sha256(b"corda_tpu.finance.FixableDeal")


FIXABLE_DEAL_PROGRAM_ID = FixableDealContract()


@register
@dataclass(frozen=True)
class FixableDealState(DealState, SchedulableState):
    """A bilateral deal awaiting a rate fixing at fix_at_micros (IRS.kt's
    FixableDealState shape, one fixing for brevity)."""

    party_a: Party = None  # type: ignore[assignment]  # floating-leg payer:
    # its node runs the scheduled fixing (FixingFlow.kt picks the floater)
    party_b: Party = None  # type: ignore[assignment]
    oracle: Party = None  # type: ignore[assignment]
    fix_of: FixOf = None  # type: ignore[assignment]
    fix_at_micros: int = 0
    notional: int = 0
    fixed_value: int | None = None
    uid: UniqueIdentifier = field(default_factory=UniqueIdentifier)

    @property
    def linear_id(self) -> UniqueIdentifier:
        return self.uid

    @property
    def contract(self) -> Contract:
        return FIXABLE_DEAL_PROGRAM_ID

    @property
    def participants(self):
        return [self.party_a.owning_key, self.party_b.owning_key]

    @property
    def parties(self):
        return [self.party_a, self.party_b]

    def next_scheduled_activity(self, this_state_ref: StateRef, flow_factory):
        from ..node.services.scheduler import ScheduledActivity

        if self.fixed_value is not None:
            return None
        return ScheduledActivity("FixingFlow", (this_state_ref,),
                                 self.fix_at_micros)


@register_flow
class FixingFlow(FlowLogic):
    """Scheduler-launched on party_a's node when the fixing falls due:
    query the oracle, build the fixing transaction, gather the oracle's
    tear-off signature and the counterparty's signature, notarise and
    broadcast (FixingFlow.kt capability)."""

    def __init__(self, state_ref: StateRef):
        self.state_ref = state_ref

    def call(self):
        sar = self._load()
        deal = sar.state.data
        me = self.service_hub.my_identity
        if me != deal.party_a:
            # BOTH participants' schedulers fire (each vault holds the deal);
            # only the floating-leg payer acts — the other side exits quietly
            # rather than erroring a flow per fixing.
            return None
        other = deal.party_b

        fix = yield from self.sub_flow(
            RatesFixQueryFlow(deal.oracle, deal.fix_of))

        tx = TransactionBuilder(notary=sar.state.notary)
        tx.add_input_state(sar)
        tx.add_output_state(replace(deal, fixed_value=fix.value))
        tx.add_command(Command(fix, (me.owning_key, other.owning_key,
                                     deal.oracle.owning_key)))
        tx.sign_with(self.service_hub.legal_identity_key)
        ptx = tx.to_signed_transaction(check_sufficient_signatures=False)

        oracle_sig = yield from self.sub_flow(
            RatesFixSignFlow(deal.oracle, ptx))
        ptx = ptx.with_additional_signature(oracle_sig)

        response = yield self.send_and_receive(other, ptx, object)
        their_sig = response.unwrap(
            lambda s: self.check_counterparty_signature(
                s, ptx.id.bytes, other))
        stx = ptx.with_additional_signature(their_sig)
        final = yield from self.sub_flow(
            FinalityFlow(stx, (me, other)))
        return final

    def _load(self):
        state = self.service_hub.load_state(self.state_ref)
        if state is None:
            raise FlowException(f"unknown state {self.state_ref}")
        from ..contracts.structures import StateAndRef

        return StateAndRef(state, self.state_ref)



@register_flow
class FixingAcceptorFlow(FlowLogic):
    """party_b: validate that the proposed fixing only sets fixed_value to
    the oracle-signed Fix, then co-sign."""

    def __init__(self, other_party: Party):
        self.other_party = other_party

    def call(self):
        response = yield self.receive(self.other_party, SignedTransaction)
        ptx = response.unwrap(self._validate)
        sig = self.service_hub.legal_identity_key.sign(ptx.id.bytes)
        yield self.send(self.other_party, sig)
        return None

    def _validate(self, ptx) -> SignedTransaction:
        if not isinstance(ptx, SignedTransaction):
            raise FlowException("expected a SignedTransaction")
        wtx = ptx.tx
        deals = [o.data for o in wtx.outputs
                 if isinstance(o.data, FixableDealState)]
        if len(deals) != 1 or deals[0].fixed_value is None:
            raise FlowException("proposal does not fix exactly one deal")
        deal = deals[0]
        me = self.service_hub.my_identity
        if me not in deal.parties:
            raise FlowException("we are not a party to this deal")
        # The oracle must already have signed the tx (over its tear-off).
        oracle_keys = deal.oracle.owning_key.keys
        if not any(sig.by in oracle_keys for sig in ptx.sigs):
            raise FlowException("missing the oracle's signature")
        fixes = [c.value for c in wtx.commands if isinstance(c.value, Fix)]
        if len(fixes) != 1 or fixes[0].value != deal.fixed_value:
            raise FlowException("fix command does not match the fixed value")
        if fixes[0].of != deal.fix_of:
            # The oracle signature only proves SOME fix is genuine — it must
            # be the fix THIS deal references, or a cheaper instrument's rate
            # could be substituted.
            raise FlowException("fix is for a different instrument")
        return ptx


def install_fixing_acceptor(smm) -> None:
    smm.register_flow_initiator(
        "FixingFlow", lambda party: FixingAcceptorFlow(party))
