"""Interest-rate swap modelled as a universal-contract product.

Capability match for the reference's IRS modelling (reference:
samples/irs-demo/src/main/kotlin/net/corda/contracts/IRS.kt — the bespoke
~700-line contract — and experimental/src/test/kotlin/net/corda/contracts/
universal/IRS.kt, which re-expresses the same product in ~40 lines of the
universal DSL). This framework takes the universal route as the primary
representation: the full cashflow schedule is a ``RollOut`` whose per-period
template nets the floating leg (LIBOR-fixed via the oracle machinery of
flows/oracle.py) against the fixed leg, so the whole lifecycle — fix the
period's rate, pay the net amount, roll to the next period — is driven by
the one generic ``UniversalContract`` with no product-specific code.

Lifecycle per period (each step is an on-ledger transition):

1. ``UApplyFixes`` substitutes the period's LIBOR fixing (attested by the
   oracle key the product pins) into the reduced-period arrangement.
2. ``UAction "pay floating"`` (or ``"pay fixed"``) nets the legs: the payer
   transfers ``|floating − fixed|`` and the state rolls to the remaining
   schedule via the spliced ``Continuation``.
"""

from __future__ import annotations

from ..contracts.structures import (
    Command,
    StateAndRef,
    StateRef,
    Timestamp,
    now_micros,
)
from ..contracts.universal import (
    Actions,
    All,
    Const,
    Continuation,
    EndDate,
    GTE,
    Interest,
    PosPart,
    RollOut,
    StartDate,
    TimeCondition,
    Transfer,
    UAction,
    UApplyFixes,
    UniversalState,
    actions_of,
    all_of,
    arrange,
    after,
    collect_fixings,
    eval_amount,
    fixing,
    involved_parties,
    reduce_rollout,
    replace_fixings,
    transfer,
    _DAY_MICROS,
)
from ..crypto.composite import CompositeKey
from ..crypto.party import Party
from ..flows.api import FlowException, FlowLogic, register_flow
from ..flows.finality import FinalityFlow
from ..flows.oracle import FixOf, RatesFixQueryFlow, RatesFixSignFlow
from ..transactions.builder import TransactionBuilder
from .types import Tenor


def interest_rate_swap(
    notional: int,                 # fixed-point quanta (universal.SCALE)
    currency: str,
    fixed_rate: int,               # percent, fixed-point (e.g. 0.5% = SCALE//2)
    floating_index: str,           # e.g. "LIBOR"
    index_tenor: str,              # e.g. "3M"
    oracle: Party | CompositeKey,  # who may attest the index fixing
    fixed_leg_payer: Party,
    floating_leg_payer: Party,
    start_day: int,
    end_day: int,
    frequency: Tenor = Tenor("3M"),
    day_count: str = "ACT/365",
) -> RollOut:
    """The reference experimental IRS arrangement (universal/IRS.kt
    contractInitial), with one deliberate hardening: the reference offers
    two separate "pay floating"/"pay fixed" actions, which lets the debtor
    exercise the out-of-the-money action (netting to zero under PosPart) and
    discharge the period without paying. Here each period has a single
    ``settle`` action that carries BOTH clamped directions — whichever party
    exercises it, the in-the-money leg transfers the positive net and the
    mirror leg transfers zero, so the true net always lands on ledger."""
    floating = Interest(Const(notional), day_count,
                        fixing(floating_index, StartDate(), index_tenor,
                               oracle),
                        StartDate(), EndDate())
    fixed = Interest(Const(notional), day_count, Const(fixed_rate),
                     StartDate(), EndDate())
    parties = {fixed_leg_payer, floating_leg_payer}
    template = Actions(frozenset({
        arrange("settle", after(EndDate()), parties,
                all_of(transfer(PosPart(floating - fixed), currency,
                                floating_leg_payer, fixed_leg_payer),
                       transfer(PosPart(fixed - floating), currency,
                                fixed_leg_payer, floating_leg_payer),
                       Continuation())),
    }))
    return RollOut(start_day, end_day, frequency, template)


# ---------------------------------------------------------------------------
# Network flows: the swap's period lifecycle over real sessions
# (reference: samples/irs-demo/.../flows/FixingFlow.kt capability, re-hosted
# on the universal contract so one flow pair serves every RollOut product)
# ---------------------------------------------------------------------------


def _participants(arrangement) -> tuple:
    return tuple(sorted(involved_parties(arrangement),
                        key=lambda k: k.to_base58_string()))


def _load_sar(flow: FlowLogic, ref: StateRef) -> StateAndRef:
    state = flow.service_hub.load_state(ref)
    if state is None:
        raise FlowException(f"unknown state {ref}")
    return StateAndRef(state, ref)


def _period_fix_of(reduced) -> tuple[FixOf, CompositeKey]:
    """The (FixOf, pinned oracle key) of the reduced period's single Fixing.
    Products with several fixings per period would generalise this."""
    found = collect_fixings(reduced)
    if not found:
        raise FlowException("current period has no fixing to apply")
    if len(found) > 1:
        raise FlowException("multiple distinct fixings in one period")
    return next(iter(found.items()))


@register_flow
class IrsFixFlow(FlowLogic):
    """Apply the current period's oracle fixing to a RollOut state: query the
    rate, build the UApplyFixes transition, collect the oracle's tear-off
    signature over the embedded Fix command, notarise, broadcast."""

    def __init__(self, state_ref: StateRef, oracle_party: Party,
                 counterparty: Party):
        self.state_ref = state_ref
        self.oracle_party = oracle_party
        self.counterparty = counterparty

    def call(self):
        sar = _load_sar(self, self.state_ref)
        details = sar.state.data.details
        if not isinstance(details, RollOut):
            raise FlowException("fixing applies to RollOut states")
        reduced = reduce_rollout(details)
        fix_of, oracle_key = _period_fix_of(reduced)
        if oracle_key != self.oracle_party.owning_key:
            raise FlowException(
                "the product pins a different oracle for this source")

        fix = yield from self.sub_flow(
            RatesFixQueryFlow(self.oracle_party, fix_of))
        fixed = replace_fixings(reduced, {fix.of: fix.value})

        me = self.service_hub.my_identity
        tx = TransactionBuilder(notary=sar.state.notary)
        tx.add_input_state(sar)
        tx.add_output_state(UniversalState(_participants(fixed), fixed))
        tx.add_command(UApplyFixes((fix,)), me.owning_key)
        tx.add_command(Command(fix, (self.oracle_party.owning_key,)))
        tx.sign_with(self.service_hub.legal_identity_key)
        ptx = tx.to_signed_transaction(check_sufficient_signatures=False)
        # Fail fast on OUR node before consuming anyone's time: a transition
        # the contract rejects must never reach the oracle or the notary.
        ptx.tx.to_ledger_transaction(self.service_hub).verify()

        oracle_sig = yield from self.sub_flow(
            RatesFixSignFlow(self.oracle_party, ptx))
        stx = ptx.with_additional_signature(oracle_sig)
        return (yield from self.sub_flow(
            FinalityFlow(stx, (me, self.counterparty))))


@register_flow
class IrsSettleFlow(FlowLogic):
    """Exercise the period's ``settle`` action on a fixed state: evaluate the
    netted legs, emit one state per leg plus the rolled remainder, timestamp,
    notarise, broadcast."""

    def __init__(self, state_ref: StateRef, counterparty: Party,
                 action_name: str = "settle"):
        self.state_ref = state_ref
        self.counterparty = counterparty
        self.action_name = action_name

    def call(self):
        sar = _load_sar(self, self.state_ref)
        details = sar.state.data.details
        if isinstance(details, RollOut):
            raise FlowException("apply the period fixing before settling")
        action = actions_of(details).get(self.action_name)
        if action is None:
            raise FlowException(f"no action {self.action_name!r} on state")
        me = self.service_hub.my_identity
        if me not in action.actors:
            raise FlowException(f"{me} may not exercise {self.action_name!r}")

        parts = (set(action.arrangement.arrangements)
                 if isinstance(action.arrangement, All)
                 else {action.arrangement})
        tx = TransactionBuilder(notary=sar.state.notary)
        tx.add_input_state(sar)
        for part in sorted(parts, key=repr):
            if isinstance(part, Transfer):
                amount = eval_amount(None, part.amount)
                settled = Transfer(Const(amount), part.currency,
                                   part.from_party, part.to_party)
                tx.add_output_state(
                    UniversalState(_participants(settled), settled))
            else:
                tx.add_output_state(
                    UniversalState(_participants(part), part))
        # Anchor the timestamp window so the action's time condition holds:
        # an after-style (GTE) gate pins the earliest-possible-time at the
        # boundary, a before-style (LTE) gate caps the latest; a gate that
        # cannot hold yet fails cleanly instead of notarising garbage.
        after, before = None, now_micros() + 30_000_000
        cond = action.condition
        if isinstance(cond, TimeCondition) and isinstance(cond.day, Const):
            boundary = cond.day.value * _DAY_MICROS
            if cond.cmp == GTE:
                if boundary > before:
                    raise FlowException(
                        f"the period ending on day {cond.day.value} has not "
                        "ended yet")
                after = boundary
            else:  # LTE: must demonstrably commit before the deadline
                if boundary < now_micros():
                    raise FlowException(
                        f"the deadline on day {cond.day.value} has passed")
                before = min(before, boundary)
        tx.set_time(Timestamp(after, before))
        tx.add_command(UAction(self.action_name), me.owning_key)
        tx.sign_with(self.service_hub.legal_identity_key)
        stx = tx.to_signed_transaction(check_sufficient_signatures=False)
        # Verify locally BEFORE notarising: a condition shape this flow's
        # window anchoring doesn't cover (composite conditions, computed
        # days) must fail here — not consume the input at the notary with a
        # transaction every counterparty will reject.
        stx.tx.to_ledger_transaction(self.service_hub).verify()
        return (yield from self.sub_flow(
            FinalityFlow(stx, (me, self.counterparty))))
