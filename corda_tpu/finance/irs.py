"""Interest-rate swap modelled as a universal-contract product.

Capability match for the reference's IRS modelling (reference:
samples/irs-demo/src/main/kotlin/net/corda/contracts/IRS.kt — the bespoke
~700-line contract — and experimental/src/test/kotlin/net/corda/contracts/
universal/IRS.kt, which re-expresses the same product in ~40 lines of the
universal DSL). This framework takes the universal route as the primary
representation: the full cashflow schedule is a ``RollOut`` whose per-period
template nets the floating leg (LIBOR-fixed via the oracle machinery of
flows/oracle.py) against the fixed leg, so the whole lifecycle — fix the
period's rate, pay the net amount, roll to the next period — is driven by
the one generic ``UniversalContract`` with no product-specific code.

Lifecycle per period (each step is an on-ledger transition):

1. ``UApplyFixes`` substitutes the period's LIBOR fixing (attested by the
   oracle key the product pins) into the reduced-period arrangement.
2. ``UAction "pay floating"`` (or ``"pay fixed"``) nets the legs: the payer
   transfers ``|floating − fixed|`` and the state rolls to the remaining
   schedule via the spliced ``Continuation``.
"""

from __future__ import annotations

from ..contracts.universal import (
    Actions,
    Const,
    Continuation,
    EndDate,
    Interest,
    PosPart,
    RollOut,
    StartDate,
    all_of,
    arrange,
    after,
    fixing,
    transfer,
)
from ..crypto.composite import CompositeKey
from ..crypto.party import Party
from .types import Tenor


def interest_rate_swap(
    notional: int,                 # fixed-point quanta (universal.SCALE)
    currency: str,
    fixed_rate: int,               # percent, fixed-point (e.g. 0.5% = SCALE//2)
    floating_index: str,           # e.g. "LIBOR"
    index_tenor: str,              # e.g. "3M"
    oracle: Party | CompositeKey,  # who may attest the index fixing
    fixed_leg_payer: Party,
    floating_leg_payer: Party,
    start_day: int,
    end_day: int,
    frequency: Tenor = Tenor("3M"),
    day_count: str = "ACT/365",
) -> RollOut:
    """The reference experimental IRS arrangement (universal/IRS.kt
    contractInitial), with one deliberate hardening: the reference offers
    two separate "pay floating"/"pay fixed" actions, which lets the debtor
    exercise the out-of-the-money action (netting to zero under PosPart) and
    discharge the period without paying. Here each period has a single
    ``settle`` action that carries BOTH clamped directions — whichever party
    exercises it, the in-the-money leg transfers the positive net and the
    mirror leg transfers zero, so the true net always lands on ledger."""
    floating = Interest(Const(notional), day_count,
                        fixing(floating_index, StartDate(), index_tenor,
                               oracle),
                        StartDate(), EndDate())
    fixed = Interest(Const(notional), day_count, Const(fixed_rate),
                     StartDate(), EndDate())
    parties = {fixed_leg_payer, floating_leg_payer}
    template = Actions(frozenset({
        arrange("settle", after(EndDate()), parties,
                all_of(transfer(PosPart(floating - fixed), currency,
                                floating_leg_payer, fixed_leg_payer),
                       transfer(PosPart(fixed - floating), currency,
                                fixed_leg_payer, floating_leg_payer),
                       Continuation())),
    }))
    return RollOut(start_day, end_day, frequency, template)
