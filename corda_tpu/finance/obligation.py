"""Obligation: IOUs that settle against cash and net bilaterally.

Capability match for the reference's Obligation contract (reference:
finance/src/main/kotlin/net/corda/contracts/asset/Obligation.kt — clause
based; the same core rules here as direct groups): an obligation binds an
obligor to deliver an amount of a token to a beneficiary. Supported
lifecycles:

  * Issue: obligor signs new debt into existence;
  * Move: the beneficiary (owner) reassigns who is owed;
  * Settle: cash moves from obligor to beneficiary, extinguishing that much
    obligation (partial settlement leaves a remainder);
  * Net: mutual obligations between the same two parties in the same token
    collapse to a single net obligation (bilateral netting, both sign).
"""

from __future__ import annotations

from dataclasses import dataclass, replace

from ..contracts.dsl import RequirementFailed, require_that, select_command
from ..contracts.structures import (
    Command,
    CommandData,
    Contract,
    Issued,
    OwnableState,
    StateAndRef,
    TypeOnlyCommandData,
)
from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..crypto.party import Party
from ..serialization.codec import register
from ..transactions.builder import TransactionBuilder
from .amount import Amount
from .cash import Cash, CashState


@register
@dataclass(frozen=True)
class ObligationIssue(CommandData):
    nonce: int


@register
@dataclass(frozen=True)
class ObligationMove(TypeOnlyCommandData):
    pass


@register
@dataclass(frozen=True)
class ObligationSettle(CommandData):
    amount: Amount  # of the Issued token being extinguished


@register
@dataclass(frozen=True)
class ObligationNet(TypeOnlyCommandData):
    pass


@register
@dataclass(frozen=True)
class ObligationState(OwnableState):
    """`obligor` owes `amount` (of an Issued token) to `owner`
    (Obligation.kt State: the owner is the beneficiary)."""

    obligor: CompositeKey = None  # type: ignore[assignment]
    amount: Amount = None  # type: ignore[assignment]
    owner: CompositeKey = None  # type: ignore[assignment]

    @property
    def contract(self) -> Contract:
        return OBLIGATION_PROGRAM_ID

    @property
    def participants(self) -> list[CompositeKey]:
        return [self.obligor, self.owner]

    def with_new_owner(self, new_owner: CompositeKey):
        return ObligationMove(), replace(self, owner=new_owner)


class Obligation(Contract):
    def verify(self, tx) -> None:
        groups = tx.group_states(ObligationState, lambda s: s.amount.token)
        if not groups:
            raise RequirementFailed("Obligation transaction has no obligations")
        for group in groups:
            token = group.grouping_key
            in_sum = sum(s.amount.quantity for s in group.inputs)
            out_sum = sum(s.amount.quantity for s in group.outputs)
            if self._is_net_group(tx, group):
                self._verify_net(tx, group)
            elif not group.inputs:
                issue = select_command(tx.commands, ObligationIssue)
                with require_that() as req:
                    req("new debt is positive",
                        all(o.amount.quantity > 0 for o in group.outputs))
                    req("every obligor has signed the issue",
                        all(o.obligor in issue.signers
                            for o in group.outputs))
            elif in_sum > out_sum:
                settle = select_command(tx.commands, ObligationSettle)
                settled = settle.value.amount
                in_pairs = {(s.obligor, s.owner) for s in group.inputs}
                with require_that() as req:
                    req("the settle amount covers the reduction",
                        settled.token == token
                        and in_sum - out_sum == settled.quantity)
                    req("cash moves to each beneficiary for the settled "
                        "amount",
                        self._cash_covers(tx, group, settled.quantity))
                    req("the obligor signed the settlement",
                        all(s.obligor in settle.signers
                            for s in group.inputs))
                    req("the remainder keeps its original obligor and "
                        "beneficiary",  # debt cannot be reassigned here
                        all((o.obligor, o.owner) in in_pairs
                            for o in group.outputs))
            else:
                move = select_command(tx.commands, ObligationMove)

                def terms(states):  # canonical sort key: keys define no order
                    return sorted(
                        ((s.obligor, s.amount.quantity) for s in states),
                        key=lambda t: (t[0].to_base58_string(), t[1]))

                with require_that() as req:
                    req("obligation amounts are conserved in a move",
                        in_sum == out_sum)
                    req("terms other than the beneficiary are unchanged",
                        terms(group.inputs) == terms(group.outputs))
                    req("every current beneficiary has signed the move",
                        all(s.owner in move.signers for s in group.inputs))

    @staticmethod
    def _cash_covers(tx, group, settled_quantity: int) -> bool:
        """Cash outputs to the beneficiaries must cover what was settled,
        in the obligation's underlying product."""
        product = group.grouping_key.product \
            if isinstance(group.grouping_key, Issued) else group.grouping_key
        owed: dict = {}
        for s in group.inputs:
            owed[s.owner] = owed.get(s.owner, 0) + s.amount.quantity
        for o in group.outputs:
            owed[o.owner] = owed.get(o.owner, 0) - o.amount.quantity
        paid: dict = {}
        for out in tx.outputs:
            if isinstance(out, CashState) \
                    and out.amount.token.product == product:
                paid[out.owner] = paid.get(out.owner, 0) \
                    + out.amount.quantity
        covered = 0
        for owner, reduction in owed.items():
            if reduction <= 0:
                continue
            if paid.get(owner, 0) < reduction:
                return False
            covered += reduction
        return covered == settled_quantity

    @staticmethod
    def _is_net_group(tx, group) -> bool:
        """A group is a netting when the GROUP ITSELF holds obligations in
        both directions between one pair — the tx-wide command alone must not
        reroute an unrelated group in the same transaction."""
        if not any(isinstance(c.value, ObligationNet) for c in tx.commands):
            return False
        directed = {(s.obligor, s.owner) for s in group.inputs}
        undirected = {frozenset(p) for p in directed}
        return len(undirected) == 1 and len(directed) == 2

    @staticmethod
    def _verify_net(tx, group) -> None:
        net_cmd = select_command(tx.commands, ObligationNet)
        pairs = {frozenset((s.obligor, s.owner)) for s in group.inputs}
        with require_that() as req:
            req("netting involves exactly one pair of parties",
                len(pairs) == 1)
            gross = {}
            for s in group.inputs:
                gross[(s.obligor, s.owner)] = gross.get(
                    (s.obligor, s.owner), 0) + s.amount.quantity
            directions = list(gross.items())
            req("netting requires obligations in both directions",
                len(directions) == 2)
            (d1, q1), (d2, q2) = directions
            net_quantity = abs(q1 - q2)
            if net_quantity == 0:
                req("zero net debt leaves no outputs", not group.outputs)
            else:
                net_obligor, net_owner = d1 if q1 > q2 else d2
                req("exactly one net obligation remains",
                    len(group.outputs) == 1)
                if group.outputs:
                    out = group.outputs[0]
                    req("the net obligation has the right direction and size",
                        out.obligor == net_obligor
                        and out.owner == net_owner
                        and out.amount.quantity == net_quantity)
            req("both parties signed the netting",
                all(k in net_cmd.signers for pair in pairs for k in pair))

    @property
    def legal_contract_reference(self) -> SecureHash:
        return SecureHash.sha256(b"corda_tpu.finance.Obligation")

    # -- generation --------------------------------------------------------

    @staticmethod
    def generate_issue(obligor: CompositeKey, beneficiary: CompositeKey,
                       amount: Amount, notary: Party,
                       nonce: int = 0) -> TransactionBuilder:
        tx = TransactionBuilder(notary=notary)
        tx.add_output_state(ObligationState(obligor, amount, beneficiary))
        tx.add_command(Command(ObligationIssue(nonce), (obligor,)))
        return tx

    @staticmethod
    def generate_settle(tx: TransactionBuilder, obligations: list[StateAndRef],
                        cash_states: list[StateAndRef],
                        amount: Amount) -> None:
        """Pay `amount` of the obligations' token from the obligor's cash.
        All obligations must share one obligor and one beneficiary — mixed
        inputs would build a transaction the contract rejects."""
        token = obligations[0].state.data.amount.token
        pairs = {(o.state.data.obligor, o.state.data.owner)
                 for o in obligations}
        if len(pairs) != 1:
            raise ValueError(
                "generate_settle needs a single (obligor, beneficiary) pair; "
                "settle mixed obligations in separate transactions")
        total = sum(o.state.data.amount.quantity for o in obligations)
        if amount.quantity > total:
            raise ValueError("settling more than is owed")
        for sar in obligations:
            tx.add_input_state(sar)
        remainder = total - amount.quantity
        state = obligations[0].state.data
        if remainder:
            tx.add_output_state(replace(
                state, amount=Amount(remainder, token)))
        product = token.product if isinstance(token, Issued) else token
        Cash.generate_spend(
            tx, Amount(amount.quantity, product), state.owner, cash_states)
        tx.add_command(Command(
            ObligationSettle(Amount(amount.quantity, token)),
            (state.obligor,)))

    @staticmethod
    def generate_net(tx: TransactionBuilder,
                     obligations: list[StateAndRef]) -> None:
        gross: dict = {}
        token = obligations[0].state.data.amount.token
        for sar in obligations:
            tx.add_input_state(sar)
            s = sar.state.data
            gross[(s.obligor, s.owner)] = gross.get(
                (s.obligor, s.owner), 0) + s.amount.quantity
        (d1, q1), (d2, q2) = list(gross.items())
        net_quantity = abs(q1 - q2)
        if net_quantity:
            obligor, owner = d1 if q1 > q2 else d2
            tx.add_output_state(ObligationState(
                obligor, Amount(net_quantity, token), owner))
        signers = {k for pair in gross for k in pair}
        tx.add_command(Command(ObligationNet(), tuple(signers)))


OBLIGATION_PROGRAM_ID = Obligation()
