"""OnLedgerAsset: the generic issued-fungible-asset contract.

Capability match for the reference's OnLedgerAsset + AbstractConserveAmount
(reference: finance/src/main/kotlin/net/corda/contracts/asset/
OnLedgerAsset.kt:26-60, finance/.../clause/ConserveAmount.kt): one shared
implementation of the issue/move/exit conservation rules and of greedy
coin-selection transaction generation with change, parameterised by the
concrete asset's state/command types and state derivation. Cash and
CommodityContract instantiate it (the reference's CommodityContract.kt:36
is "intentionally similar to Cash" for exactly this reason); Obligation's
bilateral settle/net lifecycle is a different shape and stays its own
contract.
"""

from __future__ import annotations

from ..contracts.dsl import RequirementFailed, require_that, select_command
from ..contracts.structures import (
    Command,
    CommandData,
    Contract,
    Issued,
    StateAndRef,
)
from ..crypto.composite import CompositeKey
from ..crypto.party import PartyAndReference
from ..transactions.builder import TransactionBuilder
from .amount import Amount, sum_or_zero


class InsufficientBalanceException(Exception):
    def __init__(self, amount_missing: Amount):
        super().__init__(f"Insufficient balance, missing {amount_missing}")
        self.amount_missing = amount_missing


class OnLedgerAsset(Contract):
    """Subclasses set the four type attributes and implement the three
    factory hooks + derive_state (OnLedgerAsset.kt's abstract surface)."""

    state_type: type
    issue_command_type: type
    move_command_type: type
    exit_command_type: type
    asset_noun: str = "asset"  # for error text ("cash", "commodity")

    # -- hooks -------------------------------------------------------------

    def make_issue_command(self, nonce: int) -> CommandData:
        raise NotImplementedError

    def make_move_command(self) -> CommandData:
        raise NotImplementedError

    def make_exit_command(self, amount: Amount) -> CommandData:
        raise NotImplementedError

    def derive_state(self, template, amount: Amount, owner: CompositeKey):
        """New state like `template` with amount/owner replaced
        (OnLedgerAsset.deriveState): keeps concrete-state extra fields."""
        raise NotImplementedError

    # -- verification (Cash.kt clause semantics, direct requireThat form) --

    def verify(self, tx) -> None:
        groups = tx.group_states(self.state_type, lambda s: s.amount.token)
        if not groups:
            raise RequirementFailed(
                f"{type(self).__name__} transaction has no "
                f"{self.asset_noun} states")
        for group in groups:
            token = group.grouping_key
            issuer_key = token.issuer.party.owning_key
            input_sum = sum_or_zero((s.amount for s in group.inputs), token)
            output_sum = sum_or_zero((s.amount for s in group.outputs), token)

            issue_cmds = [c for c in tx.commands
                          if isinstance(c.value, self.issue_command_type)]
            exit_cmds = [c for c in tx.commands
                         if isinstance(c.value, self.exit_command_type)
                         and c.value.amount.token == token]
            if issue_cmds and not group.inputs:
                with require_that() as req:
                    req("output values sum to more than the inputs",
                        output_sum.quantity > input_sum.quantity)
                    req("the issue command has the issuer as a signer",
                        any(issuer_key in c.signers for c in issue_cmds))
            elif exit_cmds:
                exit_amount = exit_cmds[0].value.amount
                with require_that() as req:
                    req("the amounts balance minus the exit amount",
                        input_sum.quantity - output_sum.quantity
                        == exit_amount.quantity)
                    req("the exit command is signed by the issuer",
                        any(issuer_key in c.signers for c in exit_cmds))
                    req("the exit command is signed by every input owner",
                        all(any(s.owner in c.signers for c in exit_cmds)
                            for s in group.inputs))
            else:
                move = select_command(tx.commands, self.move_command_type)
                with require_that() as req:
                    req("there are input states in a move", bool(group.inputs))
                    req("the amounts balance",
                        input_sum.quantity == output_sum.quantity)
                    req("every input owner has signed the move",
                        all(s.owner in move.signers for s in group.inputs))

    # -- transaction generation (OnLedgerAsset.kt:40-47 capability) --------

    def generate_issue(self, amount: Amount, issuer: PartyAndReference,
                       owner: CompositeKey, notary, nonce: int = 0,
                       ) -> TransactionBuilder:
        token = Issued(issuer, amount.token)
        state = self.derive_state(None, Amount(amount.quantity, token), owner)
        tx = TransactionBuilder(notary=notary)
        tx.add_output_state(state)
        tx.add_command(Command(self.make_issue_command(nonce),
                               (issuer.party.owning_key,)))
        return tx

    def generate_spend(self, tx: TransactionBuilder, amount: Amount,
                       recipient: CompositeKey,
                       asset_states: list[StateAndRef],
                       change_owner: CompositeKey | None = None,
                       ) -> list[CompositeKey]:
        """Greedy coin selection: consume states until `amount` of the
        product is covered; pay the recipient, return change. Returns the
        keys that must sign (input owners)."""
        product = amount.token
        gathered: list[StateAndRef] = []
        covered = 0
        for sar in asset_states:
            state = sar.state.data
            if not isinstance(state, self.state_type):
                continue
            if state.amount.token.product != product:
                continue
            gathered.append(sar)
            covered += state.amount.quantity
            if covered >= amount.quantity:
                break
        if covered < amount.quantity:
            raise InsufficientBalanceException(
                Amount(amount.quantity - covered, product))
        for sar in gathered:
            tx.add_input_state(sar)
        # Pay by issuer bucket, largest first, to minimise outputs.
        by_token: dict = {}
        for sar in gathered:
            st = sar.state.data
            by_token[st.amount.token] = (
                by_token.get(st.amount.token, 0) + st.amount.quantity)
        remaining = amount.quantity
        template = gathered[0].state.data
        change_key = change_owner or template.owner
        for token, qty in sorted(by_token.items(), key=lambda kv: -kv[1]):
            pay = min(qty, remaining)
            if pay:
                tx.add_output_state(self.derive_state(
                    template, Amount(pay, token), recipient))
            if qty > pay:  # change stays with the spender
                tx.add_output_state(self.derive_state(
                    template, Amount(qty - pay, token), change_key))
            remaining -= pay
        owners = list({sar.state.data.owner for sar in gathered})
        tx.add_command(Command(self.make_move_command(), tuple(owners)))
        return owners

    def generate_exit(self, tx: TransactionBuilder, amount: Amount,
                      asset_states: list[StateAndRef],
                      ) -> list[CompositeKey]:
        """Consume states of the exact issued token and burn `amount`,
        returning any remainder to its owner."""
        token = amount.token
        gathered = [s for s in asset_states
                    if isinstance(s.state.data, self.state_type)
                    and s.state.data.amount.token == token]
        covered = sum(s.state.data.amount.quantity for s in gathered)
        if covered < amount.quantity:
            raise InsufficientBalanceException(
                Amount(amount.quantity - covered, token))
        for sar in gathered:
            tx.add_input_state(sar)
        if covered > amount.quantity:
            template = gathered[0].state.data
            tx.add_output_state(self.derive_state(
                template, Amount(covered - amount.quantity, token),
                template.owner))
        owners = list({s.state.data.owner for s in gathered})
        signers = owners + [token.issuer.party.owning_key]
        tx.add_command(Command(self.make_exit_command(amount),
                               tuple(signers)))
        return signers
