"""TwoPartyTradeFlow: delivery-versus-payment between two nodes.

Capability match for the reference's TwoPartyTradeFlow (reference:
finance/src/main/kotlin/net/corda/flows/TwoPartyTradeFlow.kt:18-45):

  Seller: owns an asset, wants `price` cash.
    1. send the buyer the asset + price + the key to pay;
    2. receive the buyer's partially-signed DvP transaction;
    3. check it (resolve the buyer's cash history, confirm payment + asset
       movement), sign it;
    4. FinalityFlow: notarise and broadcast to both parties.
  Buyer (initiated): receives the offer, resolves the ASSET's history,
    gathers cash from its vault, builds the swap (asset -> buyer,
    cash -> seller), signs, returns it — then learns the outcome through
    the finality broadcast.

As in the reference, both legs of the swap are atomic: one transaction moves
the asset and the cash, so the notary's uniqueness commit is the settlement
point. Signature checks on the received transaction ride the node's
micro-batched verifier.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..contracts.structures import Command, StateAndRef
from ..crypto.composite import CompositeKey
from ..crypto.party import Party
from ..flows.api import FlowException, FlowLogic, register_flow
from ..flows.finality import FinalityFlow
from ..flows.resolve import ResolveTransactionsFlow
from ..serialization.codec import register
from ..transactions.builder import TransactionBuilder
from ..transactions.signed import SignedTransaction
from .amount import Amount
from .cash import Cash, CashState


@register
@dataclass(frozen=True)
class SellerTradeInfo:
    """The seller's opening message (TwoPartyTradeFlow.kt SellerTradeInfo)."""

    asset_for_sale: StateAndRef
    price: Amount  # plain-currency amount
    seller_owner_key: CompositeKey


class UnacceptablePriceException(FlowException):
    def __init__(self, given_price: Amount):
        super().__init__(f"Unacceptable price: {given_price}")
        self.given_price = given_price


class AssetMismatchException(FlowException):
    pass


@register_flow
class SellerFlow(FlowLogic):
    def __init__(self, other_party: Party, asset_to_sell: StateAndRef,
                 price: Amount):
        self.other_party = other_party
        self.asset_to_sell = asset_to_sell
        self.price = price

    def call(self):
        my_key = self.service_hub.my_identity.owning_key
        hello = SellerTradeInfo(self.asset_to_sell, self.price, my_key)
        response = yield self.send_and_receive(
            self.other_party, hello, SignedTransaction)
        ptx = response.unwrap(self._validate_partial)

        # The buyer's cash inputs come from history we don't have: fetch and
        # verify it (this also batch-verifies the buyer's signature).
        yield from self.sub_flow(
            ResolveTransactionsFlow(ptx.tx, self.other_party))

        # Everything checks out — counter-sign and finalise (notarise +
        # broadcast to both parties).
        my_sig = self.service_hub.legal_identity_key.sign(ptx.id.bytes)
        stx = ptx.with_additional_signature(my_sig)
        final = yield from self.sub_flow(FinalityFlow(
            stx, (self.service_hub.my_identity, self.other_party)))
        return final

    def _validate_partial(self, ptx: SignedTransaction) -> SignedTransaction:
        wtx = ptx.tx
        if self.asset_to_sell.ref not in wtx.inputs:
            raise AssetMismatchException(
                "Transaction does not consume the asset being sold")
        my_key = self.service_hub.my_identity.owning_key
        paid = sum(
            out.data.amount.quantity
            for out in wtx.outputs
            if isinstance(out.data, CashState) and out.data.owner == my_key
            and out.data.amount.token.product == self.price.token
        )
        if paid < self.price.quantity:
            raise FlowException(
                f"Transaction pays {paid}, expected {self.price}")
        return ptx


@register_flow
class BuyerFlow(FlowLogic):
    """The responding side; register with
    smm.register_flow_initiator('SellerFlow', lambda party: BuyerFlow(party,
    acceptable_price, notary))."""

    def __init__(self, other_party: Party, acceptable_price: Amount,
                 notary: Party):
        self.other_party = other_party
        self.acceptable_price = acceptable_price
        self.notary = notary

    def call(self):
        offer = yield self.receive(self.other_party, SellerTradeInfo)
        trade = offer.unwrap(self._validate_offer)

        # The asset's provenance is unknown to us: resolve + verify it before
        # paying for it (Buyer.validateTradeRequest capability).
        yield from self.sub_flow(ResolveTransactionsFlow(
            (trade.asset_for_sale.ref.txhash,), self.other_party))

        my_key = self.service_hub.my_identity.owning_key
        tx = TransactionBuilder(notary=self.notary)
        # Soft-locked indexed coin selection: concurrent buyers on this
        # vault reserve disjoint coins instead of racing generate_spend
        # over the same full listing and double-spending at the notary.
        vault_states = self.service_hub.vault_service.select_coins(
            str(trade.price.token), trade.price.quantity,
            holder=self.run_id or b"buyer")
        Cash.generate_spend(
            tx, trade.price, trade.seller_owner_key, vault_states,
            change_owner=my_key)
        tx.add_input_state(trade.asset_for_sale)
        move_cmd, new_asset = trade.asset_for_sale.state.data.with_new_owner(my_key)
        tx.add_output_state(new_asset)
        tx.add_command(Command(move_cmd, (trade.asset_for_sale.state.data.owner,)))

        tx.sign_with(self.service_hub.legal_identity_key)
        ptx = tx.to_signed_transaction(check_sufficient_signatures=False)
        yield self.send(self.other_party, ptx)
        # Settlement arrives via the seller's finality broadcast.
        return ptx.id

    def _validate_offer(self, trade: SellerTradeInfo) -> SellerTradeInfo:
        if not isinstance(trade, SellerTradeInfo):
            raise FlowException("Expected SellerTradeInfo")
        if trade.price.token != self.acceptable_price.token or \
                trade.price.quantity > self.acceptable_price.quantity:
            raise UnacceptablePriceException(trade.price)
        return trade


