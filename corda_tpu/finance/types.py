"""Financial time types: tenors, business calendars, day rolling.

Capability match for the reference's FinanceTypes (reference:
core/src/main/kotlin/net/corda/core/contracts/FinanceTypes.kt — Tenor,
BusinessCalendar with holiday sets, date roll conventions, day-count
helpers; used by the IRS demo's fixing schedule). Dates are integer epoch
DAYS (UTC) so they serialize canonically like every other ledger number.
"""

from __future__ import annotations

import datetime as _dt
import re
from dataclasses import dataclass

from ..serialization.codec import register

_DAY = _dt.timedelta(days=1)
_EPOCH = _dt.date(1970, 1, 1)


def date_to_days(d: _dt.date) -> int:
    return (d - _EPOCH).days


def days_to_date(days: int) -> _dt.date:
    return _EPOCH + days * _DAY


@register
@dataclass(frozen=True, order=True)
class Tenor:
    """A duration token like 1D / 2W / 3M / 10Y (FinanceTypes.kt Tenor)."""

    name: str

    _PATTERN = re.compile(r"^(\d+)([DWMY])$")

    def __post_init__(self):
        if not self._PATTERN.match(self.name):
            raise ValueError(f"invalid tenor {self.name!r}")

    @property
    def amount(self) -> int:
        return int(self._PATTERN.match(self.name).group(1))

    @property
    def unit(self) -> str:
        return self._PATTERN.match(self.name).group(2)

    def days_from(self, start_days: int) -> int:
        """Approximate day count of this tenor from a start date (months/
        years advance calendar-wise, as the reference's TimeUnit maths)."""
        start = days_to_date(start_days)
        n = self.amount
        if self.unit == "D":
            end = start + n * _DAY
        elif self.unit == "W":
            end = start + 7 * n * _DAY
        elif self.unit == "M":
            month = start.month - 1 + n
            year = start.year + month // 12
            month = month % 12 + 1
            day = min(start.day, _days_in_month(year, month))
            end = _dt.date(year, month, day)
        else:  # Y
            end = _dt.date(start.year + n,
                           start.month,
                           min(start.day,
                               _days_in_month(start.year + n, start.month)))
        return date_to_days(end) - start_days

    def __str__(self) -> str:
        return self.name


def _days_in_month(year: int, month: int) -> int:
    nxt = _dt.date(year + month // 12, month % 12 + 1, 1)
    return (nxt - _dt.date(year, month, 1)).days


FOLLOWING = "Following"
MODIFIED_FOLLOWING = "ModifiedFollowing"
PREVIOUS = "Previous"


@register
@dataclass(frozen=True)
class BusinessCalendar:
    """Working-day calendar: weekends plus an explicit holiday set
    (FinanceTypes.kt BusinessCalendar — there loaded from resources; here the
    holiday list is part of the value)."""

    holidays: frozenset[int] = frozenset()  # epoch-day numbers

    def __post_init__(self):
        object.__setattr__(self, "holidays", frozenset(self.holidays))

    def is_working_day(self, day: int) -> bool:
        return days_to_date(day).weekday() < 5 and day not in self.holidays

    def roll(self, day: int, convention: str = FOLLOWING) -> int:
        """Move a non-working day onto a working one (applyRollConvention)."""
        if self.is_working_day(day):
            return day
        if convention == FOLLOWING:
            return self._step(day, +1)
        if convention == PREVIOUS:
            return self._step(day, -1)
        if convention == MODIFIED_FOLLOWING:
            rolled = self._step(day, +1)
            if days_to_date(rolled).month != days_to_date(day).month:
                return self._step(day, -1)
            return rolled
        raise ValueError(f"unknown roll convention {convention!r}")

    def _step(self, day: int, direction: int) -> int:
        while not self.is_working_day(day):
            day += direction
        return day

    def advance(self, start_day: int, tenor: Tenor,
                convention: str = FOLLOWING) -> int:
        """start + tenor, rolled to a working day (moveBusinessDays/
        applyTenor capability)."""
        return self.roll(start_day + tenor.days_from(start_day), convention)

    @staticmethod
    def union(*calendars: "BusinessCalendar") -> "BusinessCalendar":
        out: frozenset[int] = frozenset()
        for c in calendars:
            out = out | c.holidays
        return BusinessCalendar(out)


class Frequency:
    """Payment schedule frequencies (FinanceTypes.kt:242-263 Frequency):
    each is a (name, annual compound count, tenor) triple; ``offset`` steps a
    date forward n periods via the tenor's calendar arithmetic. The seven
    canonical instances below are the registry; constructing ad-hoc
    Frequency values is fine but never aliases ``Frequency.of``."""

    _BY_NAME: dict[str, "Frequency"] = {}

    def __init__(self, name: str, annual_compound_count: int, tenor_name: str):
        self.name = name
        self.annual_compound_count = annual_compound_count
        self.tenor = Tenor(tenor_name)

    def offset(self, day: int, n: int = 1) -> int:
        for _ in range(n):
            day += self.tenor.days_from(day)
        return day

    @staticmethod
    def of(name: str) -> "Frequency":
        try:
            return Frequency._BY_NAME[name]
        except KeyError:
            raise ValueError(f"unknown frequency {name!r}") from None

    def __repr__(self):
        return f"Frequency.{self.name}"


for _freq in (Frequency("Annual", 1, "1Y"), Frequency("SemiAnnual", 2, "6M"),
              Frequency("Quarterly", 4, "3M"), Frequency("Monthly", 12, "1M"),
              Frequency("BiWeekly", 26, "2W"), Frequency("Weekly", 52, "1W"),
              Frequency("Daily", 365, "1D")):
    Frequency._BY_NAME[_freq.name] = _freq
    setattr(Frequency, {"Annual": "ANNUAL", "SemiAnnual": "SEMI_ANNUAL",
                        "Quarterly": "QUARTERLY", "Monthly": "MONTHLY",
                        "BiWeekly": "BI_WEEKLY", "Weekly": "WEEKLY",
                        "Daily": "DAILY"}[_freq.name], _freq)
del _freq
