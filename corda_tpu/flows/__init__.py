"""L2/L3 flow framework: the programming model and the library flows."""

from .api import (  # noqa: F401
    FlowException,
    FlowLogic,
    FlowSessionException,
    ReceiveRequest,
    SendAndReceiveRequest,
    SendRequest,
    UntrustworthyData,
    VerifyTxRequest,
    flow_registry,
    register_flow,
)
from .notary import (  # noqa: F401
    NotaryClientFlow,
    NotaryConflict,
    NotaryError,
    NotaryException,
    NotaryServiceFlow,
    NotarySignaturesMissing,
    NotaryTimestampInvalid,
    NotaryTransactionInvalid,
    ValidatingNotaryFlow,
)
from .fetch import FetchAttachmentsFlow, FetchTransactionsFlow  # noqa: F401
from .resolve import ResolveTransactionsFlow  # noqa: F401
from .finality import BroadcastTransactionFlow, FinalityFlow  # noqa: F401
from .data_vending import install_data_vending  # noqa: F401
from .deal import DealAcceptorFlow, DealInstigatorFlow  # noqa: F401
from .oracle import (  # noqa: F401
    Fix,
    FixOf,
    RateOracle,
    RatesFixQueryFlow,
    RatesFixSignFlow,
)
from .state_replacement import (  # noqa: F401
    NotaryChangeAcceptor,
    NotaryChangeFlow,
    install_notary_change_acceptor,
)
