"""The flow programming model.

Capability match for the reference's FlowLogic (reference:
core/src/main/kotlin/net/corda/core/flows/FlowLogic.kt:28-131) and
FlowStateMachine (core/.../flows/FlowStateMachine.kt), re-designed for
checkpointability without continuation serialization (SURVEY.md §7 stage 3):

The reference suspends Quasar fibers and Kryo-serializes their stacks
(node/.../statemachine/FlowStateMachineImpl.kt:238-261). Here a flow's
`call()` is a Python *generator* that yields effect requests; the state
machine manager (corda_tpu/node/statemachine.py) executes effects and feeds
results back in. Checkpoints record the ordered results of completed
suspensions, so crash-recovery is deterministic replay: re-run the generator,
feed the recorded results, suppress re-execution of effects. The requirement
this places on flow code — determinism between suspension points — is the
standard durable-execution contract.

Usage:

    @register_flow
    class PingFlow(FlowLogic):
        def __init__(self, other: Party):
            self.other = other

        def call(self):
            answer = yield self.send_and_receive(self.other, "ping")
            result = yield from self.sub_flow(OtherFlow(answer.unwrap()))
            return result

All four effect kinds suspend via `yield`:
  self.send(party, payload)                (resolves to None)
  self.receive(party, cls)                 (resolves to UntrustworthyData)
  self.send_and_receive(party, p, cls)     (resolves to UntrustworthyData)
  self.verify_signatures_batched(stx, ...) (resolves when the micro-batched
                                            TPU verify completes — the seam
                                            the reference lacks)
Sub-flows compose with `yield from self.sub_flow(flow)` (reference:
FlowLogic.kt:98-109).
"""

from __future__ import annotations

import inspect
from dataclasses import dataclass, field
from typing import Any, Callable, Generator, TYPE_CHECKING

from ..crypto.composite import CompositeKey
from ..crypto.party import Party
from ..serialization.codec import register as register_codec
from ..utils.excheckpoint import register_flow_exception

if TYPE_CHECKING:
    from ..transactions.signed import SignedTransaction


@register_flow_exception
class FlowException(Exception):
    """Base error for flow failures."""


@register_flow_exception
class FlowSessionException(FlowException):
    """The counterparty session failed: rejected init, unexpected end, or a
    type mismatch on receive."""


@register_codec
@dataclass(frozen=True)
class UntrustworthyData:
    """Wrapper forcing acknowledgement that peer data is unvalidated
    (reference: core/.../utilities/UntrustworthyData.kt). Codec-registered
    because recorded receive results appear in checkpoints."""

    payload: Any

    def unwrap(self, validator: Callable[[Any], Any] | None = None) -> Any:
        if validator is not None:
            return validator(self.payload)
        return self.payload


# ---------------------------------------------------------------------------
# Effect requests (what flows yield) — the analogue of ProtocolIORequest
# (reference: node/.../statemachine/StateMachineManager.kt IO request types)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class SendRequest:
    party: Party
    payload: Any
    scope: str = ""  # which (sub-)flow's session namespace to use
    flow_name: str = ""  # initiating flow for SessionInit


@dataclass(frozen=True)
class ReceiveRequest:
    party: Party
    expected_type: type = object
    scope: str = ""
    flow_name: str = ""


@dataclass(frozen=True)
class SendAndReceiveRequest:
    party: Party
    payload: Any
    expected_type: type = object
    scope: str = ""
    flow_name: str = ""


@dataclass(frozen=True)
class VerifyTxRequest:
    """Check a SignedTransaction's signatures through the node's micro-batched
    verifier; suspends so the manager can aggregate across concurrent flows
    (the notary hot-path seam; reference hot loop at
    core/.../transactions/SignedTransaction.kt:83-87)."""

    stx: "SignedTransaction"
    allowed_to_be_missing: tuple[CompositeKey, ...] = ()


@dataclass(frozen=True)
class VerifySigRequest:
    """Check ONE raw signature through the same micro-batched verifier — the
    single-signature sibling of VerifyTxRequest. Used where a flow validates
    a counterparty's or notary's signature over known content (reference:
    NotaryFlow.kt:58-80 validateSignature); riding the pump means N
    concurrent flows validate their responses in one kernel call instead of
    N sequential host-oracle scalar multiplications."""

    pubkey: bytes
    message: bytes
    sig_bytes: bytes
    description: str = ""


@dataclass(frozen=True)
class ServiceRequest:
    """Suspend on an asynchronous node service (e.g. the Raft commit log):
    `start()` launches the operation and returns a poll callable; the node's
    run loop polls it each round — poll() returns None while pending, a value
    when done, or raises. The single-threaded cooperative design forbids a
    flow from blocking in-place (that would starve the very message pump the
    service needs), so this is the async seam.

    Not serialized: a flow restored from a checkpoint re-reaches the yield
    and re-launches the operation, so start() must be idempotent (as the
    replicated first-committer-wins commit is)."""

    start: Callable[[], Callable[[], Any]]


# ---------------------------------------------------------------------------
# Flow whitelist registry — the analogue of FlowLogicRefFactory
# (reference: core/.../flows/FlowLogicRef.kt:25-172)
# ---------------------------------------------------------------------------


class FlowRegistry:
    """Whitelisted reflective flow construction: checkpoints and RPC refer to
    flows by registered name, never by arbitrary class path."""

    def __init__(self):
        self._by_name: dict[str, type] = {}

    def register(self, cls: type, name: str | None = None) -> type:
        flow_name = name or cls.__qualname__
        existing = self._by_name.get(flow_name)
        if existing is not None and existing is not cls:
            raise ValueError(f"flow name {flow_name!r} already registered")
        self._by_name[flow_name] = cls
        cls.flow_name = flow_name
        return cls

    def create(self, name: str, args: tuple) -> "FlowLogic":
        cls = self._by_name.get(name)
        if cls is None:
            raise FlowException(f"flow {name!r} is not whitelisted")
        return cls(*args)

    def get(self, name: str) -> type | None:
        return self._by_name.get(name)


flow_registry = FlowRegistry()


def register_flow(cls: type | None = None, *, name: str | None = None):
    """Decorator: whitelist a FlowLogic subclass for checkpoint/RPC creation."""
    if cls is None:
        return lambda c: flow_registry.register(c, name)
    return flow_registry.register(cls)


# ---------------------------------------------------------------------------
# FlowLogic
# ---------------------------------------------------------------------------


class FlowLogic:
    """Base class for multi-party protocols (reference: FlowLogic.kt:28).

    Subclasses implement call() as a generator (or a plain method for flows
    with no suspensions). Constructor parameters must be stored as same-named
    attributes — checkpoints capture them via the constructor signature
    (checkpoint_args) and rebuild the flow with cls(*args).
    """

    flow_name: str = ""  # set by @register_flow

    # Injected by the state machine manager before the first step:
    service_hub = None
    state_machine = None  # the FlowStateMachine driving this logic
    progress_tracker = None
    # Session namespace: "" for a top-level flow; sub-flows get a fresh scope
    # unless they share the parent's sessions (reference: subFlow
    # shareParentSessions, DataVendingService.kt NotifyTransactionHandler).
    _session_scope: str = ""

    def call(self):
        raise NotImplementedError

    def _my_flow_name(self) -> str:
        return type(self).flow_name or type(self).__qualname__

    # -- effect constructors (yield these) --------------------------------

    def send(self, party: Party, payload: Any) -> SendRequest:
        return SendRequest(party, payload, self._session_scope, self._my_flow_name())

    def receive(self, party: Party, expected_type: type = object) -> ReceiveRequest:
        return ReceiveRequest(
            party, expected_type, self._session_scope, self._my_flow_name()
        )

    def send_and_receive(
        self, party: Party, payload: Any, expected_type: type = object
    ) -> SendAndReceiveRequest:
        return SendAndReceiveRequest(
            party, payload, expected_type, self._session_scope, self._my_flow_name()
        )

    def verify_signatures_batched(
        self, stx: "SignedTransaction", *allowed_to_be_missing: CompositeKey
    ) -> VerifyTxRequest:
        return VerifyTxRequest(stx, tuple(allowed_to_be_missing))

    def verify_signature_batched(self, sig, content: bytes) -> VerifySigRequest:
        """Validate one signature over `content` via the verify pump
        (`yield` it; raises SignatureError on mismatch when resumed)."""
        return VerifySigRequest(
            bytes(sig.by.encoded), bytes(content), bytes(sig.bytes),
            description=f"by {sig.by}")

    def service_request(self, start: Callable) -> ServiceRequest:
        """Suspend on an async node service; see ServiceRequest."""
        return ServiceRequest(start)

    @staticmethod
    def check_counterparty_signature(sig, content: bytes, counterparty: Party):
        """Shared validator for a returned co-signature: it must be a real
        signature, BY the counterparty's key, over `content` — any other
        valid signature would only fail much later (post-notarisation) as
        missing signatures."""
        from ..crypto.keys import DigitalSignature

        if not isinstance(sig, DigitalSignature.WithKey):
            raise FlowException("expected the counterparty's signature")
        if sig.by not in counterparty.owning_key.keys:
            raise FlowException(
                f"signature is not by the counterparty {counterparty}")
        sig.verify(content)
        return sig

    def sub_flow(
        self, flow: "FlowLogic", share_parent_sessions: bool = False
    ) -> Generator:
        """Run a child flow inline (reference: FlowLogic.kt:98-109). Use
        `yield from`. By default the child opens its own sessions (so e.g. a
        notary's fetch sub-flow talks to the counterparty's data-vending
        responder, not its pending notarisation session); pass
        share_parent_sessions=True to reuse this flow's sessions."""
        flow.service_hub = self.service_hub
        flow.state_machine = self.state_machine
        if share_parent_sessions:
            flow._session_scope = self._session_scope
        else:
            flow._session_scope = self.state_machine.allocate_subflow_scope()
        result = flow.call()
        if inspect.isgenerator(result):
            result = yield from result
        return result

    # -- checkpoint support ------------------------------------------------

    _ckpt_params_cache: dict = {}  # flow class -> constructor param names

    def checkpoint_args(self) -> tuple:
        """The constructor arguments, recovered by signature convention."""
        cls = type(self)
        pnames = FlowLogic._ckpt_params_cache.get(cls)
        if pnames is None:
            sig = inspect.signature(cls.__init__)
            pnames = []
            for pname, param in list(sig.parameters.items())[1:]:  # skip self
                if param.kind in (param.VAR_POSITIONAL, param.VAR_KEYWORD):
                    raise FlowException(
                        f"{cls.__name__}: *args/**kwargs constructors are not "
                        "checkpointable; use explicit parameters"
                    )
                pnames.append(pname)
            pnames = FlowLogic._ckpt_params_cache[cls] = tuple(pnames)
        args = []
        for pname in pnames:
            if not hasattr(self, pname):
                raise FlowException(
                    f"{cls.__name__}: constructor parameter {pname!r} must be "
                    "stored as attribute self.{pname} for checkpointing"
                )
            args.append(getattr(self, pname))
        return tuple(args)

    @property
    def run_id(self):
        return self.state_machine.run_id if self.state_machine else None

    def record_transactions(self, txs) -> None:
        """Store transactions WITH provenance: in addition to
        ServiceHub.record_transactions, each tx is mapped to this flow's
        run id in the provenance log (reference: ServiceHubInternal
        recording into StateMachineRecordedTransactionMappingStorage.kt) —
        flows should record through this, not the hub directly, so the
        explorer can attribute ledger activity to protocol runs."""
        self.service_hub.record_transactions(txs, run_id=self.run_id)
