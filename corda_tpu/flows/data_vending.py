"""Data vending: serving fetch/notify requests from peers.

Capability match for the reference's DataVending.Service (reference:
node/src/main/kotlin/net/corda/node/services/persistence/DataVendingService.kt):
responder flows for FetchTransactionsFlow, FetchAttachmentsFlow and
BroadcastTransactionFlow. Content addressing doubles as access control —
knowing a hash grants the right to fetch it (DataVendingService.kt:24-31).
"""

from __future__ import annotations

from ..crypto.party import Party
from .api import FlowLogic, register_flow
from .fetch import FetchRequest, FetchResponse
from .finality import NotifyTxRequest
from .resolve import ResolveTransactionsFlow


@register_flow
class FetchTransactionsHandler(FlowLogic):
    def __init__(self, other_party: Party):
        self.other_party = other_party

    def call(self):
        req = yield self.receive(self.other_party, FetchRequest)
        request = req.unwrap(lambda r: r if r.hashes else None)
        if request is None:
            return None
        storage = self.service_hub.storage_service.validated_transactions
        items = tuple(storage.get_transaction(h) for h in request.hashes)
        yield self.send(self.other_party, FetchResponse(items))
        return None


@register_flow
class FetchAttachmentsHandler(FlowLogic):
    def __init__(self, other_party: Party):
        self.other_party = other_party

    def call(self):
        req = yield self.receive(self.other_party, FetchRequest)
        request = req.unwrap(lambda r: r if r.hashes else None)
        if request is None:
            return None
        attachments = self.service_hub.storage_service.attachments
        items = []
        for h in request.hashes:
            att = attachments.open_attachment(h)
            items.append(None if att is None else att.open())
        yield self.send(self.other_party, FetchResponse(tuple(items)))
        return None


@register_flow
class NotifyTransactionHandler(FlowLogic):
    """Accept a broadcast transaction: resolve its history, then record
    (DataVendingService.kt:95-103)."""

    def __init__(self, other_party: Party):
        self.other_party = other_party

    def call(self):
        req = yield self.receive(self.other_party, NotifyTxRequest)
        request = req.unwrap()
        yield from self.sub_flow(
            ResolveTransactionsFlow(request.tx, self.other_party),
            share_parent_sessions=True,
        )
        self.record_transactions([request.tx])
        return None


def install_data_vending(smm) -> None:
    """Register the three handlers on a node's state machine manager."""
    smm.register_flow_initiator(
        "FetchTransactionsFlow", lambda party: FetchTransactionsHandler(party)
    )
    smm.register_flow_initiator(
        "FetchAttachmentsFlow", lambda party: FetchAttachmentsHandler(party)
    )
    smm.register_flow_initiator(
        "BroadcastTransactionFlow", lambda party: NotifyTransactionHandler(party)
    )
