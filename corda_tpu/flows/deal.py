"""TwoPartyDealFlow: agree a bilateral deal and put it on-ledger.

Capability match for the reference's TwoPartyDealFlow (reference:
core/src/main/kotlin/net/corda/flows/TwoPartyDealFlow.kt — the generic
instigator/acceptor handshake under the IRS demo's deal creation): the
instigator proposes a DealState, the acceptor validates it (it must be a
party to the deal; an app-supplied validator checks the terms), both sign,
the instigator notarises and broadcasts.

Responder wiring (app side):
    smm.register_flow_initiator("DealInstigatorFlow",
        lambda party: DealAcceptorFlow(party, validator=my_check))
"""

from __future__ import annotations

from dataclasses import dataclass

from ..contracts.structures import Command, DealState
from ..crypto.keys import DigitalSignature
from ..crypto.party import Party
from ..serialization.codec import register
from ..transactions.builder import TransactionBuilder
from ..transactions.signed import SignedTransaction
from .api import FlowException, FlowLogic, register_flow
from .finality import FinalityFlow


@register
@dataclass(frozen=True)
class DealHandshake:
    """The proposal: a partially-signed transaction creating the deal."""

    ptx: SignedTransaction


@register_flow
class DealInstigatorFlow(FlowLogic):
    def __init__(self, other_party: Party, deal: DealState,
                 deal_command, notary: Party):
        self.other_party = other_party
        self.deal = deal
        self.deal_command = deal_command
        self.notary = notary

    def call(self):
        me = self.service_hub.my_identity.owning_key
        them = self.other_party.owning_key
        tx = TransactionBuilder(notary=self.notary)
        tx.add_output_state(self.deal)
        tx.add_command(Command(self.deal_command, (me, them)))
        tx.sign_with(self.service_hub.legal_identity_key)
        ptx = tx.to_signed_transaction(check_sufficient_signatures=False)

        response = yield self.send_and_receive(
            self.other_party, DealHandshake(ptx), DigitalSignature.WithKey)
        sig = response.unwrap(
            lambda s: self.check_counterparty_signature(
                s, ptx.id.bytes, self.other_party))
        stx = ptx.with_additional_signature(sig)
        final = yield from self.sub_flow(FinalityFlow(
            stx, (self.service_hub.my_identity, self.other_party)))
        return final



@register_flow
class DealAcceptorFlow(FlowLogic):
    """Subclass and override validate_terms (and register the subclass) to
    impose app-level acceptance rules — a METHOD, not an injected callable,
    because constructor args are checkpointed and callables cannot round-trip
    through a checkpoint (the reference's Acceptor is likewise abstract)."""

    def __init__(self, other_party: Party):
        self.other_party = other_party

    def call(self):
        response = yield self.receive(self.other_party, DealHandshake)
        handshake = response.unwrap(self._validate)
        sig = self.service_hub.legal_identity_key.sign(handshake.ptx.id.bytes)
        yield self.send(self.other_party, sig)
        return handshake.ptx.id

    def validate_terms(self, deal: DealState) -> None:
        """App hook: raise FlowException to refuse the deal."""

    def _validate(self, handshake) -> "DealHandshake":
        if not isinstance(handshake, DealHandshake):
            raise FlowException("expected a DealHandshake")
        wtx = handshake.ptx.tx
        deals = [o.data for o in wtx.outputs if isinstance(o.data, DealState)]
        if len(deals) != 1:
            raise FlowException("proposal must create exactly one deal")
        deal = deals[0]
        me = self.service_hub.my_identity
        if me not in deal.parties:
            raise FlowException("we are not a party to the proposed deal")
        if wtx.inputs:
            raise FlowException("deal creation must not consume states")
        self.validate_terms(deal)
        return handshake
