"""Fetching content-addressed data from a peer.

Capability match for the reference's FetchDataFlow / FetchTransactionsFlow /
FetchAttachmentsFlow (reference: core/src/main/kotlin/net/corda/flows/
FetchDataFlow.kt:26-99): load what we have locally, request the rest from the
counterparty, and reject responses that don't hash to what was asked for
(malicious-peer defence). The serving side is the data-vending responder
(corda_tpu/flows/data_vending.py).
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto.hashes import SecureHash
from ..serialization.codec import register
from .api import FlowException, FlowLogic, register_flow


class BadAnswer(FlowException):
    pass


class HashNotFound(BadAnswer):
    def __init__(self, requested: SecureHash):
        super().__init__(f"Hash not found: {requested}")
        self.requested = requested


class DownloadedVsRequestedDataMismatch(BadAnswer):
    def __init__(self, requested: SecureHash, got: SecureHash):
        super().__init__(f"Got {got} but requested {requested}")
        self.requested = requested
        self.got = got


@register
@dataclass(frozen=True)
class FetchRequest:
    hashes: tuple[SecureHash, ...]


@register
@dataclass(frozen=True)
class FetchResponse:
    # Entries align with the request; None where the peer lacks the item.
    items: tuple


@dataclass(frozen=True)
class FetchResult:
    from_disk: tuple
    downloaded: tuple

    @property
    def all_items(self) -> tuple:
        return self.from_disk + self.downloaded


class _FetchFlowBase(FlowLogic):
    """Shared request/validate logic; subclasses define load/id_of/store."""

    def __init__(self, requests: tuple, other_side):
        self.requests = tuple(requests)
        self.other_side = other_side

    def _load_local(self, item_hash: SecureHash):
        raise NotImplementedError

    def _id_of(self, item) -> SecureHash:
        raise NotImplementedError

    def _store(self, items) -> None:
        pass

    def call(self):
        from_disk, to_fetch = [], []
        for h in self.requests:
            local = self._load_local(h)
            if local is not None:
                from_disk.append(local)
            else:
                to_fetch.append(h)
        if not to_fetch:
            return FetchResult(tuple(from_disk), ())
        response = yield self.send_and_receive(
            self.other_side, FetchRequest(tuple(to_fetch)), FetchResponse
        )
        items = response.unwrap().items
        if len(items) != len(to_fetch):
            raise BadAnswer("response size does not match request")
        for requested, item in zip(to_fetch, items):
            if item is None:
                raise HashNotFound(requested)
            if self._id_of(item) != requested:
                raise DownloadedVsRequestedDataMismatch(requested, self._id_of(item))
        self._store(items)
        return FetchResult(tuple(from_disk), tuple(items))


@register_flow
class FetchTransactionsFlow(_FetchFlowBase):
    """Fetch SignedTransactions by id (reference: FetchTransactionsFlow)."""

    def _load_local(self, item_hash):
        return self.service_hub.storage_service.validated_transactions.get_transaction(
            item_hash
        )

    def _id_of(self, stx):
        return stx.id


@register_flow
class FetchAttachmentsFlow(_FetchFlowBase):
    """Fetch attachment blobs by id (reference: FetchAttachmentsFlow); writes
    them into local attachment storage."""

    def _load_local(self, item_hash):
        att = self.service_hub.storage_service.attachments.open_attachment(item_hash)
        return None if att is None else att.open()

    def _id_of(self, blob: bytes):
        return SecureHash.sha256(blob)

    def _store(self, items):
        for blob in items:
            self.service_hub.storage_service.attachments.import_attachment(blob)
