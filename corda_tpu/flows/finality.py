"""Finalising and broadcasting transactions.

Capability match for the reference's FinalityFlow (reference:
core/src/main/kotlin/net/corda/flows/FinalityFlow.kt:17-51) and
BroadcastTransactionFlow (core/.../flows/BroadcastTransactionFlow.kt):
notarise if needed, record locally, then notify every participant, whose
data-vending NotifyTransactionHandler resolves and records it.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto.party import Party
from ..serialization.codec import register
from ..transactions.signed import SignedTransaction
from ..utils.progress import ProgressTracker, Step
from .api import FlowLogic, register_flow
from .notary import notarise_with_retry


@register
@dataclass(frozen=True)
class NotifyTxRequest:
    tx: SignedTransaction


@register_flow
class BroadcastTransactionFlow(FlowLogic):
    """Record locally and notify participants (BroadcastTransactionFlow.kt)."""

    def __init__(self, notarised_transaction: SignedTransaction, participants: tuple):
        self.notarised_transaction = notarised_transaction
        self.participants = tuple(participants)

    def call(self):
        self.record_transactions([self.notarised_transaction])
        msg = NotifyTxRequest(self.notarised_transaction)
        me = self.service_hub.my_identity
        for participant in self.participants:
            if participant != me:
                yield self.send(participant, msg)
        return None


@register_flow
class FinalityFlow(FlowLogic):
    """Notarise (if needed) then broadcast (FinalityFlow.kt:27-51).

    Progress mirrors the reference's NOTARISING/BROADCASTING tracker, with
    the notary sub-flow's own steps spliced beneath NOTARISING."""

    def __init__(self, transaction: SignedTransaction, participants: tuple):
        self.transaction = transaction
        self.participants = tuple(participants)
        self.NOTARISING = Step("Requesting signature by notary service")
        self.BROADCASTING = Step("Broadcasting transaction to participants")
        self.progress_tracker = ProgressTracker(
            self.NOTARISING, self.BROADCASTING)

    def call(self):
        stx = self.transaction
        if self._needs_notary_signature(stx):
            self.progress_tracker.current_step = self.NOTARISING
            notary_sig = yield from notarise_with_retry(
                self, stx,
                on_attempt=lambda nf: self.progress_tracker.set_child_tracker(
                    self.NOTARISING, nf.progress_tracker))
            stx = stx.with_additional_signature(notary_sig)
        self.progress_tracker.current_step = self.BROADCASTING
        yield from self.sub_flow(
            BroadcastTransactionFlow(stx, self.participants),
            share_parent_sessions=True,
        )
        return stx  # the framework marks the tracker Done on completion

    @staticmethod
    def _needs_notary_signature(stx: SignedTransaction) -> bool:
        notary = stx.tx.notary
        if notary is None:
            return False
        signers = {sig.by for sig in stx.sigs}
        return not notary.owning_key.is_fulfilled_by(signers)
