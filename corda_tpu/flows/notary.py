"""Notary flows: the uniqueness-consensus round trip — the north-star path.

Capability match for the reference's NotaryFlow (reference:
core/src/main/kotlin/net/corda/flows/NotaryFlow.kt) and ValidatingNotaryFlow
(core/.../flows/ValidatingNotaryFlow.kt). Protocol (NotaryFlow.kt:96-147):

  client: verify own signatures → sendAndReceive(SignRequest) → validate reply
  notary: receive → validate timestamp → beforeCommit (validating variant:
          check signatures + resolve dependencies + run contracts) → commit
          inputs to the uniqueness provider → sign tx id → reply

TPU-first difference: every signature check suspends into the node's
micro-batched verifier (VerifyTxRequest) so concurrent notarisation requests
verify as ONE kernel batch — the reference's sequential hot loop
(SignedTransaction.kt:83-87) becomes the batch axis.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..crypto.composite import CompositeKey
from ..crypto.hashes import SecureHash
from ..obs import trace as _obs
from ..crypto.keys import DigitalSignature, SignatureError
from ..crypto.party import Party
from ..crypto.signed_data import SignedData
from ..serialization.codec import register
from ..transactions.signed import SignaturesMissingException, SignedTransaction
from ..utils.progress import ProgressTracker, Step
from .api import FlowException, FlowLogic, FlowSessionException, register_flow


# ---------------------------------------------------------------------------
# Wire types (reference: NotaryFlow.kt:150-158) and errors (:163-183)
# ---------------------------------------------------------------------------


@register
@dataclass(frozen=True)
class SignRequest:
    tx: SignedTransaction
    caller_identity: Party


@register
@dataclass(frozen=True)
class NotarySuccess:
    sig: DigitalSignature.WithKey


@register
@dataclass(frozen=True)
class NotaryFailure:
    error: "NotaryError"


class NotaryError:
    """Marker base (reference: NotaryError sealed class)."""


@register
@dataclass(frozen=True)
class NotaryConflict(NotaryError):
    """Input(s) already consumed; conflict evidence signed by the notary."""

    tx_id: SecureHash
    signed_conflict: SignedData

    def __str__(self):
        return (
            f"One or more input states for transaction {self.tx_id} have been "
            "used in another transaction"
        )


@register
@dataclass(frozen=True)
class NotaryTimestampInvalid(NotaryError):
    pass


@register
@dataclass(frozen=True)
class NotaryTransactionInvalid(NotaryError):
    pass


@register
@dataclass(frozen=True)
class NotaryUnavailable(NotaryError):
    """The notary could not decide in time (e.g. a Raft leadership episode
    outlasted the commit window). RETRYABLE: unlike the other errors this
    says nothing about the transaction — resubmitting the same tx later is
    safe and expected (commit is idempotent, first-committer-wins).

    leader_hint: the legal name of the cluster member the service believes
    is the current Raft leader (None when unknown) — a retrying client can
    re-send straight to the leader instead of waiting out another redirect
    round trip."""

    reason: str = ""
    leader_hint: str | None = None

    def __str__(self):
        return f"Notary service temporarily unavailable: {self.reason}"


@register
@dataclass(frozen=True)
class OverloadedError(NotaryError):
    """Admission control shed the request at the notarise entry point
    before any verification or consensus work was done. RETRYABLE like
    NotaryUnavailable: says nothing about the transaction — only that the
    service chose to shed THIS lane's load right now. retry_after_ms is
    the server's backoff suggestion (token-bucket refill estimate, capped);
    notarise_with_retry uses it as the floor for its next park."""

    lane: str = ""
    retry_after_ms: float = 0.0

    def __str__(self):
        return (f"Notary admission control shed {self.lane or 'request'} "
                f"load (retry after {self.retry_after_ms:.0f} ms)")


@register
@dataclass(frozen=True)
class WrongShardEpoch(NotaryError):
    """The shard group that received the request does not own (some of) the
    touched states under the shard-map epoch it currently enforces — a
    reshard landed between the client deriving its directory and the
    commit applying. RETRYABLE like NotaryUnavailable, with one extra
    obligation: re-sending to the SAME member can never succeed, so
    notarise_with_retry re-derives the shard directory from the network
    map before its next attempt."""

    reason: str = ""

    def __str__(self):
        return (f"Notary shard map changed underneath the request "
                f"(re-derive the directory): {self.reason}")


@register
@dataclass(frozen=True)
class NotarySignaturesMissing(NotaryError):
    missing: frozenset

    def __str__(self):
        return f"Missing signatures from: {sorted(self.missing, key=repr)}"


from ..utils.excheckpoint import register_flow_exception


@register_flow_exception
class NotaryException(FlowException):
    """Carries the structured NotaryError through checkpoint replay so
    restored flows can branch on error kind exactly as live ones do."""

    def __init__(self, error: NotaryError):
        super().__init__(f"Error response from Notary - {error}")
        self.error = error

    def __checkpoint_payload__(self):
        return self.error

    @classmethod
    def __from_checkpoint__(cls, message, payload):
        return cls(payload)


# ---------------------------------------------------------------------------
# Client (reference: NotaryFlow.kt:24-81)
# ---------------------------------------------------------------------------


@register_flow
class NotaryClientFlow(FlowLogic):
    """Obtain the notary's uniqueness signature over a transaction.

    Progress steps mirror the reference's NotaryFlow tracker
    (NotaryFlow.kt REQUESTING/VALIDATING)."""

    def __init__(self, stx: SignedTransaction, via: Party | None = None):
        self.stx = stx
        # Optional override of WHICH cluster member receives the request
        # (leader redirect): the tx's notary identity still governs state
        # checks, but the wire request goes to `via`. Cluster members are
        # mutually trusted replicas of one service, so a signature by the
        # via-member's service key is accepted.
        self.via = via
        self.VERIFYING = Step("Verifying our signatures")
        self.REQUESTING = Step("Requesting signature by notary service")
        self.VALIDATING = Step("Validating response from notary service")
        self.progress_tracker = ProgressTracker(
            self.VERIFYING, self.REQUESTING, self.VALIDATING)

    def call(self):
        wtx = self.stx.tx
        notary_party = wtx.notary
        if notary_party is None:
            raise FlowException("Transaction does not specify a Notary")
        for ref in wtx.inputs:
            state = self.service_hub.load_state(ref)
            if state is not None and state.notary != notary_party:
                raise FlowException("Input states must have the same Notary")
        # Check our own signature set (batched with everything else pending
        # on this node); the notary's signature is the one allowed missing.
        self.progress_tracker.current_step = self.VERIFYING
        try:
            yield self.verify_signatures_batched(self.stx, notary_party.owning_key)
        except SignatureError as e:
            raise NotaryException(
                NotarySignaturesMissing(frozenset(self.stx.get_missing_signatures()))
            ) from e

        self.progress_tracker.current_step = self.REQUESTING
        target = self.via if self.via is not None else notary_party
        request = SignRequest(self.stx, self.service_hub.my_identity)
        response = yield self.send_and_receive(target, request)
        self.progress_tracker.current_step = self.VALIDATING
        result = response.unwrap()

        if isinstance(result, NotarySuccess):
            sig = result.sig
            if sig.by not in target.owning_key.keys:
                raise FlowException("Invalid signer for the notary result")
            # Validate through the verify pump: N concurrent clients share
            # one kernel call instead of N host-oracle verifications
            # (reference: NotaryFlow.kt:58-80 validateSignature, sequential).
            yield self.verify_signature_batched(sig, self.stx.id.bytes)
            return sig
        if isinstance(result, NotaryFailure):
            if isinstance(result.error, NotaryConflict):
                result.error.signed_conflict.verified()  # authenticates evidence
            raise NotaryException(result.error)
        raise FlowSessionException(
            f"Received invalid result from Notary service {notary_party}"
        )


def _resolve_member(flow: FlowLogic, legal_name: str) -> Party | None:
    """Map a leader_hint legal name to a Party via the network map."""
    try:
        cache = flow.service_hub.network_map_cache
        for info in cache.party_nodes:
            if info.legal_identity.name == legal_name:
                return info.legal_identity
    except AttributeError:
        # leader_hint is an optimisation: a hub without a network-map cache
        # (minimal test fixtures) just means no hint, not a failure. Any
        # other exception propagates — a broken map must surface.
        return None
    return None


def _shard_directory(flow: FlowLogic):
    """Discover the sharded-notary topology from the network map: members
    of shard group g advertise "corda.notary.shard.<g>of<n>[@epoch]", so
    the map every party already syncs doubles as the shard directory.
    Returns (count, {group: [Party, ...]}) or None when unsharded.

    Epoch-aware: mid-reshard the map mixes advertisements from two epochs
    (members re-register as their fences activate). Prefer the highest
    COMPLETE epoch — one with all of its `count` groups present — so the
    client only adopts a new map once it can actually route everywhere;
    when no epoch is complete (a refresh raced the re-registrations), fall
    back to the epoch with the greatest group coverage, ties to the newer.
    A wrong pick is never a correctness problem: the group's fence bounces
    WrongShardEpoch and the retry re-derives."""
    from ..node.services.sharding import parse_shard_service_full

    # epoch -> (count, {group: [Party, ...]})
    epochs: dict[int, tuple[int, dict[int, list[Party]]]] = {}
    try:
        for info in flow.service_hub.network_map_cache.party_nodes:
            for svc in info.advertised_services:
                parsed = parse_shard_service_full(str(svc.type))
                if parsed is not None:
                    g, n, e = parsed
                    count, groups = epochs.setdefault(e, (n, {}))
                    if n > count:
                        epochs[e] = (n, groups)
                    groups.setdefault(g, []).append(info.legal_identity)
    except Exception:
        return None
    best = None
    for e, (count, groups) in epochs.items():
        complete = len(groups) >= count
        key = (1 if complete else 0, len(groups) if not complete else 0, e)
        if best is None or key > best[0]:
            best = (key, count, groups)
    if best is None:
        return None
    _, count, groups = best
    if count <= 1 or not groups:
        return None
    for members in groups.values():
        members.sort(key=lambda p: p.name)
    return count, groups


def _route_group(stx: SignedTransaction, directory) -> int | None:
    """Owning group for routing: the first input's shard (for a
    single-shard tx that IS the owning group — the fast path; for a
    cross-shard tx it picks the coordinator deterministically). None when
    unsharded or the tx has no inputs (an issuance commits anywhere)."""
    if directory is None:
        return None
    inputs = stx.tx.inputs
    if not inputs:
        return None
    from ..node.services.sharding import shard_of

    return shard_of(inputs[0], directory[0])


def _timer_poll(wake_at: float):
    """Non-blocking in-flow backoff: a ServiceRequest poll that stays
    pending until `wake_at` (time.monotonic). Sleeping in place would
    starve the run loop the retry depends on."""
    import time as _time

    return lambda: (True if _time.monotonic() >= wake_at else None)


def notarise_with_retry(flow: FlowLogic, stx: SignedTransaction,
                        retries: int = 2, on_attempt=None,
                        deadline_s: float | None = None,
                        backoff_s: float = 0.1,
                        max_backoff_s: float = 2.0):
    """yield-from helper: notarise `stx` via a fresh NotaryClientFlow per
    attempt, retrying ONLY the RETRYABLE NotaryUnavailable error (a
    consensus window elapsing says nothing about the tx, and commit is
    idempotent first-committer-wins). A fresh sub-flow per attempt matters:
    each one opens its own session, because the service flow ends after
    replying. `on_attempt(notary_flow)` lets callers hook up progress
    trackers.

    The retry budget is bounded two ways: `retries` counts attempts, and
    `deadline_s` (when set) REPLACES the count with a wall-clock budget —
    retry until the deadline, however many attempts that is. Between
    attempts the flow parks on a ServiceRequest timer (exponential backoff
    from `backoff_s` up to `max_backoff_s`) instead of hammering a cluster
    mid-election. When the failure carries a `leader_hint` (the Raft
    provider knows who leads now), the next attempt is sent straight to
    that member via NotaryClientFlow(via=...) instead of re-traversing a
    redirect.

    Leader hints are keyed PER GROUP: with a sharded notary there are N
    independent Raft clusters, and a hint from one shard's deposed leader
    names a member of THAT group only — applying it to a request routed at
    another group would aim the retry at a node that is not even a member
    of the deciding cluster. Sharded topologies are discovered from the
    network map (see _shard_directory) and requests route to the owning
    group of the tx's first input, so single-shard traffic lands on its
    group's coordinator directly (the fast path).

    The load/bench tools (loadgen, loadtest, demo_cordapp) deliberately
    call NotaryClientFlow raw — retries there would mask the availability
    behaviour they exist to measure."""
    import time as _time

    deadline = None if deadline_s is None else _time.monotonic() + deadline_s
    attempt = 0
    backoff = backoff_s

    def derive():
        directory = _shard_directory(flow)
        group = _route_group(stx, directory)
        members = (frozenset(p.name for p in directory[1].get(group, ()))
                   if directory is not None and group is not None else None)
        return directory, group, members

    directory, group, group_members = derive()
    # group id -> preferred member; None key = the unsharded single cluster.
    hints: dict = {}
    while True:
        via: Party | None = hints.get(group)
        if via is None and group is not None:
            members = directory[1].get(group)
            if members:
                via = members[0]
        notary_flow = NotaryClientFlow(stx, via=via)
        if on_attempt is not None:
            on_attempt(notary_flow)
        try:
            return (yield from flow.sub_flow(notary_flow))
        except NotaryException as e:
            # OverloadedError is the admission-control shed: retryable for
            # the same reason NotaryUnavailable is — nothing was decided
            # about the transaction, the service just declined the work.
            # WrongShardEpoch is retryable too (a fence bounce decides
            # nothing), but ONLY after re-deriving the shard directory:
            # the member that bounced will bounce forever.
            if not isinstance(e.error, (NotaryUnavailable, OverloadedError,
                                        WrongShardEpoch)):
                raise
            attempt += 1
            now = _time.monotonic()
            if (deadline is None and attempt > retries) or \
                    (deadline is not None and now >= deadline):
                raise
            shed = isinstance(e.error, OverloadedError)
            epoch_bump = isinstance(e.error, WrongShardEpoch)
            if epoch_bump:
                # The map moved underneath us: rebuild directory, routing
                # group and the hint filter from the refreshed network map,
                # and drop the stale group's preferred-member hint (it
                # belongs to the old topology).
                hints.pop(group, None)
                directory, group, group_members = derive()
            if shed and e.error.retry_after_ms > 0:
                # The server's refill estimate floors the park: retrying
                # sooner would just be shed again at the same bucket.
                backoff = max(backoff, e.error.retry_after_ms / 1e3)
            hint = getattr(e.error, "leader_hint", None)
            if hint:
                resolved = _resolve_member(flow, hint)
                # The hint redirects only the group THIS attempt was
                # routed at; with a shard directory in hand, drop hints
                # naming non-members of that group outright.
                if resolved is not None and (
                        group_members is None
                        or resolved.name in group_members):
                    hints[group] = resolved
            if backoff > 0:
                wake_at = now + min(backoff, max_backoff_s)
                if deadline is not None:
                    wake_at = min(wake_at, deadline)
                pctx = (_obs.get_context()
                        if (shed or epoch_bump) and _obs.ACTIVE is not None
                        else None)
                t_park = _obs.now() if pctx is not None else 0.0
                yield flow.service_request(
                    lambda wake_at=wake_at: _timer_poll(wake_at))
                if pctx is not None and _obs.ACTIVE is not None:
                    # Client-side cost of the shed (admission_wait) or of a
                    # reshard racing this request (epoch_wait): the backoff
                    # park shows up in the stage breakdown either way.
                    if epoch_bump:
                        _obs.record("epoch_wait", t_park, _obs.now(),
                                    trace_id=pctx[0], parent=pctx[1],
                                    attrs={"attempt": attempt})
                    else:
                        _obs.record("admission_wait", t_park, _obs.now(),
                                    trace_id=pctx[0], parent=pctx[1],
                                    attrs={"lane": e.error.lane,
                                           "attempt": attempt})
                backoff = min(backoff * 2, max_backoff_s)


# ---------------------------------------------------------------------------
# Service (reference: NotaryFlow.kt:96-147)
# ---------------------------------------------------------------------------


@register_flow
class NotaryServiceFlow(FlowLogic):
    """The non-validating notary: commits inputs without seeing history.

    `service` is the node's NotaryServiceBase (a checkpoint token) exposing
    timestamp_checker, uniqueness_provider and signing.
    """

    def __init__(self, other_side: Party, service):
        self.other_side = other_side
        self.service = service

    def call(self):
        req = yield self.receive(self.other_side, SignRequest)
        t0 = _obs.now() if _obs.ACTIVE is not None else 0.0
        try:
            request = req.unwrap(self._validate_request)
            self._admit_or_shed()
            stx = request.tx
            req_identity = request.caller_identity
            wtx = stx.tx
            self._validate_timestamp(wtx)
            yield from self.before_commit(stx, req_identity)
            yield from self._commit_input_states(wtx, req_identity)
            sig = self.service.sign(stx.id.bytes)
            result = NotarySuccess(sig)
        except NotaryException as e:
            result = NotaryFailure(e.error)
        except Exception:
            # Malformed request payloads (tx_bits/id mismatch, wrong-shaped
            # message) and unexpected internal errors must produce a
            # diagnosable notary error, not a generic session death
            # (reference gap noted at NotaryFlow.kt:96-113). If the primary
            # session itself is dead, the send below fails and ends the flow.
            # Logged: an internal error (e.g. a failing commit-log write)
            # reported to the client as "invalid" needs an operator trail.
            import logging

            logging.getLogger(__name__).exception(
                "notary service flow error; replying NotaryTransactionInvalid"
            )
            result = NotaryFailure(NotaryTransactionInvalid())
        if _obs.ACTIVE is not None:
            sm = self.state_machine
            if sm.trace_id is not None and not sm.replaying:
                # request received -> reply queued, stitched into the
                # client's trace (the service fsm joined it at SessionInit).
                # Skipped on checkpoint replay: the live run already
                # recorded it.
                _obs.record("notary_process", t0, _obs.now(),
                            trace_id=sm.trace_id, parent=sm.trace_span,
                            attrs={"ok": isinstance(result, NotarySuccess)})
        yield self.send(self.other_side, result)
        return None

    @staticmethod
    def _validate_request(request):
        if not isinstance(request, SignRequest):
            raise ValueError(f"Expected SignRequest, got {type(request).__name__}")
        return request

    def _admit_or_shed(self) -> None:
        """QoS admission control at the notarise entry point: consult the
        node's AdmissionController (attached to the service token when
        [qos] enabled; absent otherwise — zero work on the disabled path)
        BEFORE any verify/consensus work. A shed raises the retryable
        OverloadedError, which rides the ordinary NotaryFailure reply."""
        admission = getattr(self.service, "admission", None)
        if admission is None:
            return
        from ..qos.context import LANE_INTERACTIVE

        sm = self.state_machine
        qctx = getattr(sm, "qos", None)
        # Unlabelled traffic admits through the interactive bucket: legacy
        # clients must never be out-prioritised by labelled bulk load.
        lane = qctx.lane if qctx is not None else LANE_INTERACTIVE
        depth = sm.manager.qos_queue_depth()
        retry_after_s = admission.admit(lane, depth)
        if retry_after_s is not None:
            raise NotaryException(
                OverloadedError(lane, retry_after_s * 1e3))

    def _validate_timestamp(self, wtx) -> None:
        if wtx.timestamp is not None and not self.service.timestamp_checker.is_valid(
            wtx.timestamp
        ):
            raise NotaryException(NotaryTimestampInvalid())

    def before_commit(self, stx: SignedTransaction, req_identity: Party):
        """Non-validating: no history check (NotaryFlow.kt:121-130)."""
        return
        yield  # pragma: no cover — makes this a generator for yield-from

    def _commit_input_states(self, wtx, req_identity: Party):
        """Commit via the uniqueness provider. Async providers (the Raft
        cluster) expose commit_async -> poll; the flow suspends on it so the
        node keeps pumping consensus traffic (blocking in-place would starve
        the very message loop the quorum round needs). Generator either way
        (yield-from'd by call())."""
        from ..node.services.api import (
            UniquenessException,
            UniquenessUnavailableException,
        )
        from ..node.services.raft import (
            CommitQueueFullException,
            WrongShardEpochException,
        )
        from ..serialization.codec import serialize

        provider = self.service.uniqueness_provider
        try:
            if hasattr(provider, "commit_async"):
                yield self.service_request(
                    lambda: provider.commit_async(
                        wtx.inputs, wtx.id, req_identity))
            else:
                provider.commit(wtx.inputs, wtx.id, req_identity)
        except UniquenessException as e:
            conflict_data = serialize(e.error)
            signed = SignedData(conflict_data, self.service.sign(conflict_data.bytes))
            raise NotaryException(NotaryConflict(wtx.id, signed)) from e
        except CommitQueueFullException as e:
            # Must precede the generic unavailability mapping (subclass):
            # a full commit queue is the pipelined apply executor's
            # admission shed — surface it as the SAME retryable overload
            # error the QoS admission plane uses, so notarise_with_retry's
            # shed-backoff handling covers both layers.
            raise NotaryException(OverloadedError(
                "commit", CommitQueueFullException.RETRY_AFTER_MS)) from e
        except WrongShardEpochException as e:
            # Must precede the generic unavailability mapping (it is a
            # subclass): a fence bounce is retryable but the client has to
            # re-derive the shard directory first — a leader hint for the
            # OLD routing would aim the retry at the same fence.
            raise NotaryException(WrongShardEpoch(str(e))) from e
        except UniquenessUnavailableException as e:
            # A consensus window elapsing says NOTHING about the tx: reply
            # with the RETRYABLE unavailability error, never "transaction
            # invalid" (which would mislead a client into abandoning a
            # perfectly good transaction). Attach the provider's current
            # leader hint so the client's retry can go straight there.
            hint = getattr(provider, "leader_hint", None)
            if callable(hint):
                try:
                    hint = hint()
                except Exception:
                    hint = None
            raise NotaryException(NotaryUnavailable(str(e), hint)) from e


@register_flow
class ValidatingNotaryFlow(NotaryServiceFlow):
    """Fully validates the transaction (signatures, dependency resolution,
    contract code) before committing (reference: ValidatingNotaryFlow.kt:23-50).
    The caller reveals its transaction history in exchange for stronger
    notarisation guarantees."""

    def before_commit(self, stx: SignedTransaction, req_identity: Party):
        from ..contracts.verification import TransactionVerificationException
        from .resolve import ResolveTransactionsFlow

        try:
            # THE hot spot: micro-batched across all concurrent requests.
            try:
                yield self.verify_signatures_batched(
                    stx, self.service.notary_identity.owning_key
                )
            except SignaturesMissingException as e:
                # Typed distinction, preserved across checkpoint replay
                # (reference branches on the exception type the same way,
                # ValidatingNotaryFlow.kt:39-45).
                raise NotaryException(
                    NotarySignaturesMissing(frozenset(e.missing))
                ) from e
            wtx = stx.tx
            yield from self.sub_flow(ResolveTransactionsFlow(wtx, self.other_side))
            wtx.to_ledger_transaction(self.service_hub).verify()
        except NotaryException:
            raise
        except (TransactionVerificationException, SignatureError) as e:
            raise NotaryException(NotaryTransactionInvalid()) from e
