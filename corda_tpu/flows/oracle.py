"""Oracle flows: query facts, then get a signature over a Merkle tear-off.

Capability match for the reference's rate-fix oracle machinery (reference:
samples/irs-demo/src/main/kotlin/net/corda/irs/api/NodeInterestRates.kt:37-55
— Oracle.sign(FilteredTransaction) signs a transaction id only after checking
every REVEALED command is a fix it attests to, without seeing anything else —
and samples/irs-demo/.../flows/RatesFixFlow.kt — the client-side
query + build + sign round trip).

Privacy property exercised end-to-end: the oracle receives a
FilteredTransaction (commands only), verifies the partial Merkle proof
against the given id, checks the fix values equal its own data, and signs the
id. The rest of the transaction stays hidden.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..contracts.structures import Command, CommandData
from ..crypto.hashes import SecureHash
from ..crypto.keys import DigitalSignature
from ..crypto.party import Party
from ..serialization.codec import register
from ..serialization.tokens import SerializeAsToken
from ..transactions.filtered import FilteredTransaction, FilterFuns
from .api import FlowException, FlowLogic, register_flow


@register
@dataclass(frozen=True, order=True)
class FixOf:
    """What is being fixed: a named index on a day for a tenor
    (FinanceTypes Fix/FixOf capability)."""

    name: str
    for_day: int  # epoch days
    of_tenor: str


@register
@dataclass(frozen=True)
class Fix(CommandData):
    """An observed fact: the fix and its value, embedded as a command so the
    oracle's signature covers it (NodeInterestRates Fix)."""

    of: FixOf
    value: int  # scaled by 10^4 (basis-point hundredths); ints serialize
    # canonically, unlike floats


@register
@dataclass(frozen=True)
class QueryRequest:
    queries: tuple  # of FixOf


@register
@dataclass(frozen=True)
class QueryResponse:
    fixes: tuple  # of Fix


@register
@dataclass(frozen=True)
class SignRequest:
    ftx: FilteredTransaction
    tx_id: SecureHash


@register
@dataclass(frozen=True)
class SignResponse:
    sig: DigitalSignature.WithKey


@register
@dataclass(frozen=True)
class SignRefused:
    """The oracle declined (bad proof, wrong value, oversharing) — the reason
    travels back so clients can diagnose instead of seeing a dead session."""

    reason: str


class RateOracle(SerializeAsToken):
    """The oracle service: holds the rate table, answers queries, and signs
    tear-offs whose every revealed Fix matches the table
    (NodeInterestRates.Oracle.sign capability). A checkpoint token, so
    handler flows referencing it survive node restarts."""

    def __init__(self, smm, key_pair, rates: dict[FixOf, int]):
        self.key_pair = key_pair
        self.rates = dict(rates)
        smm.register_flow_initiator(
            "RatesFixQueryFlow", lambda party: OracleQueryHandler(party, self))
        smm.register_flow_initiator(
            "RatesFixSignFlow", lambda party: OracleSignHandler(party, self))
        smm.token_context.register(self)

    @property
    def token_name(self) -> str:
        return "rate-oracle"

    def query(self, queries) -> list[Fix]:
        out = []
        for q in queries:
            if q not in self.rates:
                raise FlowException(f"unknown fix {q}")
            out.append(Fix(q, self.rates[q]))
        return out

    def sign(self, ftx: FilteredTransaction, tx_id: SecureHash
             ) -> DigitalSignature.WithKey:
        # 1. The tear-off must genuinely belong to tx_id.
        if not ftx.verify(tx_id):
            raise FlowException("partial Merkle proof failed")
        # 2. Only commands may be revealed to this oracle.
        leaves = ftx.filtered_leaves
        if leaves.inputs or leaves.outputs or leaves.attachments:
            raise FlowException("oracle must only see commands")
        fixes = [c.value for c in leaves.commands if isinstance(c.value, Fix)]
        if not fixes:
            raise FlowException("no Fix commands to attest")
        # 3. Every revealed fix must match our table.
        for fix in fixes:
            if self.rates.get(fix.of) != fix.value:
                raise FlowException(f"incorrect fix {fix}")
        return self.key_pair.sign(tx_id.bytes)


@register_flow
class OracleQueryHandler(FlowLogic):
    def __init__(self, other_party: Party, oracle):
        self.other_party = other_party
        self.oracle = oracle

    def call(self):
        req = yield self.receive(self.other_party, QueryRequest)
        try:
            reply = QueryResponse(
                tuple(self.oracle.query(req.unwrap().queries)))
        except FlowException as e:
            reply = SignRefused(str(e))
        yield self.send(self.other_party, reply)


@register_flow
class OracleSignHandler(FlowLogic):
    def __init__(self, other_party: Party, oracle):
        self.other_party = other_party
        self.oracle = oracle

    def call(self):
        req = yield self.receive(self.other_party, SignRequest)
        request = req.unwrap()
        try:
            sig = self.oracle.sign(request.ftx, request.tx_id)
            reply = SignResponse(sig)
        except FlowException as e:
            reply = SignRefused(str(e))
        yield self.send(self.other_party, reply)


@register_flow
class RatesFixQueryFlow(FlowLogic):
    """Client: ask the oracle for a fix (RatesFixFlow query leg)."""

    def __init__(self, oracle_party: Party, fix_of: FixOf):
        self.oracle_party = oracle_party
        self.fix_of = fix_of

    def call(self):
        response = yield self.send_and_receive(
            self.oracle_party, QueryRequest((self.fix_of,)), object)
        reply = response.unwrap()
        if isinstance(reply, SignRefused):
            raise FlowException(f"oracle refused query: {reply.reason}")
        if not isinstance(reply, QueryResponse):
            raise FlowException("unexpected oracle reply")
        fixes = reply.fixes
        if len(fixes) != 1 or fixes[0].of != self.fix_of:
            raise FlowException("oracle returned the wrong fix")
        return fixes[0]


@register_flow
class RatesFixSignFlow(FlowLogic):
    """Client: send ONLY the Fix commands (tear-off) and collect the
    oracle's signature over the whole transaction id."""

    def __init__(self, oracle_party: Party, stx):
        self.oracle_party = oracle_party
        self.stx = stx

    def call(self):
        wtx = self.stx.tx
        funs = FilterFuns(filter_commands=lambda c: isinstance(c.value, Fix))
        ftx = FilteredTransaction.build_merkle_transaction(wtx, funs)
        response = yield self.send_and_receive(
            self.oracle_party, SignRequest(ftx, wtx.id), object)
        reply = response.unwrap()
        if isinstance(reply, SignRefused):
            raise FlowException(f"oracle refused to sign: {reply.reason}")
        if not isinstance(reply, SignResponse):
            raise FlowException("unexpected oracle reply")
        sig = reply.sig
        sig.verify(wtx.id.bytes)
        return sig
