"""Recursive dependency resolution and verification.

Capability match for the reference's ResolveTransactionsFlow (reference:
core/src/main/kotlin/net/corda/flows/ResolveTransactionsFlow.kt:31-197):
breadth-first download of the transaction dependency graph from the
counterparty (DoS-bounded at 5000), topological sort, then verify and record
each dependency deepest-first. Signature checks ride the node's micro-batched
verifier.
"""

from __future__ import annotations

from ..crypto.hashes import SecureHash
from ..crypto.party import Party
from ..transactions.signed import SignedTransaction
from ..transactions.wire import WireTransaction
from .api import FlowException, FlowLogic, register_flow
from .fetch import FetchAttachmentsFlow, FetchTransactionsFlow


class ExcessivelyLargeTransactionGraph(FlowException):
    pass


def topological_sort(transactions: list[SignedTransaction]) -> list[SignedTransaction]:
    """Order so dependencies come before dependents
    (ResolveTransactionsFlow.kt:37-68)."""
    forward: dict[SecureHash, list[SignedTransaction]] = {}
    for stx in transactions:
        for inp in stx.tx.inputs:
            forward.setdefault(inp.txhash, []).append(stx)
    visited: set[SecureHash] = set()
    result: list[SignedTransaction] = []

    def visit(stx: SignedTransaction) -> None:
        if stx.id in visited:
            return
        visited.add(stx.id)
        for dependent in forward.get(stx.id, ()):
            visit(dependent)
        result.append(stx)

    for stx in transactions:
        visit(stx)
    result.reverse()
    if len(result) != len(transactions):
        raise FlowException("cycle in transaction graph?")
    return result


@register_flow
class ResolveTransactionsFlow(FlowLogic):
    """Verify a transaction by resolving and verifying its full history."""

    transaction_count_limit = 5000  # DoS bound (ResolveTransactionsFlow.kt:78-80)

    def __init__(self, tx, other_side: Party):
        # tx: WireTransaction (check deps only), SignedTransaction (also
        # verify the tx itself against its history), or a tuple of
        # SecureHash tx ids to fetch+verify directly (the reference's
        # Set<SecureHash> constructor, ResolveTransactionsFlow.kt:88-92).
        self.tx = tx
        self.other_side = other_side

    def call(self):
        if isinstance(self.tx, (tuple, frozenset, set)):
            downloads = yield from self._download_dependencies(set(self.tx))
            results = yield from self._verify_and_record(downloads)
            return results
        stx = self.tx if isinstance(self.tx, SignedTransaction) else None
        wtx = stx.tx if stx is not None else self.tx
        assert isinstance(wtx, WireTransaction)
        dep_hashes = {ref.txhash for ref in wtx.inputs}

        downloads = yield from self._download_dependencies(dep_hashes)
        results = yield from self._verify_and_record(downloads)

        yield from self._fetch_missing_attachments([wtx])
        if stx is not None:
            yield self.verify_signatures_batched(stx)
        ltx = wtx.to_ledger_transaction(self.service_hub)
        ltx.verify()
        results.append(ltx)
        return results

    def _verify_and_record(self, downloads):
        """Verify + record downloaded dependencies, deepest-first. Batched
        signature math + completeness; NO allowances: committed history must
        carry every required signature INCLUDING the notary's (the reference
        verifies dependencies strictly, ResolveTransactionsFlow.kt:105-111)."""
        results = []
        for dep_stx in topological_sort(downloads):
            yield self.verify_signatures_batched(dep_stx)
            ltx = dep_stx.tx.to_ledger_transaction(self.service_hub)
            ltx.verify()
            self.record_transactions([dep_stx])
            results.append(ltx)
        return results

    def _download_dependencies(self, deps_to_check: set[SecureHash]):
        """BFS with dedupe and the transaction-count DoS limit
        (ResolveTransactionsFlow.kt:131-182)."""
        next_requests = list(dict.fromkeys(deps_to_check))
        result_q: dict[SecureHash, SignedTransaction] = {}
        limit_counter = 0
        while next_requests:
            not_fetched = tuple(h for h in next_requests if h not in result_q)
            next_requests = []
            if not not_fetched:
                break
            fetched = yield from self.sub_flow(
                FetchTransactionsFlow(not_fetched, self.other_side)
            )
            # from_disk items are already verified and recorded locally;
            # only fresh downloads enter the verify queue.
            downloads = list(fetched.downloaded)
            # Batch the id recompute of the whole download wave (device
            # kernel above the crossover size) instead of hashing each
            # transaction's components on first .tx touch below.
            SignedTransaction.prime_ids(downloads)
            yield from self._fetch_missing_attachments([s.tx for s in downloads])
            for dep in downloads:
                result_q.setdefault(dep.id, dep)
            next_requests = list(
                dict.fromkeys(
                    inp.txhash for dep in downloads for inp in dep.tx.inputs
                )
            )
            limit_counter += len(next_requests)
            if limit_counter > self.transaction_count_limit:
                raise ExcessivelyLargeTransactionGraph()
        return list(result_q.values())

    def _fetch_missing_attachments(self, wtxs):
        missing = tuple(
            att
            for wtx in wtxs
            for att in wtx.attachments
            if self.service_hub.storage_service.attachments.open_attachment(att) is None
        )
        if missing:
            yield from self.sub_flow(FetchAttachmentsFlow(missing, self.other_side))
