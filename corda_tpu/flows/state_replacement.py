"""State replacement + notary change: move a state to new terms by unanimous
consent of its participants.

Capability match for the reference's AbstractStateReplacementFlow and
NotaryChangeFlow (reference: core/src/main/kotlin/net/corda/flows/
AbstractStateReplacementFlow.kt, NotaryChangeFlow.kt): the instigator builds
a replacement transaction, gathers a signature from every other participant
(each acceptor independently validates the proposal before signing), then
notarises and broadcasts. NotaryChange is the concrete instance: the
replacement moves the state to a different notary and the platform's
NotaryChangeTransactionType rules (TransactionTypes.kt:123-160 equivalent at
corda_tpu/transactions/types.py) enforce that NOTHING but the notary changed.
"""

from __future__ import annotations

from dataclasses import dataclass

from ..contracts.structures import StateAndRef, StateRef
from ..crypto.keys import DigitalSignature
from ..crypto.party import Party
from ..serialization.codec import register
from ..transactions.builder import NotaryChangeBuilder
from ..transactions.signed import SignedTransaction
from .api import FlowException, FlowLogic, register_flow
from .finality import FinalityFlow
from .notary import notarise_with_retry


class StateReplacementException(FlowException):
    pass


@register
@dataclass(frozen=True)
class ReplacementProposal:
    """What the instigator sends each participant: the state being replaced,
    the modification (here: the new notary), and the proposed transaction."""

    state_ref: StateRef
    new_notary: Party
    stx: SignedTransaction


@register_flow
class NotaryChangeFlow(FlowLogic):
    """Instigator (NotaryChangeFlow.kt capability): propose, collect
    signatures from all other participants, notarise with the ORIGINAL
    notary, broadcast. Returns the replacement StateAndRef."""

    def __init__(self, state: StateAndRef, new_notary: Party):
        self.state = state
        self.new_notary = new_notary

    def call(self):
        old_notary = self.state.state.notary
        if old_notary == self.new_notary:
            raise StateReplacementException(
                "The new notary is the same as the current one")
        # The OLD notary notarises the change (it controls the consumed
        # input); only the OUTPUT state moves to the new notary.
        tx = NotaryChangeBuilder(old_notary)
        tx.add_input_state(self.state)
        tx.add_output_state(self.state.state.with_notary(self.new_notary),
                            notary=self.new_notary)
        tx.sign_with(self.service_hub.legal_identity_key)
        stx = tx.to_signed_transaction(check_sufficient_signatures=False)

        my_key = self.service_hub.my_identity.owning_key
        proposal = ReplacementProposal(self.state.ref, self.new_notary, stx)
        parties = []
        for participant in self.state.state.data.participants:
            if participant == my_key:
                continue
            party = self.service_hub.identity_service.party_from_key(participant)
            if party is None:
                raise StateReplacementException(
                    f"no identity known for participant {participant!r}")
            parties.append(party)
        for party in parties:
            response = yield self.send_and_receive(
                party, proposal, DigitalSignature.WithKey)
            sig = response.unwrap(lambda s: self._check_sig(s, stx))
            stx = stx.with_additional_signature(sig)

        # Notarise with the OLD notary (it controls the consumed state) and
        # broadcast to everyone involved.
        notary_sig = yield from notarise_with_retry(self, stx)
        final = stx.with_additional_signature(notary_sig)
        yield from self.sub_flow(FinalityFlow(
            final, tuple(parties) + (self.service_hub.my_identity,)))
        return final.tx.out_ref(0)

    @staticmethod
    def _check_sig(sig, stx):
        if not isinstance(sig, DigitalSignature.WithKey):
            raise StateReplacementException("expected a signature")
        sig.verify(stx.id.bytes)
        return sig


@register_flow
class NotaryChangeAcceptor(FlowLogic):
    """Acceptor: validate that the proposal changes ONLY the notary of a
    state we co-own, then sign (AbstractStateReplacementFlow.Acceptor)."""

    def __init__(self, other_party: Party):
        self.other_party = other_party

    def call(self):
        response = yield self.receive(self.other_party, ReplacementProposal)
        proposal = response.unwrap(self._validate)
        sig = self.service_hub.legal_identity_key.sign(proposal.stx.id.bytes)
        yield self.send(self.other_party, sig)
        return None

    def _validate(self, proposal) -> "ReplacementProposal":
        if not isinstance(proposal, ReplacementProposal):
            raise StateReplacementException("expected a ReplacementProposal")
        wtx = proposal.stx.tx
        from ..transactions.types import NotaryChangeTransactionType

        if not isinstance(wtx.type, NotaryChangeTransactionType):
            raise StateReplacementException(
                "proposal is not a notary-change transaction")
        if list(wtx.inputs) != [proposal.state_ref]:
            raise StateReplacementException(
                "proposal consumes unexpected states")
        if any(out.notary != proposal.new_notary for out in wtx.outputs):
            raise StateReplacementException(
                "output notary does not match the proposal")
        my_key = self.service_hub.my_identity.owning_key
        if not any(my_key in out.data.participants for out in wtx.outputs):
            raise StateReplacementException(
                "we are not a participant in the replacement state")
        return proposal


def install_notary_change_acceptor(smm) -> None:
    """Auto-accept notary changes we participate in (the reference registers
    the acceptor's flow factory the same way)."""
    smm.register_flow_initiator(
        "NotaryChangeFlow", lambda party: NotaryChangeAcceptor(party))
