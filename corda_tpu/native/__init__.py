"""Native runtime components (C over the CPython API), with fallbacks.

The reference's runtime tiers (Kryo serialization, Artemis framing) are
JVM bytecode the JIT compiles to machine code; the corda_tpu equivalents
are Python, which pays an interpreter tax on the hottest per-message loops.
This package holds C implementations of those loops — currently the codec
decode/encode core (`_ccodec.c`, wired in by corda_tpu/serialization/
codec.py) — compiled on first use with the system compiler and loaded with
a graceful pure-Python fallback, so the framework never REQUIRES a
toolchain but uses one when present. Set CORDA_TPU_NO_NATIVE=1 to force
the Python paths (conformance tests run both).
"""

from __future__ import annotations

import hashlib
import importlib
import os
import pathlib
import subprocess
import sysconfig
import tempfile


def _src_digest(src: pathlib.Path) -> str:
    return hashlib.sha256(src.read_bytes()).hexdigest()


def _load_native(name: str, link_args: tuple = ()):
    """Import a native module from this package, building it on first use.
    Returns the module or None (no compiler, build failure, or
    CORDA_TPU_NO_NATIVE).

    Freshness: these cores sit on consensus-critical paths, so a stale
    build must never shadow updated C source — the built .so carries a
    sidecar recording the source sha256, and any mismatch triggers a
    rebuild. Builds go to a temp name and os.replace (atomic) so
    concurrent builders (the driver spawns many node processes at once)
    never load a half-written .so.
    """
    if os.environ.get("CORDA_TPU_NO_NATIVE"):
        return None
    src = pathlib.Path(__file__).with_name(name + ".c")
    if not src.exists():
        return None
    ext_suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    target = src.with_name(name + ext_suffix)
    stamp = src.with_name(name + ".src-sha256")
    digest = _src_digest(src)
    if target.exists():
        try:
            fresh = stamp.read_text().strip() == digest
        except OSError:
            fresh = False
        if fresh:
            try:
                return importlib.import_module(f"{__name__}.{name}")
            except ImportError:
                pass  # broken artifact: rebuild below
    include = sysconfig.get_paths()["include"]
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(src.parent))
        os.close(fd)
        subprocess.run(
            ["gcc", "-O2", "-fPIC", "-shared", f"-I{include}",
             str(src), "-o", tmp, *link_args],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, target)
        stamp.write_text(digest + "\n")
    except Exception:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return None
    # The failed import above may have cached the directory listing from
    # before the .so existed; without invalidation the fresh build can be
    # invisible to this process (1s-mtime filesystems).
    importlib.invalidate_caches()
    try:
        return importlib.import_module(f"{__name__}.{name}")
    except ImportError:
        return None


def load_ccodec():
    """The native codec decode/encode core (`_ccodec.c`, wired in by
    corda_tpu/serialization/codec.py)."""
    return _load_native("_ccodec")


def _libcrypto_path():
    """The installed libcrypto shared object, headers or not: this image
    ships libcrypto.so.3 without the dev symlink, so the builder links
    the versioned file directly."""
    import glob

    for pattern in ("/usr/lib/*/libcrypto.so", "/lib/*/libcrypto.so",
                    "/usr/lib/*/libcrypto.so.*", "/lib/*/libcrypto.so.*",
                    "/usr/lib/libcrypto.so*", "/usr/local/lib/libcrypto.so*"):
        hits = sorted(glob.glob(pattern))
        if hits:
            return hits[0]
    return None


def load_ccommit():
    """The batched CRC32C integrity-frame core (`_ccommit.c`, wired in by
    node/services/integrity.py for the columnar commit path)."""
    return _load_native("_ccommit")


def load_cverify():
    """The batched libcrypto Ed25519 verify core (`_cverify.c`, wired in
    by corda_tpu/crypto/provider.py). None when libcrypto is absent."""
    lib = _libcrypto_path()
    if lib is None:
        return None
    return _load_native("_cverify", (lib,))
