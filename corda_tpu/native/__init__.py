"""Native runtime components (C over the CPython API), with fallbacks.

The reference's runtime tiers (Kryo serialization, Artemis framing) are
JVM bytecode the JIT compiles to machine code; the corda_tpu equivalents
are Python, which pays an interpreter tax on the hottest per-message loops.
This package holds C implementations of those loops — currently the codec
decode core (`_ccodec.c`, wired in by corda_tpu/serialization/codec.py) —
compiled on first use with the system compiler and loaded with a graceful
pure-Python fallback, so the framework never REQUIRES a toolchain but uses
one when present. Set CORDA_TPU_NO_NATIVE=1 to force the Python paths
(conformance tests run both).
"""

from __future__ import annotations

import os
import pathlib
import subprocess
import sysconfig
import tempfile


def load_ccodec():
    """Import the native codec core, building it on first use. Returns the
    module or None (no compiler, build failure, or CORDA_TPU_NO_NATIVE)."""
    if os.environ.get("CORDA_TPU_NO_NATIVE"):
        return None
    try:
        from . import _ccodec  # already built

        return _ccodec
    except ImportError:
        pass
    src = pathlib.Path(__file__).with_name("_ccodec.c")
    if not src.exists():
        return None
    ext_suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    target = src.with_name("_ccodec" + ext_suffix)
    include = sysconfig.get_paths()["include"]
    # Build to a temp name and os.replace (atomic) so concurrent builders
    # (the driver spawns many node processes at once) never load a
    # half-written .so.
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=str(src.parent))
        os.close(fd)
        subprocess.run(
            ["gcc", "-O2", "-fPIC", "-shared", f"-I{include}",
             str(src), "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, target)
    except Exception:
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return None
    try:
        from . import _ccodec

        return _ccodec
    except ImportError:
        return None
