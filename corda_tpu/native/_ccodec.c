/* Native decode core for the corda_tpu canonical codec.
 *
 * The wire format is defined by corda_tpu/serialization/codec.py (_decode);
 * this is a semantics-identical C implementation of the hot loop — the
 * profile of a Raft notary firehose put ~55% of round CPU inside
 * _decode/_read_varint, and the reference's equivalent tier (Kryo) is JVM
 * bytecode JIT-compiled, so a Python-only codec is the one place this
 * framework was paying an interpreter tax the reference does not.
 *
 * Division of labour: every primitive / collection tag decodes natively;
 * the OBJECT tag decodes its wire name + field values natively, then calls
 * back into Python (codec._construct) for registry lookup, custom decoders
 * and dataclass construction — so the whitelist and construction semantics
 * live in exactly one place (codec.py). Canonicality rules (minimal
 * varints, strict dict/frozenset encoded-byte ordering, canonical -0.0,
 * depth and count gates) are enforced here bit-for-bit; the conformance
 * suite runs both decoders against the same corpus.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <string.h>
#include <math.h>

/* Tag values MUST match codec.py's _TAG_* constants bit-for-bit. */
#define TAG_NONE 0x00
#define TAG_FALSE 0x01
#define TAG_TRUE 0x02
#define TAG_INT 0x03
#define TAG_BYTES 0x04
#define TAG_STR 0x05
#define TAG_LIST 0x06
#define TAG_DICT 0x07
#define TAG_OBJECT 0x08
#define TAG_FROZENSET 0x09
#define TAG_FLOAT 0x0A

#define MAX_DEPTH 64

/* Set once by _init(): the codec's DeserializationError and the Python
 * construct callback for objects. */
static PyObject *DeserializationError = NULL;
static PyObject *construct_cb = NULL;

static void
raise_deser(const char *msg)
{
    if (DeserializationError != NULL) {
        PyErr_SetString(DeserializationError, msg);
    }
    else {
        PyErr_SetString(PyExc_ValueError, msg);
    }
}

/* Decode one varint. Fast path accumulates into a uint64; payloads wider
 * than 63 bits (e.g. zigzagged 256-bit crypto integers) fall back to
 * PyLong arithmetic. Returns new pos, or -1 on error. *out receives a NEW
 * reference to a PyLong. Enforces the minimal-encoding rule. */
static Py_ssize_t
read_varint(const unsigned char *data, Py_ssize_t len, Py_ssize_t pos,
            PyObject **out)
{
    unsigned long long acc = 0;
    int shift = 0;
    Py_ssize_t start = pos;

    while (1) {
        if (pos >= len) {
            raise_deser("truncated varint");
            return -1;
        }
        unsigned char b = data[pos++];
        if (shift <= 56) {
            acc |= ((unsigned long long)(b & 0x7F)) << shift;
        }
        if (!(b & 0x80)) {
            if (b == 0 && shift > 0) {
                raise_deser("non-minimal varint");
                return -1;
            }
            if (shift <= 56) {
                *out = PyLong_FromUnsignedLongLong(acc);
                return (*out == NULL) ? -1 : pos;
            }
            break; /* wide: redo with PyLong below */
        }
        shift += 7;
    }

    /* Slow path: rebuild from the bytes with PyLong arithmetic. The fast
     * loop only breaks here after SEEING the terminator in-bounds, so the
     * re-scan is bounded — the explicit check documents (and enforces)
     * that invariant. */
    {
        PyObject *result = PyLong_FromLong(0);
        if (result == NULL)
            return -1;
        int sh = 0;
        for (Py_ssize_t i = start;; i++) {
            if (i >= len) {
                Py_DECREF(result);
                raise_deser("truncated varint");
                return -1;
            }
            unsigned char b = data[i];
            PyObject *group = PyLong_FromUnsignedLong(b & 0x7F);
            PyObject *shn = PyLong_FromLong(sh);
            if (group == NULL || shn == NULL) {
                Py_XDECREF(group);
                Py_XDECREF(shn);
                Py_DECREF(result);
                return -1;
            }
            PyObject *shifted = PyNumber_Lshift(group, shn);
            Py_DECREF(group);
            Py_DECREF(shn);
            if (shifted == NULL) {
                Py_DECREF(result);
                return -1;
            }
            PyObject *summed = PyNumber_Or(result, shifted);
            Py_DECREF(shifted);
            Py_DECREF(result);
            if (summed == NULL)
                return -1;
            result = summed;
            if (!(b & 0x80)) {
                *out = result;
                return i + 1;
            }
            sh += 7;
        }
    }
}

/* Varint whose value is needed as a size: rejects values > SSIZE_MAX. */
static Py_ssize_t
read_size(const unsigned char *data, Py_ssize_t len, Py_ssize_t pos,
          Py_ssize_t *out)
{
    PyObject *n = NULL;
    pos = read_varint(data, len, pos, &n);
    if (pos < 0)
        return -1;
    Py_ssize_t v = PyLong_AsSsize_t(n);
    Py_DECREF(n);
    if (v < 0) {
        if (PyErr_Occurred())
            PyErr_Clear();
        raise_deser("collection count exceeds data");
        return -1;
    }
    *out = v;
    return pos;
}

static Py_ssize_t decode_value(const unsigned char *data, Py_ssize_t len,
                               Py_ssize_t pos, int depth, PyObject **out);

/* zigzag-decode a PyLong: (n >> 1) ^ -(n & 1). New reference. */
static PyObject *
unzigzag(PyObject *n)
{
    PyObject *one = PyLong_FromLong(1);
    if (one == NULL)
        return NULL;
    PyObject *half = PyNumber_Rshift(n, one);
    PyObject *low = PyNumber_And(n, one);
    Py_DECREF(one);
    if (half == NULL || low == NULL) {
        Py_XDECREF(half);
        Py_XDECREF(low);
        return NULL;
    }
    PyObject *neg = PyNumber_Negative(low);
    Py_DECREF(low);
    if (neg == NULL) {
        Py_DECREF(half);
        return NULL;
    }
    PyObject *result = PyNumber_Xor(half, neg);
    Py_DECREF(half);
    Py_DECREF(neg);
    return result;
}

static Py_ssize_t
decode_value(const unsigned char *data, Py_ssize_t len, Py_ssize_t pos,
             int depth, PyObject **out)
{
    if (depth > MAX_DEPTH) {
        raise_deser("nesting too deep");
        return -1;
    }
    if (pos >= len) {
        raise_deser("truncated data");
        return -1;
    }
    unsigned char tag = data[pos++];
    switch (tag) {
    case TAG_NONE:
        Py_INCREF(Py_None);
        *out = Py_None;
        return pos;
    case TAG_FALSE:
        Py_INCREF(Py_False);
        *out = Py_False;
        return pos;
    case TAG_TRUE:
        Py_INCREF(Py_True);
        *out = Py_True;
        return pos;
    case TAG_INT: {
        PyObject *n = NULL;
        pos = read_varint(data, len, pos, &n);
        if (pos < 0)
            return -1;
        /* Fast path: small zigzag values avoid PyNumber calls. */
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(n, &overflow);
        if (!overflow && v >= 0) {
            Py_DECREF(n);
            long long dec = (long long)((unsigned long long)v >> 1);
            if (v & 1)
                dec = -dec - 1;
            *out = PyLong_FromLongLong(dec);
            return (*out == NULL) ? -1 : pos;
        }
        PyErr_Clear();
        *out = unzigzag(n);
        Py_DECREF(n);
        return (*out == NULL) ? -1 : pos;
    }
    case TAG_FLOAT: {
        if (pos + 8 > len) {
            raise_deser("truncated float");
            return -1;
        }
        unsigned long long bits = 0;
        for (int i = 0; i < 8; i++)
            bits = (bits << 8) | data[pos + i];
        double value;
        memcpy(&value, &bits, 8);
        if (!isfinite(value)) {
            raise_deser("non-finite float");
            return -1;
        }
        if (value == 0.0 && data[pos] != 0) {
            raise_deser("non-canonical negative zero");
            return -1;
        }
        *out = PyFloat_FromDouble(value);
        return (*out == NULL) ? -1 : pos + 8;
    }
    case TAG_BYTES: {
        Py_ssize_t n;
        pos = read_size(data, len, pos, &n);
        if (pos < 0)
            return -1;
        if (n > len - pos) {
            raise_deser("truncated bytes");
            return -1;
        }
        *out = PyBytes_FromStringAndSize((const char *)data + pos, n);
        return (*out == NULL) ? -1 : pos + n;
    }
    case TAG_STR: {
        Py_ssize_t n;
        pos = read_size(data, len, pos, &n);
        if (pos < 0)
            return -1;
        if (n > len - pos) {
            raise_deser("truncated string");
            return -1;
        }
        PyObject *s = PyUnicode_DecodeUTF8((const char *)data + pos, n, NULL);
        if (s == NULL) {
            PyObject *type, *value, *tb;
            PyErr_Fetch(&type, &value, &tb);
            PyObject *msg = PyUnicode_FromFormat("invalid utf-8 string: %S",
                                                 value ? value : Py_None);
            Py_XDECREF(type);
            Py_XDECREF(value);
            Py_XDECREF(tb);
            if (msg != NULL) {
                PyErr_SetObject(DeserializationError, msg);
                Py_DECREF(msg);
            }
            return -1;
        }
        *out = s;
        return pos + n;
    }
    case TAG_LIST: {
        Py_ssize_t n;
        pos = read_size(data, len, pos, &n);
        if (pos < 0)
            return -1;
        if (n > len - pos) {
            raise_deser("collection count exceeds data");
            return -1;
        }
        PyObject *tup = PyTuple_New(n);
        if (tup == NULL)
            return -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *item = NULL;
            pos = decode_value(data, len, pos, depth + 1, &item);
            if (pos < 0) {
                Py_DECREF(tup);
                return -1;
            }
            PyTuple_SET_ITEM(tup, i, item);
        }
        *out = tup;
        return pos;
    }
    case TAG_DICT: {
        Py_ssize_t n;
        pos = read_size(data, len, pos, &n);
        if (pos < 0)
            return -1;
        if (n > len - pos) {
            raise_deser("collection count exceeds data");
            return -1;
        }
        PyObject *d = PyDict_New();
        if (d == NULL)
            return -1;
        Py_ssize_t prev_start = -1, prev_end = -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            Py_ssize_t kstart = pos;
            PyObject *k = NULL;
            pos = decode_value(data, len, pos, depth + 1, &k);
            if (pos < 0) {
                Py_DECREF(d);
                return -1;
            }
            Py_ssize_t kend = pos;
            PyObject *v = NULL;
            pos = decode_value(data, len, pos, depth + 1, &v);
            if (pos < 0) {
                Py_DECREF(k);
                Py_DECREF(d);
                return -1;
            }
            if (prev_start >= 0) {
                /* strict bytewise increase of key encodings */
                Py_ssize_t alen = prev_end - prev_start;
                Py_ssize_t blen = kend - kstart;
                Py_ssize_t m = alen < blen ? alen : blen;
                int cmp = memcmp(data + prev_start, data + kstart, m);
                int le = (cmp > 0) ? 0 : (cmp < 0) ? 1 : (alen < blen);
                if (!le) {
                    Py_DECREF(k);
                    Py_DECREF(v);
                    Py_DECREF(d);
                    raise_deser("non-canonical dict entry order");
                    return -1;
                }
            }
            prev_start = kstart;
            prev_end = kend;
            int rc = PyDict_SetItem(d, k, v);
            Py_DECREF(k);
            Py_DECREF(v);
            if (rc < 0) {
                if (PyErr_ExceptionMatches(PyExc_TypeError)) {
                    PyErr_Clear();
                    raise_deser("unhashable dict key");
                }
                Py_DECREF(d);
                return -1;
            }
        }
        *out = d;
        return pos;
    }
    case TAG_FROZENSET: {
        Py_ssize_t n;
        pos = read_size(data, len, pos, &n);
        if (pos < 0)
            return -1;
        if (n > len - pos) {
            raise_deser("collection count exceeds data");
            return -1;
        }
        PyObject *list = PyList_New(n);
        if (list == NULL)
            return -1;
        Py_ssize_t prev_start = -1, prev_end = -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            Py_ssize_t start = pos;
            PyObject *item = NULL;
            pos = decode_value(data, len, pos, depth + 1, &item);
            if (pos < 0) {
                Py_DECREF(list);
                return -1;
            }
            if (prev_start >= 0) {
                Py_ssize_t alen = prev_end - prev_start;
                Py_ssize_t blen = pos - start;
                Py_ssize_t m = alen < blen ? alen : blen;
                int cmp = memcmp(data + prev_start, data + start, m);
                int le = (cmp > 0) ? 0 : (cmp < 0) ? 1 : (alen < blen);
                if (!le) {
                    Py_DECREF(item);
                    Py_DECREF(list);
                    raise_deser("non-canonical frozenset order");
                    return -1;
                }
            }
            prev_start = start;
            prev_end = pos;
            PyList_SET_ITEM(list, i, item);
        }
        PyObject *fs = PyFrozenSet_New(list);
        Py_DECREF(list);
        if (fs == NULL) {
            if (PyErr_ExceptionMatches(PyExc_TypeError)) {
                PyErr_Clear();
                raise_deser("unhashable set member");
            }
            return -1;
        }
        *out = fs;
        return pos;
    }
    case TAG_OBJECT: {
        Py_ssize_t n;
        pos = read_size(data, len, pos, &n);
        if (pos < 0)
            return -1;
        if (n > len - pos) {
            raise_deser("truncated wire name");
            return -1;
        }
        PyObject *name = PyUnicode_DecodeUTF8((const char *)data + pos, n,
                                              NULL);
        if (name == NULL) {
            PyErr_Clear();
            raise_deser("invalid wire name");
            return -1;
        }
        pos += n;
        Py_ssize_t nfields;
        pos = read_size(data, len, pos, &nfields);
        if (pos < 0) {
            Py_DECREF(name);
            return -1;
        }
        if (nfields > len - pos) {
            Py_DECREF(name);
            raise_deser("collection count exceeds data");
            return -1;
        }
        PyObject *values = PyTuple_New(nfields);
        if (values == NULL) {
            Py_DECREF(name);
            return -1;
        }
        for (Py_ssize_t i = 0; i < nfields; i++) {
            PyObject *v = NULL;
            pos = decode_value(data, len, pos, depth + 1, &v);
            if (pos < 0) {
                Py_DECREF(name);
                Py_DECREF(values);
                return -1;
            }
            PyTuple_SET_ITEM(values, i, v);
        }
        PyObject *obj = PyObject_CallFunctionObjArgs(construct_cb, name,
                                                     values, NULL);
        Py_DECREF(name);
        Py_DECREF(values);
        if (obj == NULL)
            return -1;
        *out = obj;
        return pos;
    }
    default: {
        char msg[48];
        snprintf(msg, sizeof(msg), "unknown tag 0x%02x", tag);
        raise_deser(msg);
        return -1;
    }
    }
}

/* ------------------------------------------------------------------ encode */

/* Python-side hooks for the object branch (set by init). */
static PyObject *object_parts_cb = NULL; /* value -> bytes | (name, fields, memo) */
static PyObject *memo_store_cb = NULL;   /* (value, enc_bytes) -> None */

typedef struct {
    unsigned char *buf;
    Py_ssize_t len;
    Py_ssize_t cap;
} Buf;

static int
buf_reserve(Buf *b, Py_ssize_t extra)
{
    if (b->len + extra <= b->cap)
        return 0;
    Py_ssize_t cap = b->cap ? b->cap : 256;
    while (cap < b->len + extra)
        cap *= 2;
    unsigned char *nb = PyMem_Realloc(b->buf, cap);
    if (nb == NULL) {
        PyErr_NoMemory();
        return -1;
    }
    b->buf = nb;
    b->cap = cap;
    return 0;
}

static int
buf_byte(Buf *b, unsigned char c)
{
    if (buf_reserve(b, 1) < 0)
        return -1;
    b->buf[b->len++] = c;
    return 0;
}

static int
buf_bytes(Buf *b, const unsigned char *p, Py_ssize_t n)
{
    if (buf_reserve(b, n) < 0)
        return -1;
    memcpy(b->buf + b->len, p, n);
    b->len += n;
    return 0;
}

static int
buf_varint(Buf *b, unsigned long long n)
{
    while (1) {
        unsigned char c = n & 0x7F;
        n >>= 7;
        if (n) {
            if (buf_byte(b, c | 0x80) < 0)
                return -1;
        }
        else {
            return buf_byte(b, c);
        }
    }
}

static int encode_value(Buf *b, PyObject *value, int depth);

/* Encode one value into a fresh bytes object (for dict/frozenset entry
 * sorting). */
static PyObject *
encode_to_bytes(PyObject *value, int depth)
{
    Buf sub = {NULL, 0, 0};
    if (encode_value(&sub, value, depth) < 0) {
        PyMem_Free(sub.buf);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)sub.buf, sub.len);
    PyMem_Free(sub.buf);
    return out;
}

/* Beyond the decoder's MAX_DEPTH (64) nothing is round-trippable anyway;
 * the headroom only exists so the limit can never bite legitimate data.
 * Raised as RecursionError for parity with the pure encoder, where a cycle
 * or pathological nesting exhausts the interpreter stack catchably —
 * without this guard the C recursion would SEGFAULT the node process. */
#define ENCODE_MAX_DEPTH 200

static int
encode_value(Buf *b, PyObject *value, int depth)
{
    if (depth > ENCODE_MAX_DEPTH) {
        PyErr_SetString(PyExc_RecursionError,
                        "maximum encoding depth exceeded");
        return -1;
    }
    if (value == Py_None)
        return buf_byte(b, TAG_NONE);
    if (value == Py_False)
        return buf_byte(b, TAG_FALSE);
    if (value == Py_True)
        return buf_byte(b, TAG_TRUE);
    if (PyLong_Check(value)) {
        int overflow = 0;
        long long v = PyLong_AsLongLongAndOverflow(value, &overflow);
        /* zigzag fits u64 iff |v| < 2^62-ish; be conservative. */
        if (!overflow && v > -(1LL << 62) && v < (1LL << 62)) {
            unsigned long long zz = (v < 0)
                ? (((unsigned long long)(-(v + 1))) << 1) | 1
                : ((unsigned long long)v) << 1;
            if (buf_byte(b, TAG_INT) < 0)
                return -1;
            return buf_varint(b, zz);
        }
        PyErr_Clear();
        /* Wide integers: delegate to the Python encoder (rare). */
        goto python_fallback;
    }
    if (PyFloat_Check(value)) {
        double d = PyFloat_AS_DOUBLE(value);
        if (!isfinite(d)) {
            PyErr_SetString(PyExc_TypeError,
                            "non-finite floats are not serializable");
            return -1;
        }
        if (d == 0.0)
            d = 0.0; /* normalize -0.0 */
        unsigned long long bits;
        memcpy(&bits, &d, 8);
        if (buf_byte(b, TAG_FLOAT) < 0 || buf_reserve(b, 8) < 0)
            return -1;
        for (int i = 7; i >= 0; i--)
            b->buf[b->len++] = (bits >> (8 * i)) & 0xFF;
        return 0;
    }
    if (PyBytes_Check(value)) {
        Py_ssize_t n = PyBytes_GET_SIZE(value);
        if (buf_byte(b, TAG_BYTES) < 0 || buf_varint(b, n) < 0)
            return -1;
        return buf_bytes(b, (unsigned char *)PyBytes_AS_STRING(value), n);
    }
    if (PyUnicode_Check(value)) {
        Py_ssize_t n;
        const char *utf8 = PyUnicode_AsUTF8AndSize(value, &n);
        if (utf8 == NULL)
            return -1;
        if (buf_byte(b, TAG_STR) < 0 || buf_varint(b, n) < 0)
            return -1;
        return buf_bytes(b, (const unsigned char *)utf8, n);
    }
    if (PyList_Check(value) || PyTuple_Check(value)) {
        Py_ssize_t n = PySequence_Size(value);
        if (n < 0)
            return -1;
        if (buf_byte(b, TAG_LIST) < 0 || buf_varint(b, n) < 0)
            return -1;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *item = PySequence_GetItem(value, i);
            if (item == NULL)
                return -1;
            int rc = encode_value(b, item, depth + 1);
            Py_DECREF(item);
            if (rc < 0)
                return -1;
        }
        return 0;
    }
    if (PyDict_Check(value)) {
        /* Canonical: entries sorted by (key-encoding, value-encoding). */
        PyObject *entries = PyList_New(0);
        if (entries == NULL)
            return -1;
        PyObject *k, *v;
        Py_ssize_t ppos = 0;
        while (PyDict_Next(value, &ppos, &k, &v)) {
            PyObject *kenc = encode_to_bytes(k, depth + 1);
            if (kenc == NULL)
                goto dict_fail;
            PyObject *venc = encode_to_bytes(v, depth + 1);
            if (venc == NULL) {
                Py_DECREF(kenc);
                goto dict_fail;
            }
            PyObject *pair = PyTuple_Pack(2, kenc, venc);
            Py_DECREF(kenc);
            Py_DECREF(venc);
            if (pair == NULL || PyList_Append(entries, pair) < 0) {
                Py_XDECREF(pair);
                goto dict_fail;
            }
            Py_DECREF(pair);
        }
        if (PyList_Sort(entries) < 0)
            goto dict_fail;
        Py_ssize_t n = PyList_GET_SIZE(entries);
        if (buf_byte(b, TAG_DICT) < 0 || buf_varint(b, n) < 0)
            goto dict_fail;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *pair = PyList_GET_ITEM(entries, i);
            PyObject *kenc = PyTuple_GET_ITEM(pair, 0);
            PyObject *venc = PyTuple_GET_ITEM(pair, 1);
            if (buf_bytes(b, (unsigned char *)PyBytes_AS_STRING(kenc),
                          PyBytes_GET_SIZE(kenc)) < 0
                || buf_bytes(b, (unsigned char *)PyBytes_AS_STRING(venc),
                             PyBytes_GET_SIZE(venc)) < 0)
                goto dict_fail;
        }
        Py_DECREF(entries);
        return 0;
    dict_fail:
        Py_DECREF(entries);
        return -1;
    }
    if (PyFrozenSet_Check(value)) {
        PyObject *encs = PyList_New(0);
        if (encs == NULL)
            return -1;
        PyObject *iter = PyObject_GetIter(value);
        if (iter == NULL)
            goto set_fail;
        PyObject *item;
        while ((item = PyIter_Next(iter)) != NULL) {
            PyObject *enc = encode_to_bytes(item, depth + 1);
            Py_DECREF(item);
            if (enc == NULL || PyList_Append(encs, enc) < 0) {
                Py_XDECREF(enc);
                Py_DECREF(iter);
                goto set_fail;
            }
            Py_DECREF(enc);
        }
        Py_DECREF(iter);
        if (PyErr_Occurred())
            goto set_fail;
        if (PyList_Sort(encs) < 0)
            goto set_fail;
        Py_ssize_t n = PyList_GET_SIZE(encs);
        if (buf_byte(b, TAG_FROZENSET) < 0 || buf_varint(b, n) < 0)
            goto set_fail;
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *enc = PyList_GET_ITEM(encs, i);
            if (buf_bytes(b, (unsigned char *)PyBytes_AS_STRING(enc),
                          PyBytes_GET_SIZE(enc)) < 0)
                goto set_fail;
        }
        Py_DECREF(encs);
        return 0;
    set_fail:
        Py_DECREF(encs);
        return -1;
    }
    /* Object branch: ask Python for the parts (registry, custom encoders,
     * service tokens, memo reads all live in codec._object_parts). */
    {
        PyObject *parts = PyObject_CallFunctionObjArgs(object_parts_cb,
                                                       value, NULL);
        if (parts == NULL)
            return -1;
        if (PyBytes_Check(parts)) { /* memo hit or fully Python-encoded */
            int rc = buf_bytes(b, (unsigned char *)PyBytes_AS_STRING(parts),
                               PyBytes_GET_SIZE(parts));
            Py_DECREF(parts);
            return rc;
        }
        PyObject *name_raw = PyTuple_GET_ITEM(parts, 0);
        PyObject *fields = PyTuple_GET_ITEM(parts, 1);
        int memoize = PyObject_IsTrue(PyTuple_GET_ITEM(parts, 2));
        Py_ssize_t start = b->len;
        Py_ssize_t nname = PyBytes_GET_SIZE(name_raw);
        Py_ssize_t nfields = PyTuple_GET_SIZE(fields);
        if (buf_byte(b, TAG_OBJECT) < 0 || buf_varint(b, nname) < 0
            || buf_bytes(b, (unsigned char *)PyBytes_AS_STRING(name_raw),
                         nname) < 0
            || buf_varint(b, nfields) < 0) {
            Py_DECREF(parts);
            return -1;
        }
        for (Py_ssize_t i = 0; i < nfields; i++) {
            if (encode_value(b, PyTuple_GET_ITEM(fields, i), depth + 1) < 0) {
                Py_DECREF(parts);
                return -1;
            }
        }
        Py_DECREF(parts);
        if (memoize) {
            PyObject *enc = PyBytes_FromStringAndSize(
                (const char *)b->buf + start, b->len - start);
            if (enc == NULL)
                return -1;
            PyObject *rc = PyObject_CallFunctionObjArgs(memo_store_cb, value,
                                                        enc, NULL);
            Py_DECREF(enc);
            if (rc == NULL)
                return -1;
            Py_DECREF(rc);
        }
        return 0;
    }

python_fallback:
    {
        /* Values the C core does not handle natively (wide integers):
         * object_parts_cb returns their full Python encoding as bytes. */
        PyObject *enc = PyObject_CallFunctionObjArgs(object_parts_cb, value,
                                                     NULL);
        if (enc == NULL)
            return -1;
        if (!PyBytes_Check(enc)) {
            Py_DECREF(enc);
            PyErr_SetString(PyExc_TypeError,
                            "fallback encoding must return bytes");
            return -1;
        }
        int rc = buf_bytes(b, (unsigned char *)PyBytes_AS_STRING(enc),
                           PyBytes_GET_SIZE(enc));
        Py_DECREF(enc);
        return rc;
    }
}

static PyObject *
ccodec_encode(PyObject *self, PyObject *arg)
{
    Buf b = {NULL, 0, 0};
    if (encode_value(&b, arg, 0) < 0) {
        PyMem_Free(b.buf);
        return NULL;
    }
    PyObject *out = PyBytes_FromStringAndSize((const char *)b.buf, b.len);
    PyMem_Free(b.buf);
    return out;
}

static PyObject *
ccodec_decode(PyObject *self, PyObject *arg)
{
    Py_buffer view;
    if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) < 0)
        return NULL;
    PyObject *out = NULL;
    Py_ssize_t pos = decode_value((const unsigned char *)view.buf, view.len,
                                  0, 0, &out);
    if (pos < 0) {
        PyBuffer_Release(&view);
        return NULL;
    }
    if (pos != view.len) {
        Py_DECREF(out);
        char msg[64];
        snprintf(msg, sizeof(msg), "%zd trailing bytes",
                 (Py_ssize_t)(view.len - pos));
        raise_deser(msg);
        PyBuffer_Release(&view);
        return NULL;
    }
    PyBuffer_Release(&view);
    return out;
}

static PyObject *
ccodec_init(PyObject *self, PyObject *args)
{
    PyObject *err_cls, *cb, *parts = NULL, *memo = NULL;
    if (!PyArg_ParseTuple(args, "OO|OO", &err_cls, &cb, &parts, &memo))
        return NULL;
    Py_XDECREF(DeserializationError);
    Py_XDECREF(construct_cb);
    Py_INCREF(err_cls);
    Py_INCREF(cb);
    DeserializationError = err_cls;
    construct_cb = cb;
    if (parts != NULL && memo != NULL) {
        Py_XDECREF(object_parts_cb);
        Py_XDECREF(memo_store_cb);
        Py_INCREF(parts);
        Py_INCREF(memo);
        object_parts_cb = parts;
        memo_store_cb = memo;
    }
    Py_RETURN_NONE;
}

static PyMethodDef ccodec_methods[] = {
    {"decode", ccodec_decode, METH_O,
     "decode(data) -> value; the native form of codec._decode."},
    {"encode", ccodec_encode, METH_O,
     "encode(value) -> bytes; the native form of codec._encode."},
    {"init", ccodec_init, METH_VARARGS,
     "init(DeserializationError, construct_cb[, object_parts, memo_store])."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef ccodec_module = {
    PyModuleDef_HEAD_INIT, "_ccodec",
    "Native decode core for the corda_tpu canonical codec.", -1,
    ccodec_methods,
};

PyMODINIT_FUNC
PyInit__ccodec(void)
{
    return PyModule_Create(&ccodec_module);
}
