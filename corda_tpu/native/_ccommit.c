/* Batched CRC32C for the columnar commit path, with the GIL RELEASED.
 *
 * Why this exists: the pipelined commit plane's columnar PutAll apply
 * (node/services/raft.py make_apply_command -> _put_all_many) precomputes
 * the committed_states integrity frame for every (state_ref, consuming)
 * row in a sealed batch BEFORE taking db.lock. The pure-Python CRC32C in
 * node/services/integrity.py is a per-byte table loop — fine next to an
 * fsync, hostile inside a multi-thousand-row batch where it both burns
 * interpreter time and holds the GIL against the consensus thread's
 * socket pumping. This core runs the whole batch in C between
 * Py_BEGIN/END_ALLOW_THREADS, same playbook as _cverify's sign_many.
 *
 * Bit-identical contract: the polynomial (reflected Castagnoli,
 * 0x82F63B78), byte order, init/final XOR, and the committed_crc
 * composition crc32c(consuming, crc32c(state_ref)) all match
 * integrity.py exactly — tests assert equality on reference vectors and
 * random batches, and CORDA_TPU_NO_NATIVE forces the Python path.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stddef.h>
#include <stdint.h>

#define CRC32C_POLY 0x82F63B78u /* reflected 0x1EDC6F41 */

static uint32_t crc_table[256];

static void fill_table(void) {
    for (uint32_t n = 0; n < 256; n++) {
        uint32_t c = n;
        for (int k = 0; k < 8; k++)
            c = (c & 1) ? (c >> 1) ^ CRC32C_POLY : c >> 1;
        crc_table[n] = c;
    }
}

static uint32_t crc32c_raw(uint32_t crc, const unsigned char *buf,
                           Py_ssize_t len) {
    uint32_t c = crc ^ 0xFFFFFFFFu;
    for (Py_ssize_t i = 0; i < len; i++)
        c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
    return c ^ 0xFFFFFFFFu;
}

static PyObject *crc32c_py(PyObject *self, PyObject *args) {
    Py_buffer data;
    unsigned long crc = 0;
    if (!PyArg_ParseTuple(args, "y*|k", &data, &crc))
        return NULL;
    uint32_t out = crc32c_raw((uint32_t)crc, data.buf, data.len);
    PyBuffer_Release(&data);
    return PyLong_FromUnsignedLong(out);
}

typedef struct {
    const unsigned char *ref;
    Py_ssize_t ref_len;
    const unsigned char *con;
    Py_ssize_t con_len;
} crc_job;

static PyObject *committed_crc_many(PyObject *self, PyObject *args) {
    PyObject *pairs;
    if (!PyArg_ParseTuple(args, "O", &pairs))
        return NULL;
    PyObject *seq = PySequence_Fast(pairs, "pairs must be a sequence");
    if (seq == NULL)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
    crc_job *jobs = NULL;
    uint32_t *crcs = NULL;
    PyObject *out = NULL;
    if (n > 0) {
        jobs = PyMem_Malloc(n * sizeof(crc_job));
        crcs = PyMem_Malloc(n * sizeof(uint32_t));
        if (jobs == NULL || crcs == NULL) {
            PyErr_NoMemory();
            goto done;
        }
    }
    /* Collect raw pointers under the GIL; the tuples/bytes stay alive
     * through `seq` for the duration of the call. */
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *pair = PySequence_Fast_GET_ITEM(seq, i);
        PyObject *ref, *con;
        if (PyTuple_Check(pair) && PyTuple_GET_SIZE(pair) == 2) {
            ref = PyTuple_GET_ITEM(pair, 0);
            con = PyTuple_GET_ITEM(pair, 1);
        } else {
            PyErr_SetString(PyExc_TypeError,
                            "each pair must be a (ref, consuming) tuple");
            goto done;
        }
        if (!PyBytes_Check(ref) || !PyBytes_Check(con)) {
            PyErr_SetString(PyExc_TypeError, "pair members must be bytes");
            goto done;
        }
        jobs[i].ref = (const unsigned char *)PyBytes_AS_STRING(ref);
        jobs[i].ref_len = PyBytes_GET_SIZE(ref);
        jobs[i].con = (const unsigned char *)PyBytes_AS_STRING(con);
        jobs[i].con_len = PyBytes_GET_SIZE(con);
    }
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t i = 0; i < n; i++) {
        uint32_t inner = crc32c_raw(0, jobs[i].ref, jobs[i].ref_len);
        crcs[i] = crc32c_raw(inner, jobs[i].con, jobs[i].con_len);
    }
    Py_END_ALLOW_THREADS
    out = PyList_New(n);
    if (out == NULL)
        goto done;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *v = PyLong_FromUnsignedLong(crcs[i]);
        if (v == NULL) {
            Py_DECREF(out);
            out = NULL;
            goto done;
        }
        PyList_SET_ITEM(out, i, v);
    }
done:
    PyMem_Free(jobs);
    PyMem_Free(crcs);
    Py_DECREF(seq);
    return out;
}

static PyMethodDef methods[] = {
    {"crc32c", crc32c_py, METH_VARARGS,
     "crc32c(data, crc=0) -> int: CRC32C (Castagnoli), bit-identical to "
     "integrity.crc32c."},
    {"committed_crc_many", committed_crc_many, METH_VARARGS,
     "committed_crc_many([(state_ref, consuming), ...]) -> [int]: the "
     "committed_states integrity frame for a whole columnar batch, GIL "
     "released across the computation."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_ccommit",
    "Batched CRC32C integrity frames (GIL-free hot loop).",
    -1, methods,
};

PyMODINIT_FUNC PyInit__ccommit(void) {
    fill_table();
    return PyModule_Create(&module);
}
