/* Batched Ed25519 verification over libcrypto with the GIL RELEASED.
 *
 * Why this exists: the Python host verify loop (corda_tpu/crypto/
 * fast_ed25519.py) pays per-call FFI overhead AND holds the GIL for the
 * whole batch — measured on a loaded 5-process driver cluster, per-sig
 * cost inflated ~4-8x over the single-thread OpenSSL floor because the
 * node's transport/bridge threads starve behind the verify flush. This
 * core runs the whole batch in C between Py_BEGIN/END_ALLOW_THREADS, so
 * readers, bridges and the sqlite round keep moving while signatures
 * grind. It is an ACCEPT-FAST path only: any signature it rejects is
 * re-checked by the caller on the authoritative oracle (ref_ed25519), so
 * its accept set must be (and is) a subset of the oracle's — identical
 * to the fast_ed25519 argument, one layer down.
 *
 * (Reference hot loop this replaces at batch granularity:
 * core/src/main/kotlin/net/corda/core/transactions/SignedTransaction.kt:83-87.)
 *
 * libcrypto is declared extern (no openssl headers in this image) and the
 * loader links against the installed libcrypto.so.3 directly. The five
 * symbols used are in OpenSSL 1.1.1+'s stable ABI.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <pthread.h>
#include <stddef.h>
#include <stdint.h>
#include <string.h>

typedef struct evp_pkey_st EVP_PKEY;
typedef struct evp_md_ctx_st EVP_MD_CTX;
typedef struct evp_md_st EVP_MD;
typedef struct engine_st ENGINE;
typedef struct evp_pkey_ctx_st EVP_PKEY_CTX;

extern EVP_PKEY *EVP_PKEY_new_raw_public_key(
    int type, ENGINE *e, const unsigned char *key, size_t keylen);
extern void EVP_PKEY_free(EVP_PKEY *pkey);
extern EVP_MD_CTX *EVP_MD_CTX_new(void);
extern void EVP_MD_CTX_free(EVP_MD_CTX *ctx);
extern int EVP_DigestVerifyInit(
    EVP_MD_CTX *ctx, EVP_PKEY_CTX **pctx, const EVP_MD *type, ENGINE *e,
    EVP_PKEY *pkey);
extern int EVP_DigestVerify(
    EVP_MD_CTX *ctx, const unsigned char *sig, size_t siglen,
    const unsigned char *tbs, size_t tbslen);
extern EVP_PKEY *EVP_PKEY_new_raw_private_key(
    int type, ENGINE *e, const unsigned char *key, size_t keylen);
extern int EVP_DigestSignInit(
    EVP_MD_CTX *ctx, EVP_PKEY_CTX **pctx, const EVP_MD *type, ENGINE *e,
    EVP_PKEY *pkey);
extern int EVP_DigestSign(
    EVP_MD_CTX *ctx, unsigned char *sigret, size_t *siglen,
    const unsigned char *tbs, size_t tbslen);

#define EVP_PKEY_ED25519 1087

typedef struct {
    const unsigned char *pk;
    const unsigned char *msg;
    Py_ssize_t msg_len;
    const unsigned char *sig;
    int ok;       /* result: 1 accept, 0 reject-or-skip */
    int eligible; /* well-formed enough to try (32B key, 64B sig) */
} job_t;

/* One verify. A fresh ctx per job: EVP_MD_CTX re-init across keys is
 * legal but buys nothing measurable for ed25519, and fresh state can
 * never leak a previous job's pkey on an error path. */
static int verify_one(const job_t *j) {
    EVP_PKEY *pkey = EVP_PKEY_new_raw_public_key(
        EVP_PKEY_ED25519, NULL, j->pk, 32);
    if (pkey == NULL)
        return 0;
    EVP_MD_CTX *ctx = EVP_MD_CTX_new();
    if (ctx == NULL) {
        EVP_PKEY_free(pkey);
        return 0;
    }
    int ok = 0;
    if (EVP_DigestVerifyInit(ctx, NULL, NULL, NULL, pkey) == 1
        && EVP_DigestVerify(ctx, j->sig, 64, j->msg,
                            (size_t)j->msg_len) == 1)
        ok = 1;
    EVP_MD_CTX_free(ctx);
    EVP_PKEY_free(pkey);
    return ok;
}

typedef struct {
    job_t *jobs;
    Py_ssize_t lo, hi;
} span_t;

static void *worker(void *arg) {
    span_t *s = (span_t *)arg;
    for (Py_ssize_t i = s->lo; i < s->hi; i++) {
        if (s->jobs[i].eligible)
            s->jobs[i].ok = verify_one(&s->jobs[i]);
    }
    return NULL;
}

/* Fan a big batch across a few pthreads (libcrypto's EVP verify is
 * thread-safe on independent ctx/pkey objects). Small batches stay
 * single-threaded — thread spawn costs more than they do. Capped at 4:
 * the deployment shape is several node processes sharing one small host,
 * and a verify flush must not starve its siblings. */
#define PAR_MIN 64
#define PAR_MAX_THREADS 4

#include <unistd.h>

static void run_jobs(job_t *jobs, Py_ssize_t n) {
    int nthreads = n >= PAR_MIN ? (int)(n / (PAR_MIN / 2)) : 1;
    if (nthreads > PAR_MAX_THREADS)
        nthreads = PAR_MAX_THREADS;
    long cores = sysconf(_SC_NPROCESSORS_ONLN);
    if (cores > 0 && nthreads > cores)
        nthreads = (int)cores; /* 1-core hosts: skip thread overhead */
    if (nthreads <= 1) {
        span_t all = {jobs, 0, n};
        worker(&all);
        return;
    }
    pthread_t tids[PAR_MAX_THREADS];
    span_t spans[PAR_MAX_THREADS];
    Py_ssize_t chunk = (n + nthreads - 1) / nthreads;
    int started = 0;
    for (int t = 0; t < nthreads; t++) {
        Py_ssize_t lo = (Py_ssize_t)t * chunk;
        Py_ssize_t hi = lo + chunk < n ? lo + chunk : n;
        if (lo >= hi)
            break;
        spans[t].jobs = jobs;
        spans[t].lo = lo;
        spans[t].hi = hi;
        if (t < nthreads - 1 && hi < n) {
            /* tids is compacted by success count, not span index: a failed
             * create must not leave a hole the join loop would read. */
            if (pthread_create(&tids[started], NULL, worker, &spans[t]) == 0) {
                started++;
                continue;
            }
        }
        /* last span (or a failed spawn) runs on this thread */
        worker(&spans[t]);
    }
    for (int t = 0; t < started; t++)
        pthread_join(tids[t], NULL);
}

/* verify_many(pubkeys, msgs, sigs) -> bytes (one 0/1 byte per job).
 *
 * Buffers are captured under the GIL; the verify loop runs without it. */
static PyObject *verify_many(PyObject *self, PyObject *args) {
    PyObject *pks, *msgs, *sigs;
    if (!PyArg_ParseTuple(args, "OOO", &pks, &msgs, &sigs))
        return NULL;
    PyObject *pk_seq = PySequence_Fast(pks, "pubkeys must be a sequence");
    if (pk_seq == NULL)
        return NULL;
    PyObject *msg_seq = PySequence_Fast(msgs, "msgs must be a sequence");
    if (msg_seq == NULL) {
        Py_DECREF(pk_seq);
        return NULL;
    }
    PyObject *sig_seq = PySequence_Fast(sigs, "sigs must be a sequence");
    if (sig_seq == NULL) {
        Py_DECREF(pk_seq);
        Py_DECREF(msg_seq);
        return NULL;
    }
    Py_ssize_t n = PySequence_Fast_GET_SIZE(pk_seq);
    if (PySequence_Fast_GET_SIZE(msg_seq) != n
        || PySequence_Fast_GET_SIZE(sig_seq) != n) {
        Py_DECREF(pk_seq);
        Py_DECREF(msg_seq);
        Py_DECREF(sig_seq);
        PyErr_SetString(PyExc_ValueError, "length mismatch");
        return NULL;
    }

    job_t *jobs = NULL;
    Py_buffer *views = NULL;
    Py_ssize_t n_views = 0;
    PyObject *out = NULL;
    if (n > 0) {
        jobs = PyMem_Calloc((size_t)n, sizeof(job_t));
        views = PyMem_Calloc((size_t)n * 3, sizeof(Py_buffer));
        if (jobs == NULL || views == NULL) {
            PyErr_NoMemory();
            goto done;
        }
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *items[3] = {
            PySequence_Fast_GET_ITEM(pk_seq, i),
            PySequence_Fast_GET_ITEM(msg_seq, i),
            PySequence_Fast_GET_ITEM(sig_seq, i),
        };
        Py_buffer bufs[3];
        int got = 0;
        for (; got < 3; got++) {
            if (PyObject_GetBuffer(items[got], &bufs[got],
                                   PyBUF_SIMPLE) != 0)
                break;
        }
        if (got < 3) {
            /* Unbufferable input: ineligible (reject -> oracle re-check),
             * never an exception — malformed jobs must reject, not raise. */
            PyErr_Clear();
            for (int k = 0; k < got; k++)
                PyBuffer_Release(&bufs[k]);
            continue;
        }
        for (int k = 0; k < 3; k++)
            views[n_views++] = bufs[k];
        if (bufs[0].len == 32 && bufs[2].len == 64) {
            jobs[i].pk = bufs[0].buf;
            jobs[i].msg = bufs[1].buf;
            jobs[i].msg_len = bufs[1].len;
            jobs[i].sig = bufs[2].buf;
            jobs[i].eligible = 1;
        }
    }

    Py_BEGIN_ALLOW_THREADS
    run_jobs(jobs, n);
    Py_END_ALLOW_THREADS

    out = PyBytes_FromStringAndSize(NULL, n);
    if (out != NULL) {
        char *p = PyBytes_AS_STRING(out);
        for (Py_ssize_t i = 0; i < n; i++)
            p[i] = (char)(jobs ? jobs[i].ok : 0);
    }

done:
    for (Py_ssize_t k = 0; k < n_views; k++)
        PyBuffer_Release(&views[k]);
    PyMem_Free(views);
    PyMem_Free(jobs);
    Py_DECREF(pk_seq);
    Py_DECREF(msg_seq);
    Py_DECREF(sig_seq);
    return out;
}

/* pack_words(pubkeys, msgs, sigs, bucket) -> (a, r, s, m) bytes objects.
 *
 * Host packing for the device-hash verify path: each output is the raw
 * memory of an (8, bucket) uint32 word-major array — out[w*B + i] is the
 * little-endian 32-bit word at encoding[i][4w..4w+3]; lanes beyond n are
 * zero. This replaces the Python/numpy packer (ed25519_jax.py
 * precompute_batch_device: per-item bytes() + b"".join + frombuffer +
 * transpose-copy), which was the measured bottleneck of the streaming
 * pipeline (host pack rate < kernel rate, so the depth-2 overlap starved
 * the device). Semantics match the Python path exactly: every pk and msg
 * must be 32 bytes and every sig 64, else ValueError.
 *
 * The fill loops run with the GIL RELEASED (buffers captured first), so a
 * node's transport threads keep moving while a 64k-lane batch packs.
 */
static int fill_words(uint32_t *dst, Py_ssize_t B, Py_ssize_t n,
                      const unsigned char **src, Py_ssize_t off,
                      Py_ssize_t nwords) {
    for (Py_ssize_t i = 0; i < n; i++) {
        const unsigned char *e = src[i] + off;
        for (Py_ssize_t w = 0; w < nwords; w++) {
            dst[w * B + i] = (uint32_t)e[4 * w]
                             | ((uint32_t)e[4 * w + 1] << 8)
                             | ((uint32_t)e[4 * w + 2] << 16)
                             | ((uint32_t)e[4 * w + 3] << 24);
        }
    }
    return 0;
}

static PyObject *pack_words(PyObject *self, PyObject *args) {
    PyObject *pks, *msgs, *sigs;
    Py_ssize_t bucket;
    if (!PyArg_ParseTuple(args, "OOOn", &pks, &msgs, &sigs, &bucket))
        return NULL;
    PyObject *seqs[3] = {NULL, NULL, NULL};
    PyObject *result = NULL;
    Py_buffer *views = NULL;
    const unsigned char **ptrs = NULL;
    Py_ssize_t n_views = 0;
    PyObject *outs[4] = {NULL, NULL, NULL, NULL};

    seqs[0] = PySequence_Fast(pks, "pubkeys must be a sequence");
    seqs[1] = PySequence_Fast(msgs, "msgs must be a sequence");
    seqs[2] = PySequence_Fast(sigs, "sigs must be a sequence");
    if (seqs[0] == NULL || seqs[1] == NULL || seqs[2] == NULL)
        goto done;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seqs[0]);
    if (PySequence_Fast_GET_SIZE(seqs[1]) != n
        || PySequence_Fast_GET_SIZE(seqs[2]) != n) {
        PyErr_SetString(PyExc_ValueError,
                        "pubkeys, msgs and sigs must have equal length");
        goto done;
    }
    if (bucket < n) {
        PyErr_SetString(PyExc_ValueError, "bucket smaller than batch");
        goto done;
    }
    if (n > 0) {
        views = PyMem_Calloc((size_t)n * 3, sizeof(Py_buffer));
        ptrs = PyMem_Calloc((size_t)n * 3, sizeof(unsigned char *));
        if (views == NULL || ptrs == NULL) {
            PyErr_NoMemory();
            goto done;
        }
    }
    static const Py_ssize_t want_len[3] = {32, 32, 64};
    static const char *len_err[3] = {
        "pubkeys must be 32 bytes",
        "device-hash path requires 32-byte messages",
        "sigs must be 64 bytes",
    };
    for (Py_ssize_t i = 0; i < n; i++) {
        for (int k = 0; k < 3; k++) {
            PyObject *item = PySequence_Fast_GET_ITEM(seqs[k], i);
            if (PyObject_GetBuffer(item, &views[n_views],
                                   PyBUF_SIMPLE) != 0)
                goto done; /* propagate (TypeError), matching bytes(m) */
            n_views++;
            if (views[n_views - 1].len != want_len[k]) {
                PyErr_SetString(PyExc_ValueError, len_err[k]);
                goto done;
            }
            ptrs[k * n + i] = views[n_views - 1].buf;
        }
    }
    /* 4 outputs: A (pk), R (sig[:32]), S (sig[32:]), M (msg) — each
     * 8 words x bucket lanes, zero-padded beyond n. */
    for (int k = 0; k < 4; k++) {
        outs[k] = PyBytes_FromStringAndSize(NULL, 8 * bucket * 4);
        if (outs[k] == NULL)
            goto done;
        memset(PyBytes_AS_STRING(outs[k]), 0, (size_t)(8 * bucket * 4));
    }
    {
        uint32_t *a_w = (uint32_t *)PyBytes_AS_STRING(outs[0]);
        uint32_t *r_w = (uint32_t *)PyBytes_AS_STRING(outs[1]);
        uint32_t *s_w = (uint32_t *)PyBytes_AS_STRING(outs[2]);
        uint32_t *m_w = (uint32_t *)PyBytes_AS_STRING(outs[3]);
        const unsigned char **pk_p = ptrs;
        const unsigned char **msg_p = ptrs + n;
        const unsigned char **sig_p = ptrs + 2 * n;
        Py_BEGIN_ALLOW_THREADS
        fill_words(a_w, bucket, n, pk_p, 0, 8);
        fill_words(r_w, bucket, n, sig_p, 0, 8);
        fill_words(s_w, bucket, n, sig_p, 32, 8);
        fill_words(m_w, bucket, n, msg_p, 0, 8);
        Py_END_ALLOW_THREADS
    }
    result = PyTuple_Pack(4, outs[0], outs[1], outs[2], outs[3]);

done:
    for (Py_ssize_t k = 0; k < n_views; k++)
        PyBuffer_Release(&views[k]);
    PyMem_Free(views);
    PyMem_Free(ptrs);
    for (int k = 0; k < 4; k++)
        Py_XDECREF(outs[k]);
    Py_XDECREF(seqs[0]);
    Py_XDECREF(seqs[1]);
    Py_XDECREF(seqs[2]);
    return result;
}

/* sign_many(seeds, msgs) -> bytes (64 bytes of signature per job).
 *
 * The INGEST mirror of verify_many: columnar layout (seeds and msgs are
 * single contiguous n*32-byte buffers — the batch-sign packer hands the
 * whole corpus over in two allocations, no per-item object traffic) and
 * the sign loop runs with the GIL RELEASED, fanned across the same
 * pthread budget as verify. Ed25519 signing is RFC 8032-deterministic,
 * so libcrypto's output here is byte-identical to both fast_ed25519.sign
 * and the ref_ed25519 oracle; there is no accept-set subtlety like
 * verify's S < L corner. Messages are fixed at 32 bytes because every
 * message on this path is a WireTransaction Merkle id; anything
 * variable-length takes the Python fallback. A libcrypto failure on any
 * job (cannot happen for well-formed 32-byte seeds; belt-and-braces for
 * allocation failure) raises, and the caller re-signs the batch on the
 * Python path — a wrong-or-missing signature never leaves this module
 * silently. */
typedef struct {
    const unsigned char *seeds;
    const unsigned char *msgs;
    unsigned char *sigs;
    Py_ssize_t lo, hi;
    int failed;
} sign_span_t;

static void *sign_worker(void *arg) {
    sign_span_t *s = (sign_span_t *)arg;
    for (Py_ssize_t i = s->lo; i < s->hi; i++) {
        EVP_PKEY *pkey = EVP_PKEY_new_raw_private_key(
            EVP_PKEY_ED25519, NULL, s->seeds + 32 * i, 32);
        if (pkey == NULL) {
            s->failed = 1;
            return NULL;
        }
        EVP_MD_CTX *ctx = EVP_MD_CTX_new();
        if (ctx == NULL) {
            EVP_PKEY_free(pkey);
            s->failed = 1;
            return NULL;
        }
        size_t siglen = 64;
        int ok = EVP_DigestSignInit(ctx, NULL, NULL, NULL, pkey) == 1
                 && EVP_DigestSign(ctx, s->sigs + 64 * i, &siglen,
                                   s->msgs + 32 * i, 32) == 1
                 && siglen == 64;
        EVP_MD_CTX_free(ctx);
        EVP_PKEY_free(pkey);
        if (!ok) {
            s->failed = 1;
            return NULL;
        }
    }
    return NULL;
}

static PyObject *sign_many(PyObject *self, PyObject *args) {
    Py_buffer seeds, msgs;
    if (!PyArg_ParseTuple(args, "y*y*", &seeds, &msgs))
        return NULL;
    PyObject *out = NULL;
    if (seeds.len % 32 != 0 || msgs.len != seeds.len) {
        PyErr_SetString(PyExc_ValueError,
                        "seeds and msgs must be equal-length multiples "
                        "of 32 bytes (columnar n*32 layout)");
        goto done;
    }
    Py_ssize_t n = seeds.len / 32;
    out = PyBytes_FromStringAndSize(NULL, n * 64);
    if (out == NULL)
        goto done;
    if (n > 0) {
        unsigned char *sig_buf = (unsigned char *)PyBytes_AS_STRING(out);
        const unsigned char *seed_buf = (const unsigned char *)seeds.buf;
        const unsigned char *msg_buf = (const unsigned char *)msgs.buf;
        int nthreads = n >= PAR_MIN ? (int)(n / (PAR_MIN / 2)) : 1;
        if (nthreads > PAR_MAX_THREADS)
            nthreads = PAR_MAX_THREADS;
        long cores = sysconf(_SC_NPROCESSORS_ONLN);
        if (cores > 0 && nthreads > cores)
            nthreads = (int)cores;
        sign_span_t spans[PAR_MAX_THREADS];
        pthread_t tids[PAR_MAX_THREADS];
        int started = 0, nspans = 0;
        Py_ssize_t chunk = (n + nthreads - 1) / nthreads;
        Py_BEGIN_ALLOW_THREADS
        for (int t = 0; t < nthreads; t++) {
            Py_ssize_t lo = (Py_ssize_t)t * chunk;
            Py_ssize_t hi = lo + chunk < n ? lo + chunk : n;
            if (lo >= hi)
                break;
            spans[nspans].seeds = seed_buf;
            spans[nspans].msgs = msg_buf;
            spans[nspans].sigs = sig_buf;
            spans[nspans].lo = lo;
            spans[nspans].hi = hi;
            spans[nspans].failed = 0;
            if (t < nthreads - 1 && hi < n
                && pthread_create(&tids[started], NULL, sign_worker,
                                  &spans[nspans]) == 0)
                started++;
            else
                sign_worker(&spans[nspans]);
            nspans++;
        }
        for (int t = 0; t < started; t++)
            pthread_join(tids[t], NULL);
        Py_END_ALLOW_THREADS
        for (int t = 0; t < nspans; t++) {
            if (spans[t].failed) {
                Py_DECREF(out);
                out = NULL;
                PyErr_SetString(PyExc_ValueError,
                                "libcrypto Ed25519 sign failed");
                goto done;
            }
        }
    }

done:
    PyBuffer_Release(&seeds);
    PyBuffer_Release(&msgs);
    return out;
}

static PyMethodDef methods[] = {
    {"sign_many", sign_many, METH_VARARGS,
     "sign_many(seeds, msgs) -> sigs: columnar batch Ed25519 sign via "
     "libcrypto, GIL released; n*32-byte seed and 32-byte-message "
     "buffers in, n*64 bytes of deterministic RFC 8032 signatures out."},
    {"verify_many", verify_many, METH_VARARGS,
     "Batch Ed25519 verify via libcrypto, GIL released; returns one 0/1 "
     "byte per job. Accept-fast only: rejects need an oracle re-check."},
    {"pack_words", pack_words, METH_VARARGS,
     "pack_words(pks, msgs, sigs, bucket) -> (a, r, s, m) raw (8, bucket) "
     "uint32 word arrays for the device-hash verify path; GIL released "
     "during the fill."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "_cverify",
    "Batched libcrypto Ed25519 verification (GIL-free hot loop).",
    -1, methods,
};

PyMODINIT_FUNC PyInit__cverify(void) { return PyModule_Create(&module); }
