"""L4/L5 node runtime: services, messaging, state machine manager, notaries,
node assembly."""
