"""Node configuration + the file-based network map.

Capability match for the reference's HOCON config system (reference:
node/src/main/kotlin/net/corda/node/services/config/NodeConfiguration.kt:17-79,
reference.conf defaults, per-node dev configs) re-based on TOML (stdlib
tomllib), and for the network-map directory the reference serves over the wire
(node/.../network/NetworkMapService.kt:37-60) re-based — first stage — on a
shared JSON file nodes register into (SURVEY.md §7 stage 5: "static
file/directory service first, dynamic later").
"""

from __future__ import annotations

import json
import os
import tempfile

try:
    import tomllib
except ModuleNotFoundError:  # Python < 3.11: the API-identical backport
    import tomli as tomllib
from dataclasses import dataclass, field
from pathlib import Path

from ..crypto.composite import CompositeKey
from ..crypto.party import Party
from .messaging.tcp import TcpAddress
from .services.api import NodeInfo, ServiceInfo, ServiceType


@dataclass(frozen=True)
class BatchConfig:
    """The max-wait micro-batch policy protecting notarisation p99
    (SURVEY.md §7 stage 6: flush at N sigs or T ms, whichever first)."""

    max_sigs: int = 4096
    max_wait_ms: float = 2.0
    # Round coalescing: after the first inbound message wakes a round, keep
    # draining for this long before processing. Each round costs a sqlite
    # commit (fsync), an ACK frame per connection, and (on a raft leader)
    # an AppendEntries broadcast — a small accumulation window amortises
    # all three across the burst. 0 = wake-per-message (lowest latency).
    coalesce_ms: float = 0.0
    # Async verify pipeline (crypto/async_verify.py): the run loop submits
    # accumulated batches to a feeder thread and keeps serving Raft/
    # messages/checkpoints while the verifier runs; False restores the
    # in-round synchronous flush.
    async_verify: bool = True
    # Bounded in-flight submitted batches (2 = double buffering: one batch
    # verifying, one filling).
    async_depth: int = 2
    # Verification sidecar (crypto/sidecar.py): address of a host-local
    # device-owning verify server — unix socket path or host:port. All node
    # processes on the host feed the same server so batches coalesce ACROSS
    # processes. "" disables it: verification routes exactly as before.
    sidecar: str = ""
    # Client-side round-trip deadline for one sidecar batch; a miss
    # degrades the node to its local host tier (cooldown re-probe re-opens).
    sidecar_deadline_ms: float = 2000.0
    # Mesh width of the host's sidecar (informational on the client side:
    # stamped into node_metrics so harnesses can attribute which mesh
    # served a run; the server's --devices flag is authoritative). 0 =
    # unknown/single-device.
    sidecar_devices: int = 0
    # Federated verify plane (crypto/federation.py): comma-separated
    # addresses of PER-HOST sidecar servers. When set, the node routes
    # verify batches across every listed host by queue depth + QoS lane
    # (hedged re-dispatch, per-host degrade/re-admit) instead of feeding
    # one host-local server; takes precedence over `sidecar`. "" disables
    # federation: verification routes exactly as before.
    federation_hosts: str = ""


@dataclass(frozen=True)
class RaftConfig:
    """Consensus hot-path policy (services/raft.py commit pipeline)."""

    # Group commit: the leader merges every PutAllCommand submitted in a
    # scheduling round into ONE batched log entry (PutAllBatch) — one log
    # append/fsync, one AppendEntries slot, one apply pass for the whole
    # burst, with per-request conflict isolation inside the batch. False
    # restores the one-command-per-entry path.
    group_commit: bool = True
    # Pipelined replication: how many log entries may be streamed to a
    # follower beyond its acked match position before the leader pauses
    # and probes with heartbeats (per-peer in-flight window).
    pipeline_window: int = 1024
    # Entries per AppendEntries frame when streaming a tail.
    append_chunk: int = 256
    # Pipelined commit plane (round 18): overlap consecutive rounds. The
    # leader seals round N+1 while round N is still replicating (mid-round
    # seals ride the pipeline_window), and committed-entry apply + client
    # reply construction detach onto a dedicated executor thread fed by a
    # bounded queue. False restores the serial seal→replicate→apply→reply
    # loop, bit-identical to the pre-pipeline ledger.
    pipeline: bool = True
    # Bound of the commit queue feeding the apply executor, in log
    # entries. When the queue is full the leader sheds NEW submissions
    # with a retryable OverloadedError("commit") instead of growing an
    # unbounded backlog (committed-but-unapplied entries are durable in
    # the log and drain as the executor catches up). 0 disables the
    # executor even when pipeline=true (inline apply, pipelined seals).
    apply_queue_depth: int = 4096
    # Columnar fast path: apply a run of PutAll commands from one batch
    # with set-wide conflict/reservation SELECTs and executemany inserts
    # (plus the native _ccommit CRC32C batch helper when built) instead
    # of per-ref statements. Byte-identical rows; False falls back to the
    # per-command apply.
    commit_many: bool = True
    # Partition hardening (round 20): pre-vote canvass before any real
    # election (a candidate probes at term+1 WITHOUT incrementing its
    # persisted term, so a partitioned rejoiner cannot depose a healthy
    # leader) plus check-quorum leader step-down (a leader that hears no
    # quorum for a full election window stops answering as leader).
    # False (the default) leaves election behaviour bit-identical to the
    # pre-partition-plane tree.
    prevote: bool = False


@dataclass(frozen=True)
class QosConfig:
    """QoS plane policy (corda_tpu/qos): priority lanes, deadlines, and
    admission control. ``enabled = false`` (the default) leaves the plane
    disarmed — every touch point short-circuits on one attribute check and
    behaviour is bit-identical to the pre-QoS tree."""

    enabled: bool = False
    # Default interactive SLO: flows started without an explicit deadline
    # get admitted_at + slo_ms. The sweep bench judges p99 against this.
    slo_ms: float = 50.0
    # How long before an interactive deadline the queueing points stop
    # coalescing and flush (SMM verify micro-batch, sidecar scheduler,
    # Raft group-commit round).
    deadline_guard_ms: float = 5.0
    # Anti-starvation: with both lanes runnable, every Nth pump pick takes
    # the oldest bulk step.
    bulk_every: int = 4
    # Admission token buckets, per lane (requests/s + burst; rate 0 =
    # unlimited). Bulk additionally sheds above the queue watermark.
    interactive_rate: float = 0.0
    interactive_burst: float = 32.0
    bulk_rate: float = 0.0
    bulk_burst: float = 32.0
    # Runnable-backlog ceiling above which bulk is shed; 0 disables.
    queue_watermark: int = 0


@dataclass(frozen=True)
class DurabilityConfig:
    """Durability plane policy (node/services/integrity.py).

    ``scrub_enabled = false`` (the default) leaves the online scrubber off —
    write-path CRC framing is always on (one crc32c per insert), but
    disarmed nodes spend nothing on background verification and behaviour
    is otherwise bit-identical to the pre-durability tree. Boot fsck is a
    separate tool (``python -m corda_tpu.tools.fsck``), not a config knob.
    """

    scrub_enabled: bool = False
    # Scrubber row-rate ceiling: the pass sleeps so it never verifies more
    # than this many rows per second (low-priority by construction).
    scrub_rows_per_s: float = 500.0
    # Idle wait between full-table scrub passes.
    scrub_interval_s: float = 5.0


@dataclass(frozen=True)
class VaultConfig:
    """Vault engine selection (node/services/vault.py).

    ``indexed = false`` (the default) keeps the in-memory
    NodeVaultService — bit-identical to the pre-vault-plane tree.
    ``indexed = true`` (or CORDA_TPU_VAULT_INDEXED=1) arms the sqlite
    IndexedVaultService: durable vault_states rows with covering
    indexes, watermark incremental boot, O(1) balance aggregates."""

    indexed: bool = False
    # Soft-lock reservation TTL for select_coins: how long a selected
    # coin stays shadowed from other flows before a crashed/abandoned
    # selection re-admits it.
    softlock_ttl_s: float = 5.0
    # Transactions per notify batch during watermark rebuild (bounds
    # boot memory, never the full ledger at once).
    rebuild_batch: int = 512


@dataclass(frozen=True)
class ShardConfig:
    """Sharded-notary topology (services/sharding.py).

    The input-state space is partitioned by StateRef hash across `count`
    independent Raft groups; `groups[g]` lists the member names of group g
    (a member's own raft_cluster is exactly its group). Reservations taken
    by the cross-shard two-phase coordinator expire `reserve_ttl_s` seconds
    after the coordinator's issued_at stamp — judged stamp-vs-stamp in the
    replicated state machine, never against a replica's local clock.
    """

    count: int = 1
    groups: tuple[tuple[str, ...], ...] = ()
    reserve_ttl_s: float = 15.0


# One env var carries any number of per-knob config overrides to spawned
# node processes (autotune sweep candidates, driver env_extra): a JSON
# object deep-merged over the parsed TOML in NodeConfig.load. Keys may
# be nested ({"raft": {"pipeline_window": 2048}}) or dotted
# ("raft.pipeline_window": 2048 — the autotune knob-name spelling);
# unknown keys still fail from_dict's known-keys validation, so a typo'd
# overlay crashes the node at boot instead of silently tuning nothing.
OVERLAY_ENV = "CORDA_TPU_CONFIG_OVERLAY"


def _deep_merge(base: dict, overlay: dict) -> dict:
    """A new dict: ``overlay`` wins, nested dicts merge key-wise."""
    out = dict(base)
    for key, value in overlay.items():
        if isinstance(value, dict) and isinstance(out.get(key), dict):
            out[key] = _deep_merge(out[key], value)
        else:
            out[key] = value
    return out


def config_overlay_from_env(env=None) -> dict:
    """The parsed, nested overlay from ``OVERLAY_ENV`` (empty dict when
    unset). Malformed JSON raises — the overlay is machine-written, and
    a candidate that silently ran defaults would corrupt a sweep."""
    raw = (env if env is not None else os.environ).get(OVERLAY_ENV, "")
    if not raw:
        return {}
    overlay = json.loads(raw)
    if not isinstance(overlay, dict):
        raise ValueError(
            f"{OVERLAY_ENV} must be a JSON object, got "
            f"{type(overlay).__name__}")
    nested: dict = {}
    for key, value in overlay.items():
        if "." in key:
            section, sub = key.split(".", 1)
            entry = nested.setdefault(section, {})
            if not isinstance(entry, dict):
                raise ValueError(
                    f"{OVERLAY_ENV}: {key!r} conflicts with scalar "
                    f"{section!r}")
            entry[sub] = value
        elif isinstance(value, dict) and isinstance(nested.get(key), dict):
            nested[key] = _deep_merge(nested[key], value)
        else:
            nested[key] = value
    return nested


@dataclass(frozen=True)
class NodeConfig:
    name: str
    base_dir: Path
    host: str = "127.0.0.1"
    port: int = 0  # 0 = ephemeral (the netmap records the real port)
    # none | simple | validating | raft-simple | raft-validating
    notary: str = "none"
    # For raft-* notaries: the names of ALL cluster members (incl. this node).
    raft_cluster: tuple[str, ...] = ()
    network_map: Path | None = None  # shared netmap file (bootstrap)
    map_service: bool = False  # host the wire directory service on this node
    map_node: str | None = None  # use the named node's directory service
    tls: bool = False  # mutual TLS on the transport (dev CA auto-generated)
    web_port: int | None = None  # HTTP API (status/metrics/attachments)
    verifier: str = "cpu"  # cpu | jax | jax-shadow | jax-sharded
    batch: BatchConfig = field(default_factory=BatchConfig)
    raft: RaftConfig = field(default_factory=RaftConfig)
    qos: QosConfig = field(default_factory=QosConfig)
    durability: DurabilityConfig = field(default_factory=DurabilityConfig)
    vault: VaultConfig = field(default_factory=VaultConfig)
    # Sharded notary: when set (count > 1 or groups non-empty), this raft-*
    # notary member is one shard of a partitioned uniqueness service and
    # uses the ShardedUniquenessProvider two-phase coordinator.
    notary_shards: ShardConfig | None = None
    # RPC users: ({"username","password","permissions": [flow names]|["ALL"]},)
    rpc_users: tuple = ()
    # CorDapp modules: imported at node start so their @register_flow /
    # @register decorators run; a module-level install(node) hook, if
    # present, wires responders/services (the reference's CordaPluginRegistry
    # ServiceLoader capability, AbstractNode.kt:170-173,340-352).
    cordapps: tuple[str, ...] = ()

    @staticmethod
    def load(path: str | os.PathLike) -> "NodeConfig":
        """Parse a TOML config file; relative paths resolve against its
        dir. The ``CORDA_TPU_CONFIG_OVERLAY`` env (a JSON object, set by
        the autotune controller / testing driver for spawned processes)
        deep-merges over the parsed TOML before validation, so one env
        var carries any number of per-knob overrides to every child
        process. Precedence, lowest to highest: TOML file < overlay <
        the explicit per-subsystem CORDA_TPU_* env vars read at their
        use sites (e.g. CORDA_TPU_FEDERATION still outranks an
        overlay-set [batch] sidecar in _select_batch_verifier)."""
        path = Path(path)
        with open(path, "rb") as f:
            raw = tomllib.load(f)
        overlay = config_overlay_from_env()
        if overlay:
            raw = _deep_merge(raw, overlay)
        return NodeConfig.from_dict(raw, default_dir=path.parent)

    @staticmethod
    def from_dict(raw: dict, default_dir: Path | None = None) -> "NodeConfig":
        base = Path(raw.get("base_dir", default_dir or "."))
        known = {"name", "base_dir", "host", "port", "notary", "raft_cluster",
                 "network_map", "map_service", "map_node", "tls", "web_port",
                 "verifier", "batch", "raft", "qos", "durability", "vault",
                 "rpc_users", "cordapps", "notary_shards"}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        notary = raw.get("notary", "none")
        valid_notary = ("none", "simple", "validating", "raft-simple",
                        "raft-validating")
        if notary not in valid_notary:
            raise ValueError(
                f"notary must be one of {'|'.join(valid_notary)}, got {notary!r}")
        if notary.startswith("raft") and not raw.get("raft_cluster"):
            raise ValueError("raft-* notaries need a raft_cluster name list")
        nm = raw.get("network_map")
        batch = raw.get("batch", {})
        raft = raw.get("raft", {})
        qos = raw.get("qos", {})
        durability = raw.get("durability", {})
        vault = raw.get("vault", {})
        shards_raw = raw.get("notary_shards")
        shards = None
        if shards_raw is not None:
            groups = tuple(tuple(g) for g in shards_raw.get("groups", ()))
            count = int(shards_raw.get("count", len(groups) or 1))
            # The groups list may be LONGER than count: groups beyond count
            # are pending split targets, booted ahead of a live reshard
            # (they own no keys until an epoch activates them). Shorter is
            # still a misconfiguration — some keyspace would have no group.
            if groups and len(groups) < count:
                raise ValueError(
                    f"notary_shards: count={count} but "
                    f"{len(groups)} groups")
            if not notary.startswith("raft"):
                raise ValueError("notary_shards requires a raft-* notary")
            shards = ShardConfig(
                count=count,
                groups=groups,
                reserve_ttl_s=float(shards_raw.get("reserve_ttl_s", 15.0)),
            )
        return NodeConfig(
            name=raw["name"],
            base_dir=base,
            host=raw.get("host", "127.0.0.1"),
            port=int(raw.get("port", 0)),
            notary=notary,
            raft_cluster=tuple(raw.get("raft_cluster", ())),
            network_map=(base / nm if nm and not os.path.isabs(nm) else
                         Path(nm) if nm else None),
            map_service=bool(raw.get("map_service", False)),
            map_node=raw.get("map_node"),
            tls=bool(raw.get("tls", False)),
            web_port=(int(raw["web_port"])
                      if raw.get("web_port") is not None else None),
            verifier=raw.get("verifier", "cpu"),
            batch=BatchConfig(
                max_sigs=int(batch.get("max_sigs", 4096)),
                max_wait_ms=float(batch.get("max_wait_ms", 2.0)),
                coalesce_ms=float(batch.get("coalesce_ms", 0.0)),
                async_verify=bool(batch.get("async_verify", True)),
                async_depth=int(batch.get("async_depth", 2)),
                sidecar=str(batch.get("sidecar", "")),
                sidecar_deadline_ms=float(
                    batch.get("sidecar_deadline_ms", 2000.0)),
                sidecar_devices=int(batch.get("sidecar_devices", 0)),
                # Accept a TOML list or the comma-joined string the env
                # var uses; normalise to the string form.
                federation_hosts=(
                    ",".join(str(h) for h in batch["federation_hosts"])
                    if isinstance(batch.get("federation_hosts"),
                                  (list, tuple))
                    else str(batch.get("federation_hosts", ""))),
            ),
            raft=RaftConfig(
                group_commit=bool(raft.get("group_commit", True)),
                pipeline_window=int(raft.get("pipeline_window", 1024)),
                append_chunk=int(raft.get("append_chunk", 256)),
                pipeline=bool(raft.get("pipeline", True)),
                apply_queue_depth=int(raft.get("apply_queue_depth", 4096)),
                commit_many=bool(raft.get("commit_many", True)),
                prevote=bool(raft.get("prevote", False)),
            ),
            qos=QosConfig(
                enabled=bool(qos.get("enabled", False)),
                slo_ms=float(qos.get("slo_ms", 50.0)),
                deadline_guard_ms=float(qos.get("deadline_guard_ms", 5.0)),
                bulk_every=int(qos.get("bulk_every", 4)),
                interactive_rate=float(qos.get("interactive_rate", 0.0)),
                interactive_burst=float(qos.get("interactive_burst", 32.0)),
                bulk_rate=float(qos.get("bulk_rate", 0.0)),
                bulk_burst=float(qos.get("bulk_burst", 32.0)),
                queue_watermark=int(qos.get("queue_watermark", 0)),
            ),
            durability=DurabilityConfig(
                scrub_enabled=bool(durability.get("scrub_enabled", False)),
                scrub_rows_per_s=float(
                    durability.get("scrub_rows_per_s", 500.0)),
                scrub_interval_s=float(
                    durability.get("scrub_interval_s", 5.0)),
            ),
            vault=VaultConfig(
                indexed=bool(vault.get("indexed", False)),
                softlock_ttl_s=float(vault.get("softlock_ttl_s", 5.0)),
                rebuild_batch=int(vault.get("rebuild_batch", 512)),
            ),
            notary_shards=shards,
            rpc_users=tuple(
                dict(u) for u in raw.get("rpc_users", ())),
            cordapps=tuple(raw.get("cordapps", ())),
        )


# ---------------------------------------------------------------------------
# File-based network map
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class NetMapEntry:
    name: str
    host: str
    port: int
    owning_key_b58: str  # CompositeKey.to_base58_string() (whole tree)
    services: tuple[str, ...] = ()

    def party(self) -> Party:
        return Party(self.name, CompositeKey.parse_from_base58(self.owning_key_b58))

    def node_info(self) -> NodeInfo:
        return NodeInfo(
            address=TcpAddress(self.host, self.port),
            legal_identity=self.party(),
            advertised_services=tuple(
                ServiceInfo(ServiceType(s)) for s in self.services),
        )


def _encode_owning_key(key: CompositeKey) -> str:
    return key.to_base58_string()


def netmap_register(path: str | os.PathLike, name: str, host: str, port: int,
                    owning_key: CompositeKey,
                    services: tuple[str, ...] = ()) -> None:
    """Add/replace this node's entry (atomic file replace, same-name entries
    collapse). The load-modify-replace runs under an flock on a sidecar
    lock file: nodes in a cluster boot concurrently, and without the lock
    two simultaneous registrations each read the map missing the other and
    the second replace silently drops the first node's entry — that node
    stays unreachable for its whole life (registration is boot-only; the
    periodic refresh only reads)."""
    lock = open(os.path.abspath(os.fspath(path)) + ".lock", "a")
    try:
        try:
            import fcntl
            fcntl.flock(lock, fcntl.LOCK_EX)
        except ImportError:  # non-POSIX: keep the old last-writer-wins
            pass
        entries = netmap_load(path)
        entries = [e for e in entries if e.name != name]
        entries.append(NetMapEntry(name, host, port,
                                   _encode_owning_key(owning_key),
                                   tuple(services)))
        payload = json.dumps([e.__dict__ | {"services": list(e.services)}
                              for e in sorted(entries, key=lambda e: e.name)],
                             indent=1)
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(os.path.abspath(path)))
        try:
            with os.fdopen(fd, "w") as f:
                f.write(payload)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
    finally:
        lock.close()  # closing the fd releases the flock


def netmap_load(path: str | os.PathLike) -> list[NetMapEntry]:
    try:
        with open(path) as f:
            raw = json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return []
    return [NetMapEntry(e["name"], e["host"], e["port"], e["owning_key_b58"],
                        tuple(e.get("services", ()))) for e in raw]
