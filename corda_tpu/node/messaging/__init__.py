"""L5a messaging: transport-neutral API, deterministic in-memory fake, TCP."""

from .api import Message, MessagingService, TopicSession, DEFAULT_SESSION_ID  # noqa: F401
from .inmem import InMemoryMessagingNetwork  # noqa: F401
