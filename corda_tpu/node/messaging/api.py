"""Transport-neutral messaging API.

Capability match for the reference's messaging abstraction (reference:
core/src/main/kotlin/net/corda/core/messaging/Messaging.kt): topic+session
addressed messages between opaque recipients, handler registration, and
at-least-once delivery with app-level dedupe provided by implementations
(reference: node/.../messaging/NodeMessagingClient.kt:102-113).
"""

from __future__ import annotations

import itertools
import os
from dataclasses import dataclass, field
from typing import Any, Callable

DEFAULT_SESSION_ID = 0

_uuid_counter = itertools.count(1)


def fresh_message_id() -> bytes:
    """A unique message id for dedupe (UUID-equivalent)."""
    return os.urandom(12) + next(_uuid_counter).to_bytes(4, "big")


@dataclass(frozen=True, order=True)
class TopicSession:
    """A (topic, session) address for dispatch (reference: Messaging.kt
    TopicSession)."""

    topic: str
    session_id: int = DEFAULT_SESSION_ID

    def is_blank(self) -> bool:
        return not self.topic and self.session_id == DEFAULT_SESSION_ID

    def __str__(self) -> str:
        return f"{self.topic}.{self.session_id}"


@dataclass(frozen=True)
class Message:
    """A sealed envelope: opaque payload plus routing metadata."""

    topic_session: TopicSession
    data: bytes
    unique_id: bytes
    sender: Any = None  # transport address of the origin
    # Tracing context (obs/trace.py): (trace_id, span_id) of the sending
    # flow, or None when tracing is disarmed / the sender had no context.
    # Transports stamp it on send only when obs.ACTIVE is armed — the
    # disabled path never grows the envelope.
    trace: Any = None
    # QoS context (qos/context.py): the sending flow's QosContext, or None
    # when the QoS plane is disarmed / the sender carried none. Same
    # arming discipline as trace: disarmed, the envelope never grows.
    qos: Any = None


class MessageHandlerRegistration:
    pass


class MessagingService:
    """The API nodes and services program against (Messaging.kt:23-90)."""

    @property
    def my_address(self) -> Any:
        raise NotImplementedError

    def send(self, topic_session: TopicSession, data: bytes, to: Any) -> None:
        raise NotImplementedError

    def add_message_handler(
        self, topic: str, session_id: int, callback: Callable[[Message], None]
    ) -> MessageHandlerRegistration:
        raise NotImplementedError

    def remove_message_handler(self, registration: MessageHandlerRegistration) -> None:
        raise NotImplementedError

    def stop(self) -> None:
        pass
