"""Deterministic in-memory messaging network — the test-tier transport.

Capability match for the reference's InMemoryMessagingNetwork (reference:
test-utils/src/main/kotlin/net/corda/testing/node/InMemoryMessagingNetwork.kt:29-117):
the load-bearing testing idea the survey calls out — multi-node protocols run
in one process with *manually pumped*, deterministic message delivery, plus:

  * durable queues: messages to peers with no registered handler wait
    (pendingRedelivery), mirroring store-and-forward tolerance of down peers
    (InMemoryMessagingNetwork.kt:59-63);
  * per-endpoint dedupe on message unique ids (at-least-once semantics);
  * an optional latency calculator and a sent-message observer feed
    (simulation + network-visualiser capability).
"""

from __future__ import annotations

import heapq
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from .api import (
    DEFAULT_SESSION_ID,
    Message,
    MessageHandlerRegistration,
    MessagingService,
    TopicSession,
    fresh_message_id,
)
from ...obs import trace as _obs
from ...qos import context as _qos
from ...testing import faults as _faults


@dataclass(frozen=True, order=True)
class InMemoryAddress:
    id: int
    description: str = ""

    def __str__(self) -> str:
        return self.description or f"node:{self.id}"


@dataclass(frozen=True)
class SentMessage:
    """Observer record of one network transmission."""

    sender: InMemoryAddress
    recipient: InMemoryAddress
    message: Message


@dataclass
class _Handler(MessageHandlerRegistration):
    topic: str
    session_id: int
    callback: Callable[[Message], None]


class InMemoryMessagingNetwork:
    """The shared medium. Create endpoints with create_node_messaging()."""

    def __init__(self, latency_calculator: Callable[..., int] | None = None):
        self._next_id = 1
        self.endpoints: dict[InMemoryAddress, "InMemoryMessaging"] = {}
        # Store-and-forward: messages for crashed/stopped endpoints wait here
        # keyed by address until a new endpoint reattaches (the durable
        # per-peer queue capability of ArtemisMessagingServer.kt:105-140).
        self._durable: dict[InMemoryAddress, deque[Message]] = {}
        # Min-heap of (deliver_at_tick, seq, recipient, message) — with no
        # latency calculator deliver_at_tick is always 0 → pure FIFO by seq.
        self._in_flight: list[tuple[int, int, InMemoryAddress, Message]] = []
        self._seq = 0
        self._tick = 0
        self.latency_calculator = latency_calculator
        self.sent_messages: list[SentMessage] = []
        self._send_observers: list[Callable[[SentMessage], None]] = []

    # -- topology ----------------------------------------------------------

    def create_node_messaging(self, description: str = "") -> "InMemoryMessaging":
        addr = InMemoryAddress(self._next_id, description or f"node:{self._next_id}")
        self._next_id += 1
        endpoint = InMemoryMessaging(self, addr)
        self.endpoints[addr] = endpoint
        return endpoint

    def reattach(self, address: InMemoryAddress) -> "InMemoryMessaging":
        """Bind a fresh endpoint to an existing address after a crash; durably
        queued messages will deliver to it once its handlers register."""
        old = self.endpoints.get(address)
        if old is not None:
            old.running = False
        endpoint = InMemoryMessaging(self, address)
        self.endpoints[address] = endpoint
        # Salvage anything the dead endpoint had not dispatched to a handler.
        if old is not None and old._pending:
            queue = self._durable.setdefault(address, deque())
            queue.extend(old._pending)
            old._pending.clear()
        queue = self._durable.pop(address, None)
        if queue:
            for message in queue:
                heapq.heappush(self._in_flight, (self._tick, self._seq, address, message))
                self._seq += 1
        return endpoint

    def observe_sends(self, observer: Callable[[SentMessage], None]) -> None:
        self._send_observers.append(observer)

    # -- transmission ------------------------------------------------------

    def _transmit(self, sender: InMemoryAddress, recipient: InMemoryAddress, message: Message) -> None:
        if recipient not in self.endpoints:
            raise KeyError(f"unknown recipient {recipient}")
        delay = 0
        if self.latency_calculator is not None:
            delay = int(self.latency_calculator(sender, recipient))
        record = SentMessage(sender, recipient, message)
        self.sent_messages.append(record)
        for obs in list(self._send_observers):
            obs(record)
        duplicate = False
        if _faults.ACTIVE is not None:
            # Partition cut, send side: the frame never enters the medium
            # (the observer record above stays — the cut is the network
            # eating the frame, not the sender not offering it).
            if _faults.fire_partition(sender, recipient):
                return
            act = _faults.ACTIVE.fire("transport.send")
            if act is not None:
                action, delay_s = act
                if action == "drop":
                    return
                if action in ("delay", "reorder"):
                    # delay_s counts in ticks on the in-memory network;
                    # reorder defaults to 2 ticks so same-tick traffic
                    # overtakes this message.
                    delay += max(1, int(delay_s)) if delay_s else 2
                elif action == "duplicate":
                    duplicate = True
        heapq.heappush(
            self._in_flight, (self._tick + delay, self._seq, recipient, message)
        )
        self._seq += 1
        if duplicate:
            heapq.heappush(
                self._in_flight, (self._tick + delay, self._seq, recipient, message)
            )
            self._seq += 1

    def pump(self) -> bool:
        """Deliver the next in-flight message; returns False when idle.
        Messages for stopped endpoints divert to the durable queue."""
        while self._in_flight:
            deliver_at, _, recipient, message = heapq.heappop(self._in_flight)
            self._tick = max(self._tick, deliver_at)
            endpoint = self.endpoints.get(recipient)
            if endpoint is None or not endpoint.running:
                self._durable.setdefault(recipient, deque()).append(message)
                continue
            if _faults.ACTIVE is not None:
                # Partition cut, recv side: catches frames already in
                # flight when the cut armed (send-side alone would let
                # them slip through and blur the cut edge).
                if message.sender is not None and _faults.fire_partition(
                        message.sender, recipient):
                    continue
                act = _faults.ACTIVE.fire("transport.recv")
                if act is not None:
                    action, delay_s = act
                    if action == "drop":
                        continue
                    if action == "delay":
                        heapq.heappush(self._in_flight, (
                            self._tick + max(1, int(delay_s)),
                            self._seq, recipient, message))
                        self._seq += 1
                        continue
            endpoint._deliver(message)
            return True
        return False

    def run(self, max_messages: int = 100_000) -> int:
        """Pump until quiescent; returns number of messages delivered."""
        n = 0
        while self.pump():
            n += 1
            if n >= max_messages:
                raise RuntimeError("network did not quiesce (message storm?)")
        return n

    @property
    def in_flight_count(self) -> int:
        return len(self._in_flight)

    def stop(self) -> None:
        self._in_flight.clear()
        self.endpoints.clear()


class InMemoryMessaging(MessagingService):
    """One node's endpoint on the in-memory network."""

    def __init__(self, network: InMemoryMessagingNetwork, address: InMemoryAddress):
        self._network = network
        self._address = address
        self._handlers: list[_Handler] = []
        self._pending: deque[Message] = deque()  # no handler yet — durable queue
        self._seen_ids: set[bytes] = set()
        self.running = True
        self._sends = 0
        self._redeliveries = 0  # dedupe hits (at-least-once duplicates)
        self._bursts = 0  # send_many calls (coalesced multi-frame sends)
        self._burst_frames = 0  # frames those bursts carried
        self._max_burst = 0

    @property
    def my_address(self) -> InMemoryAddress:
        return self._address

    def send(self, topic_session: TopicSession, data: bytes, to: Any) -> None:
        trace = None
        if _obs.ACTIVE is not None:
            trace = _obs.get_context()
        qos = None
        if _qos.ACTIVE is not None:
            qos = _qos.get_context()
        message = Message(
            topic_session=topic_session,
            data=data,
            unique_id=fresh_message_id(),
            sender=self._address,
            trace=trace,
            qos=qos,
        )
        self._sends += 1
        self._network._transmit(self._address, to, message)

    def send_many(self, topic_session: TopicSession, datas: list, to: Any) -> None:
        """Coalesced multi-frame send: one call, one burst accounting
        entry, N ordered transmissions (each its own Message with a fresh
        unique id — in-memory delivery has no wire to amortize, so the
        value here is exercising the SAME burst contract the TCP outbox
        implements, with real counters for the parity tests)."""
        trace = None
        if _obs.ACTIVE is not None:
            trace = _obs.get_context()
        qos = None
        if _qos.ACTIVE is not None:
            qos = _qos.get_context()
        self._bursts += 1
        self._burst_frames += len(datas)
        self._max_burst = max(self._max_burst, len(datas))
        for data in datas:
            message = Message(
                topic_session=topic_session,
                data=data,
                unique_id=fresh_message_id(),
                sender=self._address,
                trace=trace,
                qos=qos,
            )
            self._network._transmit(self._address, to, message)

    def transport_stats(self) -> dict:
        """Schema parity with TcpMessaging.transport_stats() so
        node_metrics["transport"] is homogeneous across the MockNetwork and
        multiprocess harnesses. Counters with no in-memory analogue (there
        is no outbox DB, no bridge socket, no poison queue) report zero;
        redeliveries counts real dedupe hits."""
        bursts = self._bursts
        return {
            "outbox_appends": self._sends,
            "outbox_bursts": bursts,
            "outbox_burst_frames": self._burst_frames,
            "outbox_max_burst": self._max_burst,
            "outbox_burst_avg": round(self._burst_frames / bursts, 2)
            if bursts else 0.0,
            "bridge_flushes": 0,
            "bridge_flush_frames": 0,
            "bridge_max_flush": 0,
            "bridge_flush_avg": 0.0,
            "redeliveries": self._redeliveries,
            "stale_resends": 0,
            "poison_pending": 0,
            "poison_drops": 0,
            "poison_retry_limit": 0,
            # Frames handed to the medium in total (singleton sends + burst
            # members; appends counts singletons only, tcp parity):
            # frames_sent_total / firehose requested tx = frames-per-tx,
            # the ingest amortization observable.
            "frames_sent_total": self._sends + self._burst_frames,
        }

    def add_message_handler(
        self,
        topic: str,
        session_id: int = DEFAULT_SESSION_ID,
        callback: Callable[[Message], None] = None,
    ) -> MessageHandlerRegistration:
        assert callback is not None
        handler = _Handler(topic, session_id, callback)
        self._handlers.append(handler)
        # Redeliver anything that was waiting for this handler.
        pending, self._pending = list(self._pending), deque()
        for message in pending:
            self._deliver(message, deduped=True)
        return handler

    def remove_message_handler(self, registration: MessageHandlerRegistration) -> None:
        self._handlers.remove(registration)

    def _matching(self, ts: TopicSession) -> list[_Handler]:
        return [
            h
            for h in self._handlers
            if h.topic == ts.topic and h.session_id == ts.session_id
        ]

    def _deliver(self, message: Message, deduped: bool = False) -> None:
        if not self.running:
            self._pending.append(message)
            return
        if not deduped:
            if message.unique_id in self._seen_ids:
                self._redeliveries += 1
                return  # at-least-once dedupe (NodeMessagingClient.kt:102-113)
            self._seen_ids.add(message.unique_id)
        handlers = self._matching(message.topic_session)
        if not handlers:
            self._pending.append(message)
            return
        for h in handlers:
            h.callback(message)

    def stop(self) -> None:
        self.running = False
