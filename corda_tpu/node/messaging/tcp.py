"""TCP transport: durable per-peer outboxes, at-least-once delivery, dedupe.

Capability match for the reference's Artemis tier (reference:
node/src/main/kotlin/net/corda/node/services/messaging/ArtemisMessagingServer.kt:
105-140,252-266 — durable per-peer queues + store-and-forward bridges — and
NodeMessagingClient.kt:102-113 — persistent UUID dedupe), without the broker:
each node listens on a plain TCP socket and drives its own outbox bridges.

Delivery contract:
  * send() appends to a durable outbox (sqlite when a NodeDatabase is given)
    and returns — the peer being down never blocks or drops;
  * a background bridge per peer connects, replays the outbox in order, and
    deletes entries only when the peer ACKs — at-least-once;
  * the receiver ACKs only after the message has been *processed* by the
    node's handlers (mirroring the reference's ack-after-DB-commit,
    NodeMessagingClient.kt:136-150), so a crash between receive and process
    redelivers;
  * processed unique ids are recorded durably; redeliveries are ACKed but not
    re-dispatched (dedupe).

Threading: socket I/O runs on daemon threads; handler dispatch happens ONLY
inside pump()/run_forever() on the caller's thread — the single-threaded SMM
contract is preserved (reference rationale: Node.kt:70-107).

Wire format: 4-byte big-endian length + canonical-codec frame,
  ("msg", topic, session_id, unique_id, sender_host, sender_port, data)
  ("ack", unique_id)
"""

from __future__ import annotations

import queue
import socket
import struct
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable

from ...obs import trace as _obs
from ...qos import context as _qos
from ...serialization.codec import DeserializationError, deserialize, register, serialize
from ...testing import faults as _faults
from .api import (
    DEFAULT_SESSION_ID,
    Message,
    MessageHandlerRegistration,
    MessagingService,
    TopicSession,
    fresh_message_id,
)


@register
@dataclass(frozen=True, order=True)
class TcpAddress:
    host: str
    port: int

    def __str__(self) -> str:
        return f"{self.host}:{self.port}"


@dataclass
class _Handler(MessageHandlerRegistration):
    topic: str
    session_id: int
    callback: Callable[[Message], None]


class _Outbox:
    """Durable (sqlite) or in-memory per-peer FIFO of unacked frames."""

    def __init__(self, db=None):
        self._db = db
        self._mem: list[tuple[int, str, bytes, bytes]] = []
        self._mem_seq = 0
        self._retired: list[bytes] = []  # ACKed ids awaiting node-thread delete
        self._lock = threading.Lock()
        # Burst accounting (exported via transport_stats): how well callers
        # amortize the per-append INSERT+commit into executemany bursts.
        self.stats = {"appends": 0, "bursts": 0, "burst_frames": 0,
                      "max_burst": 0}

    def append(self, peer: str, unique_id: bytes, frame: bytes) -> None:
        if self._db is not None:
            # lint: allow(no-blocking-under-lock) the outbox lock's purpose IS serializing writes on the shared sqlite connection (node thread vs bridge replay); contenders are sqlite writers, not latency-sensitive readers
            with self._lock:
                self.stats["appends"] += 1
                self._db.conn.execute(
                    "INSERT INTO outbox (peer, unique_id, blob) VALUES (?, ?, ?)",
                    (peer, unique_id, frame))
                self._db.commit()
        else:
            with self._lock:
                self.stats["appends"] += 1
                self._mem_seq += 1
                self._mem.append((self._mem_seq, peer, unique_id, frame))

    def append_many(self, peer: str,
                    entries: "list[tuple[bytes, bytes]]") -> None:
        """Burst form of append(): [(unique_id, frame), ...] lands in ONE
        executemany + ONE commit instead of an INSERT+commit (fsync, outside
        a round batch) per frame. Atomic: a crash between the executemany
        and the commit durability point rolls the WHOLE burst back — the
        caller's at-least-once resend replays it in full, never a prefix."""
        if not entries:
            return
        if self._db is not None:
            # lint: allow(no-blocking-under-lock) same sqlite write-serialization lock as append(): one burst transaction under the outbox's designated I/O lock
            with self._lock:
                self._record_burst(len(entries))
                self._db.conn.executemany(
                    "INSERT INTO outbox (peer, unique_id, blob) "
                    "VALUES (?, ?, ?)",
                    [(peer, u, f) for u, f in entries])
                self._db.commit()
        else:
            with self._lock:
                self._record_burst(len(entries))
                for u, f in entries:
                    self._mem_seq += 1
                    self._mem.append((self._mem_seq, peer, u, f))

    def _record_burst(self, n: int) -> None:
        self.stats["bursts"] += 1
        self.stats["burst_frames"] += n
        self.stats["max_burst"] = max(self.stats["max_burst"], n)

    def pending(self, peer: str) -> list[tuple[int, bytes, bytes]]:
        """[(seq, unique_id, frame)] in order for one peer (rows already
        ACK-retired but not yet deleted by the node thread are excluded)."""
        if self._db is not None:
            with self._lock:
                retired = set(self._retired)
            with self._db.aux_lock:
                rows = self._db.aux_conn.execute(
                    "SELECT seq, unique_id, blob FROM outbox WHERE peer = ? "
                    "ORDER BY seq", (peer,)).fetchall()
            return [(s, bytes(u), bytes(b)) for s, u, b in rows
                    if bytes(u) not in retired]
        with self._lock:
            return [(s, u, f) for s, p, u, f in self._mem if p == peer]

    def pending_after(self, peer: str, after_seq: int,
                      limit: int = 512) -> list[tuple[int, bytes, bytes]]:
        """Incremental form of pending(): only rows newer than after_seq —
        the replay loop polls this every 200 ms, and re-materialising the
        whole backlog each poll was O(backlog) of blob copies per peer."""
        if self._db is not None:
            with self._lock:
                retired = set(self._retired)
            with self._db.aux_lock:
                # Over-fetch by the retired count: the filter below runs
                # AFTER the SQL limit, and a window of retired-but-undeleted
                # rows must not mask a live frame sitting just beyond it.
                rows = self._db.aux_conn.execute(
                    "SELECT seq, unique_id, blob FROM outbox WHERE peer = ? "
                    "AND seq > ? ORDER BY seq LIMIT ?",
                    (peer, after_seq, limit + len(retired))).fetchall()
            return [(s, bytes(u), bytes(b)) for s, u, b in rows
                    if bytes(u) not in retired][:limit]
        with self._lock:
            return [(s, u, f) for s, p, u, f in self._mem
                    if p == peer and s > after_seq][:limit]

    def has_live(self, peer: str) -> bool:
        """Any row for `peer` that is NOT ACK-retired? The bridge's drain
        check must use this, not count(): retired rows linger until the
        node thread's flush_retired() delete, and a drain check that sees
        them spins the replay loop at full CPU (observed ~10k sqlite
        polls/s, starving the node thread's GIL) for up to a whole round
        interval after every burst."""
        return bool(self.pending_after(peer, 0, limit=1))

    def count(self, peer: str) -> int:
        """Pending-frame count WITHOUT materialising blobs (polled per
        heartbeat by consensus backpressure). May briefly overcount by the
        ACK-retired rows awaiting the node thread's delete — harmless for
        a thresholded backpressure signal."""
        if self._db is not None:
            with self._db.aux_lock:
                (n,) = self._db.aux_conn.execute(
                    "SELECT COUNT(*) FROM outbox WHERE peer = ?",
                    (peer,)).fetchone()
            return n
        with self._lock:
            return sum(1 for _, p, _, _ in self._mem if p == peer)

    def peers(self) -> set[str]:
        if self._db is not None:
            with self._db.aux_lock:
                rows = self._db.aux_conn.execute(
                    "SELECT DISTINCT peer FROM outbox").fetchall()
            return {r[0] for r in rows}
        with self._lock:
            return {p for _, p, _, _ in self._mem}

    def ack(self, unique_id: bytes) -> None:
        self.ack_many((unique_id,))

    def ack_many(self, unique_ids) -> None:
        """Retire delivered frames. Durable mode NEVER writes sqlite from
        the calling (bridge) thread: a second writer connection fighting
        the node thread's round transactions drove sqlite into busy-retry
        episodes that starved the bridges' own reads — the observed
        permanent one-directional delivery stalls under election churn.
        Ids queue here and the NODE thread deletes them in flush_retired()
        (single-writer architecture). Crash before the delete persists is
        safe: rows resend, the receiver dedupes and re-ACKs."""
        if self._db is not None:
            with self._lock:
                self._retired.extend(unique_ids)
        else:
            drop = set(unique_ids)
            with self._lock:
                self._mem = [e for e in self._mem if e[2] not in drop]

    def flush_retired(self) -> None:
        """Delete ACK-retired rows on the NODE thread's connection (called
        from pump/flush_round; rides the round batch when one is open).

        Takes db.lock: outside a round batch the shared connection may be
        mid-transaction on a foreign thread (webserver upload), and a bare
        commit here would make its half-built writes durable. Errors are
        absorbed — the rows stay, the frames resend, the receiver dedupes
        and re-ACKs (the same at-least-once recovery every other outbox
        failure path leans on)."""
        if self._db is None:
            return
        with self._lock:
            retired, self._retired = self._retired, []
        if not retired:
            return
        import sqlite3

        try:
            with self._db.lock:
                self._db.conn.executemany(
                    "DELETE FROM outbox WHERE unique_id = ?",
                    [(u,) for u in retired])
                self._db.commit()
        except (sqlite3.OperationalError, sqlite3.ProgrammingError):
            pass  # busy or closing: redelivery + dedupe retire them later


class _Dedupe:
    """Durable (sqlite) or in-memory set of processed message ids.

    The durable form keeps a process-lifetime in-memory mirror of every id
    recorded OR looked up this process, so the per-message hot path costs a
    set lookup; sqlite is consulted only on a cold miss (ids recorded by a
    previous process) and remains the durable truth."""

    def __init__(self, db=None):
        self._db = db
        self._mem: set[bytes] = set()
        self._round_recorded: list[bytes] = []
        self._lock = threading.Lock()

    def seen(self, unique_id: bytes) -> bool:
        with self._lock:
            if unique_id in self._mem:
                return True
        if self._db is None:
            return False
        # lint: allow(no-blocking-under-lock) dedupe lock serializes the sqlite read against concurrent record() writes on the same connection — it is this table's designated I/O lock
        with self._lock:
            row = self._db.conn.execute(
                "SELECT 1 FROM dedupe WHERE message_id = ?",
                (unique_id,)).fetchone()
            if row is not None:
                self._mem.add(unique_id)
            return row is not None

    def record(self, unique_id: bytes) -> None:
        # lint: allow(no-blocking-under-lock) the mem-mirror insert and the sqlite insert must be atomic vs seen(); this lock is the dedupe table's designated I/O serialization lock
        with self._lock:
            self._mem.add(unique_id)
            if self._db is not None:
                if self._db.in_batch:
                    # The sqlite row rides the round transaction; if the
                    # round aborts, the mirror entry must go with it or a
                    # redelivery would be swallowed un-durably.
                    self._round_recorded.append(unique_id)
                self._db.conn.execute(
                    "INSERT OR IGNORE INTO dedupe (message_id) VALUES (?)",
                    (unique_id,))
                self._db.commit()

    def round_committed(self) -> None:
        self._round_recorded.clear()

    def round_aborted(self) -> None:
        with self._lock:
            for unique_id in self._round_recorded:
                self._mem.discard(unique_id)
            self._round_recorded.clear()


def _send_frame(sock: socket.socket, frame: bytes) -> None:
    sock.sendall(struct.pack(">I", len(frame)) + frame)


def _recv_frame(sock: socket.socket) -> bytes | None:
    header = _recv_exact(sock, 4)
    if header is None:
        return None
    (n,) = struct.unpack(">I", header)
    if n > 64 * 1024 * 1024:
        raise DeserializationError(f"frame too large: {n}")
    return _recv_exact(sock, n)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class TcpMessaging(MessagingService):
    """One node's TCP endpoint. Call start() to listen, pump() to dispatch."""

    RETRY_BACKOFF = (0.05, 0.1, 0.2, 0.5, 1.0)  # then every 1s
    POISON_RETRIES = 50  # failed deliveries before a message is dropped
    # A frame written to a live connection but un-ACKed for this long is
    # assumed lost (e.g. the receiver dropped it without acking while other
    # traffic keeps the connection busy): reconnect and resend. Without
    # this, a lost frame on a busy connection only redelivered after a
    # reconnect that steady ACK traffic never triggers.
    STALE_RESEND_S = 5.0

    def __init__(self, host: str = "127.0.0.1", port: int = 0, db=None,
                 tls: dict | None = None):
        # tls: {"ca": Path, "cert": Path, "key": Path} PEMs — mutual TLS
        # chained to the network's shared dev CA (the reference's
        # Artemis-over-TLS capability, ArtemisMessagingComponent tcpTransport
        # + X509Utilities.kt:223-309). None = plaintext.
        self._listen_host, self._listen_port = host, port
        self._tls_server_ctx = self._tls_client_ctx = None
        if tls is not None:
            import ssl

            server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            server_ctx.load_cert_chain(str(tls["cert"]), str(tls["key"]))
            server_ctx.load_verify_locations(str(tls["ca"]))
            server_ctx.verify_mode = ssl.CERT_REQUIRED  # mutual auth
            client_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
            client_ctx.load_cert_chain(str(tls["cert"]), str(tls["key"]))
            client_ctx.load_verify_locations(str(tls["ca"]))
            client_ctx.check_hostname = False  # identity = CA membership;
            # addresses are ephemeral in dev networks
            self._tls_server_ctx, self._tls_client_ctx = server_ctx, client_ctx
        self._db = db
        self._outbox = _Outbox(db)
        self._dedupe = _Dedupe(db)
        self._handlers: list[_Handler] = []
        # (reply_socket | None, Message) pairs awaiting dispatch on pump().
        self._inbound: "queue.Queue[tuple[Any, Message]]" = queue.Queue()
        self._pending_no_handler: list[tuple[Any, Message]] = []
        self._poison: dict[bytes, int] = {}  # unique_id -> failed tries
        self._server: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._bridges: dict[str, threading.Thread] = {}
        self._bridge_wakeups: dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        self._running = False
        self._address: TcpAddress | None = None
        # Round-deferral state (db.batch() rounds): ACKs for messages whose
        # processing rode a still-open round transaction, and bridge wakeups
        # for frames whose outbox rows are not yet committed. Flushed by
        # flush_round() AFTER the round commit.
        self._deferred_acks: list[tuple[Any, bytes]] = []
        self._deferred_bridge_peers: set[str] = set()
        # Bridge writev accounting (see transport_stats). Bumped from every
        # bridge thread, read from the node/bench thread: the += below are
        # read-modify-write races without a guard, so all access goes
        # through _stats_lock (never held across I/O — counter writes only).
        self._stats_lock = threading.Lock()
        self._flush_stats = {"flushes": 0, "frames": 0, "max_frames": 0}
        # Redelivery accounting (see transport_stats): frames the dedupe
        # layer absorbed (sender resent something we already processed),
        # and poison messages dropped at the retry cap. Node-thread-only.
        self._redeliveries = 0
        self._poison_drops = 0
        self._stale_resends = 0  # bridge threads; guarded by _stats_lock

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "TcpMessaging":
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((self._listen_host, self._listen_port))
        self._server.listen(64)
        host, port = self._server.getsockname()
        self._address = TcpAddress(host, port)
        self._running = True
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"tcp-accept-{port}")
        t.start()
        self._threads.append(t)
        # Resume bridges for peers with queued outbox entries (store-and-
        # forward across restarts, ArtemisMessagingServer.kt:252-266).
        for peer in self._outbox.peers():
            self._ensure_bridge(peer)
        return self

    def stop(self) -> None:
        self._running = False
        if self._server is not None:
            # shutdown() wakes a thread blocked in accept(); close() alone
            # leaves the fd (and the port) held by that syscall on Linux.
            try:
                self._server.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._server.close()
            except OSError:
                pass
        for ev in self._bridge_wakeups.values():
            ev.set()
        # Give the accept thread a beat to leave accept() so the port frees.
        for t in self._threads[:1]:
            t.join(timeout=1.0)

    @property
    def my_address(self) -> TcpAddress:
        if self._address is None:
            raise RuntimeError("start() first")
        return self._address

    # -- sending -----------------------------------------------------------

    def _wire_tuple(self, topic_session: TopicSession, unique_id: bytes,
                    data: bytes) -> tuple:
        """The "msg" wire tuple. 7 fields normally; when tracing is armed
        AND the sending thread carries a context, two fields (trace_id,
        span_id) ride at the end; when the QoS plane is armed AND the
        thread carries a QosContext, ONE packed-bytes field follows.
        Widths therefore land on 7/8/9/10, each unambiguous — readers
        accept all four, so mixed armed/disarmed clusters interoperate and
        the disabled path never grows a frame."""
        base = (
            "msg", topic_session.topic, topic_session.session_id, unique_id,
            self.my_address.host, self.my_address.port, data,
        )
        if _obs.ACTIVE is not None:
            ctx = _obs.get_context()
            if ctx is not None:
                base = base + (ctx[0], ctx[1])
        if _qos.ACTIVE is not None:
            qctx = _qos.get_context()
            if qctx is not None:
                base = base + (qctx.to_wire(),)
        return base

    def send(self, topic_session: TopicSession, data: bytes, to: Any) -> None:
        if not isinstance(to, TcpAddress):
            raise TypeError(f"TcpMessaging can only send to TcpAddress, got {to!r}")
        unique_id = fresh_message_id()
        frame = serialize(self._wire_tuple(topic_session, unique_id, data)).bytes
        peer = str(to)
        self._outbox.append(peer, unique_id, frame)
        if _faults.ACTIVE is not None:
            # Partition cut, send side: the durable row stays (heal means
            # redeliver, same as wire loss) but the bridge is not woken —
            # the bridge loop itself parks while the cut covers this peer.
            if _faults.fire_partition(self.my_address, peer):
                return
            if self._fault_send(peer, unique_id, frame):
                return
        if self._db is not None and self._db.in_batch:
            # The row isn't committed yet; bridges read via the aux
            # connection and would see nothing. Wake them after the round.
            self._deferred_bridge_peers.add(peer)
        else:
            self._ensure_bridge(peer)

    def _fault_send(self, peer: str, unique_id: bytes, frame: bytes) -> bool:
        """transport.send injection on the durable path. Returns True when
        the bridge wakeup should be skipped: the outbox row STAYS, so a
        "dropped" or "delayed" frame is redelivered by the bridge's ~1s
        fallback re-poll — this models wire loss with the durable layer
        recovering, which is exactly the contract under test."""
        act = _faults.ACTIVE.fire("transport.send")
        if act is None:
            return False
        action, _delay_s = act
        if action in ("drop", "delay", "reorder"):
            return True
        if action == "duplicate" and frame is not None:
            # Second outbox row, same unique_id: the receiver's dedupe
            # must absorb it.
            self._outbox.append(peer, unique_id, frame)
        return False

    def send_many(self, topic_session: TopicSession, datas, to: Any) -> None:
        """Burst send: every payload in `datas` goes to ONE peer through one
        outbox executemany (one commit/fsync outside round batches) and one
        bridge wakeup, instead of an append+wake per frame. Same delivery
        contract as send() — each frame keeps its own unique_id, so ACK/
        dedupe/redelivery are per-frame."""
        if not isinstance(to, TcpAddress):
            raise TypeError(
                f"TcpMessaging can only send to TcpAddress, got {to!r}")
        if not datas:
            return
        entries = []
        for data in datas:
            unique_id = fresh_message_id()
            entries.append((unique_id, serialize(
                self._wire_tuple(topic_session, unique_id, data)).bytes))
        peer = str(to)
        self._outbox.append_many(peer, entries)
        if _faults.ACTIVE is not None:
            if _faults.fire_partition(self.my_address, peer):
                return  # cut: rows stay, bridge stays parked until heal
            if self._fault_send(peer, None, None):
                return  # whole burst "lost"; the fallback re-poll redelivers
        if self._db is not None and self._db.in_batch:
            self._deferred_bridge_peers.add(peer)
        else:
            self._ensure_bridge(peer)

    def outbox_backlog(self, to) -> int:
        """Undelivered (un-ACKed) frames queued for a peer — lets protocols
        that generate large resendable payloads (raft snapshots) avoid
        stuffing the durable outbox of an unreachable peer."""
        return self._outbox.count(str(to))

    def _note_flush(self, n_frames: int) -> None:
        """Bridge-thread writev accounting. Multiple bridges flush
        concurrently; dict += is a read-modify-write race, so the bump
        happens under the dedicated stats lock (counter-only critical
        section — the sendall stays outside any lock)."""
        with self._stats_lock:
            st = self._flush_stats
            st["flushes"] += 1
            st["frames"] += n_frames
            st["max_frames"] = max(st["max_frames"], n_frames)

    def _note_stale_resend(self) -> None:
        with self._stats_lock:
            self._stale_resends += 1

    def transport_stats(self) -> dict:
        """Self-describing burst stamps: outbox append amortization (bursts
        via append_many vs singleton appends) and the bridge's writev-style
        multi-frame flushes. Outbox counters are bumped under the outbox
        lock; bridge counters under _stats_lock — exact, not approximate."""
        ob = self._outbox.stats
        with self._stats_lock:
            fl = dict(self._flush_stats)
            stale = self._stale_resends
        return {
            "outbox_appends": ob["appends"],
            "outbox_bursts": ob["bursts"],
            "outbox_burst_frames": ob["burst_frames"],
            "outbox_max_burst": ob["max_burst"],
            "outbox_burst_avg": (round(ob["burst_frames"] / ob["bursts"], 3)
                                 if ob["bursts"] else None),
            "bridge_flushes": fl["flushes"],
            "bridge_flush_frames": fl["frames"],
            "bridge_max_flush": fl["max_frames"],
            "bridge_flush_avg": (round(fl["frames"] / fl["flushes"], 3)
                                 if fl["flushes"] else None),
            # Redelivery / retry-cap surfacing: how hard the at-least-once
            # machinery is working (and whether the poison cap is biting).
            "redeliveries": self._redeliveries,
            "stale_resends": stale,
            "poison_pending": len(self._poison),
            "poison_drops": self._poison_drops,
            "poison_retry_limit": self.POISON_RETRIES,
            # Total frames enqueued for the wire: singleton appends plus
            # every member of an append_many burst. Divided by the
            # firehose's requested tx count this is frames-per-tx — the
            # client-side wire amortization the ingest plane targets.
            "frames_sent_total": ob["appends"] + ob["burst_frames"],
        }

    def _ensure_bridge(self, peer: str) -> None:
        with self._lock:
            ev = self._bridge_wakeups.setdefault(peer, threading.Event())
            ev.set()
            t = self._bridges.get(peer)
            if t is None or not t.is_alive():
                t = threading.Thread(target=self._bridge_loop, args=(peer, ev),
                                     daemon=True, name=f"bridge-{peer}")
                self._bridges[peer] = t
                t.start()

    def _bridge_loop(self, peer: str, wakeup: threading.Event) -> None:
        """Store-and-forward bridge: replay the peer's outbox until empty,
        deleting on ACK; reconnect with backoff forever while running."""
        import sqlite3

        host, port_s = peer.rsplit(":", 1)
        attempt = 0
        while self._running:
            # Park across a held partition cut instead of churning the
            # connect/replay/stale-resend cycle (each cycle resends the
            # whole un-ACKed outbox into a void that never ACKs). A pure
            # QUERY — polling here must not advance the cut schedule.
            if _faults.ACTIVE is not None and _faults.partitioned(
                    self._address, peer):
                wakeup.clear()
                wakeup.wait(timeout=0.25)
                continue
            try:
                pending = self._outbox.pending(peer)
            except sqlite3.ProgrammingError:
                return  # db closed: the node is shutting down
            except sqlite3.OperationalError:
                pending = None  # transient lock contention: back off, retry
            if pending is None:
                wakeup.clear()
                wakeup.wait(timeout=0.05)
                continue
            if not pending:
                wakeup.clear()
                wakeup.wait(timeout=1.0)
                if not self._running:
                    return
                continue
            try:
                # wrap_socket() detaches the raw socket, so close the WRAPPED
                # one explicitly — the with-block alone would leak TLS fds.
                import contextlib

                with socket.create_connection((host, int(port_s)),
                                              timeout=5.0) as raw:
                    raw.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    sock = raw
                    if self._tls_client_ctx is not None:
                        sock = self._tls_client_ctx.wrap_socket(
                            raw, server_hostname=host)
                    with contextlib.closing(sock):
                        attempt = 0
                        self._replay_outbox(peer, sock, wakeup)
            except sqlite3.ProgrammingError:
                return  # db closed mid-replay: the node is shutting down
            except OSError:
                backoff = self.RETRY_BACKOFF[
                    min(attempt, len(self.RETRY_BACKOFF) - 1)]
                attempt += 1
                wakeup.clear()
                wakeup.wait(timeout=backoff)

    def _replay_outbox(self, peer: str, sock: socket.socket,
                       wakeup: threading.Event | None = None) -> None:
        """Stream outbox frames and consume ACKs concurrently (no head-of-line
        blocking: frames enqueued while earlier ones await ACK still go out).
        Raises OSError to trigger reconnect + redeliver when the peer stalls
        or drops. When the outbox drains, the connection is KEPT and the
        loop parks on the wakeup event: tearing it down per burst was
        measured at ~70 fresh TCP(+TLS) handshakes/s on a loaded raft
        leader — handshake latency and accept-thread churn on both sides of
        every hop.

        Frames are fetched INCREMENTALLY (seq > last sent) so steady-state
        polls touch only new rows; un-ACKed frames from this connection are
        tracked in `sent` and re-sent only after a reconnect."""
        sock.settimeout(0.2)
        sent: dict[bytes, float] = {}  # unique_id -> monotonic write time
        last_seq = 0
        idle_polls = 0
        last_stale_check = time.monotonic()
        while self._running:
            # Stale-resend guard: a frame can be lost AFTER the socket write
            # (receiver dropped it without acking) while steady ACK traffic
            # for other frames keeps idle_polls at zero — without this check
            # such a frame would only redeliver on a reconnect that never
            # comes. Checked at most once a second.
            now = time.monotonic()
            if sent and now - last_stale_check > 1.0:
                last_stale_check = now
                # A cut that armed while this connection was warm: exit to
                # the bridge loop's partition park NOW (plain OSError, not
                # a stale resend — the cut is known, not suspected; without
                # this the loop would burn a full STALE_RESEND_S window
                # resending the outbox into the void once per window).
                if _faults.ACTIVE is not None and _faults.partitioned(
                        self._address, peer):
                    raise OSError("partition cut covers peer")
                if now - min(sent.values()) > self.STALE_RESEND_S:
                    self._note_stale_resend()
                    raise OSError("frames un-ACKed past stale-resend window")
            batch = self._outbox.pending_after(peer, last_seq)
            if not batch and not sent:
                # Clear BEFORE the liveness check: a frame enqueued (and
                # the event set) between has_live() and clear() would be
                # erased and sit undelivered until the fallback re-poll.
                if wakeup is not None:
                    wakeup.clear()
                if not self._outbox.has_live(peer):
                    # Drained: every remaining row is ACK-retired and only
                    # awaits the node thread's delete. (count() would see
                    # those rows and spin this loop at full CPU.) Park on
                    # the wakeup with the connection warm; fall back to a
                    # liveness re-check every second.
                    if wakeup is None:
                        return
                    wakeup.wait(timeout=1.0)
                    continue
                # Live rows at/below last_seq remain un-ACKed from a
                # PREVIOUS connection: resend them once from scratch.
                last_seq = 0
                sent.clear()
                continue
            # writev-style flush: the whole un-sent batch concatenates into
            # one buffer and hits the socket with ONE sendall per bridge
            # wakeup — a burst previously paid a syscall (and, pre-Nagle-off,
            # a potential segment) per frame.
            buf = bytearray()
            n_frames = 0
            write_at = time.monotonic()
            for seq, unique_id, frame in batch:
                if unique_id not in sent:
                    buf += struct.pack(">I", len(frame))
                    buf += frame
                    n_frames += 1
                    sent[unique_id] = write_at
                last_seq = max(last_seq, seq)
            if buf:
                sock.sendall(buf)
                self._note_flush(n_frames)
            try:
                frame = _recv_frame(sock)
                if frame is None:
                    raise OSError("peer closed during ack wait")
                decoded = deserialize(frame)
                if (isinstance(decoded, tuple) and len(decoded) == 2
                        and decoded[0] == "ack"
                        and isinstance(decoded[1], bytes)):
                    self._outbox.ack(decoded[1])
                    sent.pop(decoded[1], None)
                elif (isinstance(decoded, tuple) and len(decoded) == 2
                        and decoded[0] == "acks"
                        and isinstance(decoded[1], tuple)):
                    # Round-coalesced ACK frame (one per connection per
                    # receiver round): retired in one sqlite transaction.
                    ids = [u for u in decoded[1] if isinstance(u, bytes)]
                    self._outbox.ack_many(ids)
                    for u in ids:
                        sent.pop(u, None)
                idle_polls = 0
            except socket.timeout:
                idle_polls += 1
                if sent and idle_polls > 50:  # ~10s outstanding, no ACK
                    raise OSError("peer not acking")
            except DeserializationError as e:
                # A peer speaking garbage (unframeable stream or undecodable
                # frame) is as dead as a closed one: reconnect + redeliver
                # rather than killing the bridge thread.
                raise OSError(f"unreadable ack stream: {e}") from e

    # -- receiving ---------------------------------------------------------

    def _accept_loop(self) -> None:
        try:
            # Poll _running via timeout; also frees the port fast on stop.
            self._server.settimeout(0.5)
        except OSError:
            return  # stop() closed the socket before this thread ran
        while self._running:
            try:
                conn, _addr = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                # Frames are small and latency-sensitive (session messages,
                # ACKs): Nagle + delayed-ACK interplay would add up to 40 ms
                # per exchange on the notary round trip.
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            # TLS handshake (if any) happens on the per-connection reader
            # thread — a stalled peer must not head-of-line block accept().
            t = threading.Thread(target=self._serve_connection, args=(conn,),
                                 daemon=True)
            t.start()
            # Prune finished reader threads so repeated connect/drop cycles
            # (port scanners) don't grow this list without bound.
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve_connection(self, conn: socket.socket) -> None:
        if self._tls_server_ctx is not None:
            try:
                conn.settimeout(5.0)
                conn = self._tls_server_ctx.wrap_socket(conn, server_side=True)
                conn.settimeout(None)
            except (OSError, ValueError):
                try:
                    conn.close()
                except OSError:
                    pass
                return  # plaintext/un-CA'd peers are refused
        self._reader_loop(conn)

    def _reader_loop(self, conn: socket.socket) -> None:
        try:
            while self._running:
                frame = _recv_frame(conn)
                if frame is None:
                    return
                try:
                    decoded = deserialize(frame)
                    kind = decoded[0]
                    if kind != "msg":
                        continue
                    # 7 fields plain; +2 (trace_id/span_id) when the sender
                    # had tracing armed; +1 packed QosContext when the QoS
                    # plane was armed. Widths 7/8/9/10 are all valid and
                    # unambiguous (trace always precedes qos).
                    width = len(decoded)
                    trace = None
                    qos = None
                    (_, topic, session_id, unique_id, shost, sport,
                     data) = decoded[:7]
                    if width in (9, 10):
                        w_trace, w_span = decoded[7], decoded[8]
                        if not (isinstance(w_trace, bytes)
                                and isinstance(w_span, bytes)):
                            continue
                        trace = (w_trace, w_span)
                    if width in (8, 10):
                        qos = _qos.QosContext.from_wire(decoded[width - 1])
                        if qos is None:
                            continue  # malformed QoS field: junk frame
                    elif width not in (7, 9):
                        continue
                    # Field TYPES are part of the wire contract: hostile
                    # well-formed frames with wrong-typed fields must die
                    # here, not on the node's pump thread (dedupe hashes
                    # unique_id; TopicSession expects str/int).
                    if not (isinstance(topic, str)
                            and isinstance(session_id, int)
                            and isinstance(unique_id, bytes)
                            and isinstance(shost, str)
                            and isinstance(sport, int)):
                        continue
                except (DeserializationError, ValueError, IndexError,
                        TypeError, KeyError):
                    # Junk from the wire — including well-framed frames that
                    # decode to a non-sequence — drop, never crash.
                    continue
                message = Message(
                    topic_session=TopicSession(topic, session_id),
                    data=data,
                    unique_id=unique_id,
                    sender=TcpAddress(shost, sport),
                    trace=trace,
                    qos=qos,
                )
                self._inbound.put((conn, message))
        except (OSError, DeserializationError):
            # Unreadable socket or unframeable stream (port scanners,
            # oversized length prefixes): drop the connection, never the
            # thread — the finally below closes it.
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- dispatch (caller thread) ------------------------------------------

    def add_message_handler(
        self,
        topic: str,
        session_id: int = DEFAULT_SESSION_ID,
        callback: Callable[[Message], None] = None,
    ) -> MessageHandlerRegistration:
        assert callback is not None
        handler = _Handler(topic, session_id, callback)
        self._handlers.append(handler)
        # Requeue messages that arrived before this handler registered.
        pending, self._pending_no_handler = self._pending_no_handler, []
        for item in pending:
            self._inbound.put(item)
        return handler

    def remove_message_handler(self, registration: MessageHandlerRegistration) -> None:
        self._handlers.remove(registration)

    def pump(self, timeout: float = 0.0, max_messages: int | None = None,
             coalesce: float = 0.0) -> int:
        """Dispatch queued inbound messages on THIS thread; ACK after
        processing. Returns number dispatched. timeout>0 blocks for the
        first message. max_messages bounds one pump call so a round (and its
        db transaction, which holds the sqlite write lock) stays short under
        firehose load — leftover messages are dispatched next round.

        coalesce>0: once the first message wakes the round, keep draining
        (blocking) for up to that many seconds from its arrival — each
        round costs a commit/fsync, per-connection ACK frames and (leader)
        an AppendEntries broadcast, and wake-per-message pays all three per
        message under trickle load."""
        self._outbox.flush_retired()  # node thread: the ONE sqlite writer
        n = attempts = 0
        window_end = None
        while True:
            if max_messages is not None and attempts >= max_messages:
                return n
            first_blocking = attempts == 0 and timeout > 0
            if first_blocking:
                block, wait = True, timeout
            elif window_end is not None:
                wait = window_end - time.monotonic()
                if wait <= 0:
                    block, wait = False, None
                else:
                    block = True
            else:
                block, wait = False, None
            try:
                conn, message = self._inbound.get(block=block, timeout=wait)
            except queue.Empty:
                return n
            if attempts == 0 and coalesce > 0:
                window_end = time.monotonic() + coalesce
            attempts += 1
            if self._dispatch(conn, message):
                n += 1

    def _dispatch(self, conn, message: Message) -> bool:
        if _faults.ACTIVE is not None:
            # Partition cut, recv side — the authoritative enforcement (a
            # frame that slipped out before the cut armed still dies
            # here). No ack, no dedupe record: after heal the sender's
            # durable outbox redelivers, preserving at-least-once.
            if message.sender is not None and _faults.fire_partition(
                    message.sender, self._address):
                return False
            act = _faults.ACTIVE.fire("transport.recv")
            if act is not None:
                action, delay_s = act
                if action == "drop":
                    # No ack, no dedupe record: the sender's stale-resend
                    # window (STALE_RESEND_S) redelivers it.
                    return False
                if action == "delay" and delay_s > 0:
                    time.sleep(delay_s)  # slow-consumer fault: stalls pump
        if self._dedupe.seen(message.unique_id):
            self._redeliveries += 1
            self._ack(conn, message.unique_id)  # redelivery: ack, don't re-run
            return False
        handlers = [h for h in self._handlers
                    if h.topic == message.topic_session.topic
                    and h.session_id == message.topic_session.session_id]
        if not handlers:
            # Park until a handler registers — but ACK now, mirroring the
            # in-memory tier's semantics (parked messages live in RAM there
            # too) and the reference's consume-then-discard of unroutable
            # session messages (StateMachineManager.kt "unknown session").
            # Without the ACK a dead session's trailing SessionEnd would
            # wedge the sender's bridge behind an ACK that never comes.
            self._pending_no_handler.append((conn, message))
            self._ack(conn, message.unique_id)
            return False
        import logging

        succeeded = failed = 0
        for h in handlers:  # per-handler isolation: one failure must not
            try:            # skip later handlers or kill the node's pump
                h.callback(message)
                succeeded += 1
            except Exception:
                failed += 1
                logging.getLogger(__name__).exception(
                    "handler failed for %s", message.topic_session)
        if failed and not succeeded:
            # Nothing processed: do NOT ack — the sender redelivers, giving
            # transient failures (e.g. a SessionInit arriving before the
            # network map knows the peer) time to resolve. A poison message
            # that fails deterministically is dropped after a retry budget
            # instead of redelivering forever.
            tries = self._poison.get(message.unique_id, 0) + 1
            if tries < self.POISON_RETRIES:
                self._poison[message.unique_id] = tries
                return False
            logging.getLogger(__name__).error(
                "dropping poison message on %s after %d failed deliveries",
                message.topic_session, tries)
            self._poison.pop(message.unique_id, None)
            self._poison_drops += 1
        # Processed (or poison-dropped): record id durably, THEN ack (crash
        # before this point means the sender redelivers; crash after means
        # dedupe swallows it). If SOME handlers succeeded and others failed,
        # we still ack — re-running the successful ones would duplicate side
        # effects, which is worse than dropping the failed delivery (every
        # production topic here has exactly one handler anyway).
        self._poison.pop(message.unique_id, None)
        self._dedupe.record(message.unique_id)
        if self._db is not None and self._db.in_batch:
            # The dedupe record (and everything processing wrote) commits at
            # round end; ACKing before that commit would let a crash lose
            # the message with the sender believing it delivered.
            self._deferred_acks.append((conn, message.unique_id))
        else:
            self._ack(conn, message.unique_id)
        return succeeded > 0

    def flush_round(self) -> None:
        """Release round-deferred effects. MUST be called after the round's
        db.batch() commit: sends the ACKs for every message processed in the
        round and wakes bridges for frames the round enqueued.

        ACKs COALESCE per connection — one ("acks", ids...) frame instead of
        up to max_messages frames: at firehose load the per-ACK serialize +
        sendall was the single hottest item in the round profile."""
        self._dedupe.round_committed()
        self._outbox.flush_retired()
        acks, self._deferred_acks = self._deferred_acks, []
        by_conn: dict[int, tuple[Any, list[bytes]]] = {}
        for conn, unique_id in acks:
            if conn is None:
                continue
            by_conn.setdefault(id(conn), (conn, []))[1].append(unique_id)
        for conn, ids in by_conn.values():
            try:
                if len(ids) == 1:
                    _send_frame(conn, serialize(("ack", ids[0])).bytes)
                else:
                    _send_frame(conn, serialize(("acks", tuple(ids))).bytes)
            except OSError:
                pass  # sender gone; it will reconnect and redeliver
        peers, self._deferred_bridge_peers = self._deferred_bridge_peers, set()
        for peer in peers:
            self._ensure_bridge(peer)

    def abort_round(self) -> None:
        """Discard round-deferred effects after a ROLLED-BACK round: the
        deferred ACKs must never be sent (their messages' processing was
        rolled back — the senders must redeliver) and the dedupe mirror
        unwinds the round's entries."""
        self._dedupe.round_aborted()
        self._deferred_acks.clear()
        self._deferred_bridge_peers.clear()

    def _ack(self, conn, unique_id: bytes) -> None:
        if conn is None:
            return
        try:
            _send_frame(conn, serialize(("ack", unique_id)).bytes)
        except OSError:
            pass  # sender gone; it will reconnect and redeliver
