"""The production node: config-driven assembly over durable storage + TCP.

Capability match for the reference's node assembly (reference:
node/src/main/kotlin/net/corda/node/internal/AbstractNode.kt:179-258 —
storage -> messaging -> vault/identity/keys -> SMM -> notary, one start()
sequence) and the CLI entry point (node/.../Main.kt:34-114).  Differences are
TPU-first by design: the verifier provider (cpu | jax) is part of the config,
and the run loop enforces the max-wait verify micro-batch policy (flush at N
sigs or T ms, whichever first) that keeps notarisation p99 bounded while
batches stay wide (SURVEY.md §7 stage 6).

Crash contract: every durable store commits before the call returns
(NodeDatabase), so `kill -9` at any point leaves a database a fresh Node over
the same base_dir resumes from — including mid-flow checkpoints
(restoreFibersFromCheckpoints capability, StateMachineManager.kt:190-226).

Run it:  python -m corda_tpu.node.node <config.toml>
"""

from __future__ import annotations

import logging
import os
import sys
import time
from collections import deque

from ..flows.data_vending import install_data_vending
from ..obs import telemetry as _tm
from ..obs import trace as _obs
from ..qos import context as _qos
from ..testing import faults as _faults
from ..utils.clock import Clock
from .config import NetMapEntry, NodeConfig, netmap_load, netmap_register
from .messaging.tcp import TcpMessaging
from .services.api import (
    NodeInfo,
    ServiceHub,
    ServiceInfo,
    ServiceType,
    SIMPLE_NOTARY,
    StorageService,
    VALIDATING_NOTARY,
)
from .services.inmemory import (
    InMemoryIdentityService,
    InMemoryNetworkMapCache,
    NodeVaultService,
    SimpleKeyManagementService,
)
from .services.notary import SimpleNotaryService, ValidatingNotaryService
from .services.persistence import (
    DBAttachmentStorage,
    DBTransactionMappingStorage,
    DBCheckpointStorage,
    DBTransactionStorage,
    NodeDatabase,
    PersistentUniquenessProvider,
)
from .statemachine import FlowHandle, StateMachineManager


def _make_verifier(kind: str):
    from ..crypto.provider import make_verifier

    return make_verifier(kind)


def _select_batch_verifier(config: NodeConfig):
    """Pick the node's verification provider from config + env.

    Precedence: federation_hosts (or CORDA_TPU_FEDERATION) — the multi-
    host router over per-host sidecars (crypto/federation.py) — then a
    single sidecar address (or CORDA_TPU_SIDECAR), then the local
    provider. Module-level so the federation-off bit-identity contract
    is testable without booting a node: with neither knob set this
    returns exactly what the pre-federation tree built.
    """
    federation = config.batch.federation_hosts or os.environ.get(
        "CORDA_TPU_FEDERATION", "")
    if federation:
        from ..crypto.federation import FederatedVerifier

        hosts = [h.strip() for h in federation.split(",") if h.strip()]
        return FederatedVerifier(
            hosts,
            deadline_ms=config.batch.sidecar_deadline_ms,
            devices=config.batch.sidecar_devices or None)
    sidecar_addr = config.batch.sidecar or os.environ.get(
        "CORDA_TPU_SIDECAR", "")
    if sidecar_addr:
        from .verify_client import SidecarVerifier

        return SidecarVerifier(
            sidecar_addr,
            deadline_ms=config.batch.sidecar_deadline_ms,
            devices=config.batch.sidecar_devices or None)
    return _make_verifier(config.verifier)


class Node:
    """One process-owning node instance over a base_dir."""

    def __init__(self, config: NodeConfig):
        self.config = config
        if config.qos.enabled:
            # Arm the QoS plane BEFORE any subsystem that reads
            # _qos.ACTIVE at send/schedule time (messaging, SMM, raft).
            # Process-wide like the obs/faults arming; qos.enabled=False
            # leaves ACTIVE None and every instrumentation point is a
            # single attribute check — bit-identical to the pre-QoS tree.
            from ..qos import context as _qos_ctx

            _qos_ctx.arm(config.name, slo_ms=config.qos.slo_ms,
                         deadline_guard_ms=config.qos.deadline_guard_ms,
                         bulk_every=config.qos.bulk_every)
        config.base_dir.mkdir(parents=True, exist_ok=True)
        self.db = NodeDatabase(config.base_dir / "node.db")
        # Durability plane: the online scrubber is built here but only
        # started in start() (a constructed-but-unstarted node must not
        # carry a background thread). None when disarmed — every metrics
        # touch point short-circuits on that one attribute check.
        self.scrubber = None
        if config.durability.scrub_enabled:
            from .services.integrity import Scrubber

            self.scrubber = Scrubber(
                self.db.path,
                rows_per_s=config.durability.scrub_rows_per_s,
                interval_s=config.durability.scrub_interval_s,
                node_name=config.name)
        self.key = self.db.load_or_create_identity(config.name)
        from ..crypto.party import Party

        self.identity = Party.of(config.name, self.key.public)

        # -- messaging (starts listening immediately; handlers attach below) --
        # A restarted node must come back on its previous port so peers'
        # queued outbox bridges (keyed by host:port) reconnect — the stable-
        # address property Artemis queues give the reference. An ephemeral
        # first start records the allocated port.
        port = config.port
        if port == 0:
            stored = self.db.get_setting("listen_port")
            if stored is not None:
                port = int(stored)
        tls_paths = None
        if config.tls:
            # Dev-mode TLS: certs chain to a shared dev CA living beside the
            # network map file (configureWithDevSSLCertificate capability).
            from ..crypto.x509 import generate_dev_tls_material

            shared = (config.network_map.parent if config.network_map
                      else config.base_dir)
            tls_paths = generate_dev_tls_material(
                config.base_dir, shared, config.name, config.host)
        try:
            self.messaging = TcpMessaging(config.host, port, db=self.db,
                                          tls=tls_paths)
            self.messaging.start()
        except OSError:
            # Stored port taken (another process got it) — fall back to
            # ephemeral; netmap re-registration updates peers going forward.
            self.messaging = TcpMessaging(config.host, 0, db=self.db,
                                          tls=tls_paths)
            self.messaging.start()
        self.db.set_setting("listen_port", str(self.messaging.my_address.port))

        # -- advertised services ------------------------------------------
        services = ()
        if config.notary in ("simple", "raft-simple"):
            services = (ServiceInfo(SIMPLE_NOTARY),)
        elif config.notary in ("validating", "raft-validating"):
            services = (ServiceInfo(VALIDATING_NOTARY),)
        self._shard_epoch_advertised = 0
        if config.notary_shards is not None:
            # Shard members also advertise their group + the total shard
            # count ("corda.notary.shard.<g>of<n>[@epoch]"): the netmap
            # every party already syncs doubles as the shard directory, so
            # clients recover the full shard map with zero extra round
            # trips. Members of PENDING groups (index >= count — boot-ahead
            # split targets) advertise nothing until a reshard epoch
            # activates them, and a restart replays the group's durable
            # fence so the advertisement matches what the state machine
            # enforces (a retired member drops its shard string entirely).
            import json as _json

            from .services.sharding import shard_service_string

            my_group = next(
                (g for g, members in enumerate(config.notary_shards.groups)
                 if config.name in members), None)
            count, epoch = config.notary_shards.count, 0
            raw = self.db.get_setting("shard_fence")
            fence = _json.loads(raw) if raw else None
            if fence is not None and fence.get("mode") == "retired":
                my_group = None
                self._shard_epoch_advertised = int(fence["epoch"])
            elif fence is not None and fence.get("mode") == "active":
                my_group = int(fence["group"])
                count = int(fence["count"])
                epoch = int(fence["epoch"])
            if my_group is not None and my_group < count:
                services += (ServiceInfo(ServiceType(shard_service_string(
                    my_group, count, epoch))),)
                self._shard_epoch_advertised = epoch
        self.info = NodeInfo(
            address=self.messaging.my_address,
            legal_identity=self.identity,
            advertised_services=services,
        )

        # -- service hub ---------------------------------------------------
        self.identity_service = InMemoryIdentityService()
        self.network_map_cache = InMemoryNetworkMapCache()
        key_service = SimpleKeyManagementService([self.key])
        # Vault engine selection: [vault] indexed=true or the env var arms
        # the sqlite-backed IndexedVaultService (durable rows, O(log n)
        # queries, watermark incremental boot). Unset = the in-memory
        # engine, bit-identical to before the vault plane existed.
        self._vault_indexed = bool(config.vault.indexed) or os.environ.get(
            "CORDA_TPU_VAULT_INDEXED", "") not in ("", "0")
        if self._vault_indexed:
            from .services.vault import IndexedVaultService

            vault_service = IndexedVaultService(
                self.db, lambda: set(key_service.keys.keys()),
                softlock_ttl_s=config.vault.softlock_ttl_s)
        else:
            vault_service = NodeVaultService(
                lambda: set(key_service.keys.keys()))
        self.services = ServiceHub(
            identity_service=self.identity_service,
            key_management_service=key_service,
            storage_service=StorageService(
                validated_transactions=DBTransactionStorage(self.db),
                attachments=DBAttachmentStorage(self.db),
                state_machine_recorded_transaction_mapping=(
                    DBTransactionMappingStorage(self.db)),
            ),
            vault_service=vault_service,
            network_map_cache=self.network_map_cache,
            clock=Clock(),
            my_info=self.info,
        )

        # Bounded by construction (see _sample_metrics_maybe): a week-long
        # soak keeps exactly one hour of samples, never an unbounded list.
        self.metrics_history: deque[dict] = deque(
            maxlen=self.METRICS_HISTORY_KEEP)

        # Verification provider: federation_hosts routes batches across
        # per-host sidecars; a single sidecar address feeds the host's
        # shared device-owning server (crypto/sidecar.py). Neither set =
        # exactly the local routing as before.
        verifier = _select_batch_verifier(config)

        # -- state machine manager ----------------------------------------
        self.smm = StateMachineManager(
            service_hub=self.services,
            messaging=self.messaging,
            checkpoint_storage=DBCheckpointStorage(self.db),
            verifier=verifier,
            our_identity=self.identity,
            defer_verify=True,  # the run loop owns the flush policy
            defer_checkpoints=True,  # run_once flushes once per round
        )
        if config.batch.async_verify:
            # Pipelined verification: the run loop submits accumulated
            # batches to a feeder thread and keeps serving Raft/messages/
            # checkpoints while the verifier runs (crypto/async_verify.py).
            from ..crypto.async_verify import AsyncVerifyService

            self.smm.async_verify = AsyncVerifyService(
                self.smm.verifier, depth=config.batch.async_depth)
        # Unknown send targets trigger an on-demand refresh (a client that
        # registered after our last periodic refresh must be reachable the
        # moment its first SessionInit arrives). Throttled: a send to a
        # GENUINELY unknown party retries through redelivery backoff, and
        # each retry must not re-read the netmap file.
        self.smm.netmap_refresh = (
            lambda: self.refresh_netmap_maybe(every=0.25))

        # -- notary --------------------------------------------------------
        # Name -> TcpAddress for every netmap entry (superset of raft
        # peers); mutated in place by refresh_netmap so bound .get methods
        # stay live.
        self._netmap_addrs: dict = {}
        self.uniqueness_provider = None
        self.notary_service = None
        self.raft_member = None
        if config.notary != "none":
            if config.notary.startswith("raft"):
                from .services.raft import (
                    RaftMember,
                    RaftUniquenessProvider,
                    make_apply_command,
                )

                self.raft_member = RaftMember(
                    name=config.name,
                    peers={},  # populated from the netmap on refresh
                    messaging=self.messaging,
                    db=self.db,
                    apply_command=make_apply_command(self.db),
                    config=config.raft,  # commit-pipeline policy ([raft])
                )
                # Cross-group reply routing (sharded notary): resolve ANY
                # netmap member by name, not just this member's own peers,
                # so a coordinator in another group gets its ClientReply
                # back even though it is outside our raft_cluster.
                self.raft_member.resolve_addr = self._netmap_addrs.get
                if config.notary_shards is not None:
                    from .services.sharding import ShardedUniquenessProvider

                    self.uniqueness_provider = ShardedUniquenessProvider(
                        self.raft_member, pump=self._raft_pump,
                        shards=config.notary_shards)
                else:
                    self.uniqueness_provider = RaftUniquenessProvider(
                        self.raft_member, pump=self._raft_pump)
            else:
                self.uniqueness_provider = PersistentUniquenessProvider(self.db)
            cls = (ValidatingNotaryService
                   if config.notary.endswith("validating")
                   else SimpleNotaryService)
            self.notary_service = cls(
                self.smm, self.services, self.identity, self.key,
                self.uniqueness_provider)
            if config.qos.enabled:
                # Admission control at the notarise entry point: the
                # controller rides the service token NotaryServiceFlow
                # already carries (read via getattr — absent means no
                # admission work at all on the disabled path).
                from ..qos import AdmissionController

                self.notary_service.admission = AdmissionController(
                    interactive_rate=config.qos.interactive_rate,
                    interactive_burst=config.qos.interactive_burst,
                    bulk_rate=config.qos.bulk_rate,
                    bulk_burst=config.qos.bulk_burst,
                    queue_watermark=config.qos.queue_watermark)

        # -- vault rebuild + scheduler ------------------------------------
        # The vault is a projection of durable transaction storage: rebuild
        # it so a restarted node sees its unconsumed states (the
        # reference's vault is DB-backed; same post-restart capability).
        # Indexed engine: replay only the delta above its persisted
        # watermark. Legacy engine: stream the whole history through
        # notify_all in bounded batches — never the full ledger in memory.
        tx_storage = self.services.storage_service.validated_transactions
        if self._vault_indexed:
            self.services.vault_service.rebuild_from(
                tx_storage, batch=config.vault.rebuild_batch)
        else:
            chunk: list = []
            for _rowid, stx in tx_storage.stream_since(
                    0, batch=config.vault.rebuild_batch):
                chunk.append(stx)
                if len(chunk) >= config.vault.rebuild_batch:
                    self.services.vault_service.notify_all(chunk)
                    chunk = []
            if chunk:
                self.services.vault_service.notify_all(chunk)
        # Vault updates join the change feed so RPC push subscribers
        # (explorer) stream ledger activity live, alongside flow events
        # (the reference pushes vaultAndUpdates the same way,
        # CordaRPCOps.kt:71-76). Subscribed AFTER the rebuild replay above:
        # a restart must not re-emit the whole stored ledger as fresh
        # events to reconnecting push clients.
        self.services.vault_service.subscribe(
            lambda update: self.smm.changes.append(
                ("vault", len(update.consumed), len(update.produced))))
        # Provenance mappings join the feed too (observers fire only on
        # FRESH rows, so a restart replaying checkpointed flows does not
        # re-announce mappings already durable in tx_mappings): push
        # subscribers see which flow produced each transaction live
        # (reference: CordaRPCOps.kt:86 stateMachineRecordedTransaction
        # MappingStorage's observable half).
        self.services.storage_service.state_machine_recorded_transaction_mapping \
            .subscribe(lambda m: self.smm.changes.append(
                ("tx_recorded", m.run_id, m.tx_id.bytes)))
        from .services.scheduler import NodeSchedulerService
        from .services.vault_observers import (
            CashBalanceMetricsObserver,
            IndexedBalanceMetricsObserver,
        )

        self.scheduler = NodeSchedulerService(
            self.smm, self.services.vault_service)
        if self._vault_indexed:
            # The indexed engine already aggregates balances durably;
            # publish from its table instead of a second scanning tally.
            IndexedBalanceMetricsObserver(self.services.vault_service,
                                          self.smm.metrics)
        else:
            CashBalanceMetricsObserver(self.services.vault_service,
                                       self.smm.metrics)
        from .services.schema import SchemaObserver

        self.schema = SchemaObserver(self.services.vault_service, self.db)

        # -- network map directory service (wire tier) ---------------------
        self.netmap_service = None
        self.netmap_client = None
        if config.map_service:
            from .services.netmap_service import NetworkMapService

            self.netmap_service = NetworkMapService(self.messaging)

        install_data_vending(self.smm)

        # -- CorDapps (reference: plugin ServiceLoader, AbstractNode.kt:
        # 170-173,340-352): importing runs the registration decorators;
        # install(node) wires responders.
        import importlib

        for module_name in config.cordapps:
            module = importlib.import_module(module_name)
            installer = getattr(module, "install", None)
            if installer is not None:
                installer(self)

        # -- RPC (reference: RPCDispatcher.kt, RPCUserService.kt) ----------
        self.rpc = None
        if config.rpc_users:
            from .rpc import RpcDispatcher, RpcUser

            users = tuple(
                RpcUser(u["username"], u["password"],
                        tuple(u.get("permissions", ())))
                for u in config.rpc_users)
            self.rpc = RpcDispatcher(self, users)

        self.webserver = None
        self._started = False
        # Elastic resharding: the latest plan seen on the netmap (set by
        # refresh_netmap), and a throttle on the fence-observation poll.
        self._reshard_plan: tuple[int, int, int] | None = None
        self._fence_checked_at = 0.0

    # -- network map -------------------------------------------------------

    def register_and_refresh_netmap(self) -> None:
        """Write our entry to the shared netmap file, then (re)load peers
        into the cache and identity service."""
        path = self.config.network_map
        if path is None:
            return
        netmap_register(
            path, self.config.name, self.messaging.my_address.host,
            self.messaging.my_address.port, self.identity.owning_key,
            tuple(str(s.type) for s in self.info.advertised_services))
        self.refresh_netmap()

    def refresh_netmap(self) -> None:
        path = self.config.network_map
        if path is None:
            return
        if _faults.ACTIVE is not None:
            # Stale-directory injection: drop skips this refresh round (the
            # node keeps routing on its old map until the next cadence),
            # stall delays it, crash kills the process inside fire().
            act = _faults.ACTIVE.fire("netmap.refresh")
            if act is not None:
                action, delay_s = act
                if action == "drop":
                    return
                if delay_s > 0:
                    time.sleep(delay_s)
        entries = netmap_load(path)
        # Self-heal: if our own row vanished (a concurrent boot clobbered
        # the file before registration was flock-serialised, or an operator
        # replaced the map), write it back — registration is otherwise
        # boot-only, so a lost entry means no peer can ever reach us.
        if self._started and all(e.name != self.config.name for e in entries):
            netmap_register(
                path, self.config.name, self.messaging.my_address.host,
                self.messaging.my_address.port, self.identity.owning_key,
                tuple(str(s.type) for s in self.info.advertised_services))
            entries = netmap_load(path)
        plan = None
        for entry in entries:
            if entry.name.startswith("_"):
                # Control pseudo-entry (no node behind it, no parseable
                # key): the reshard plan rides the map as a service string.
                from .services.sharding import parse_reshard_plan

                for svc in entry.services:
                    parsed = parse_reshard_plan(svc)
                    if parsed is not None and (plan is None
                                               or parsed[0] > plan[0]):
                        plan = parsed
                continue
            info = entry.node_info()
            self.identity_service.register_identity(info.legal_identity)
            self.network_map_cache.add_node(info)
            self._netmap_addrs[entry.name] = info.address
            if (self.raft_member is not None
                    and entry.name in self.config.raft_cluster
                    and entry.name != self.config.name):
                self.raft_member.peers[entry.name] = info.address
        self._reshard_plan = plan

    def _raft_pump(self) -> None:
        """Drive consensus while a flow blocks in commit(): deliver raft
        messages (SMM session dispatch is re-entrancy-guarded and just
        queues) and advance election/heartbeat timers."""
        self.messaging.pump(timeout=0.001)
        if self.raft_member is not None:
            self.raft_member.tick()
            self.raft_member.flush_appends()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Node":
        """Register in the map, restore checkpoints, resume flows."""
        # Web API binds here, not in __init__: a constructed-but-unstarted
        # (or failed) node must not hold a listener or serve half-built
        # state (reference: Node.kt starts Jetty inside start()).
        if self.config.web_port is not None and self.webserver is None:
            from .webserver import NodeWebServer

            self.webserver = NodeWebServer(
                self, self.config.host, self.config.web_port)
        self.register_and_refresh_netmap()
        if self.config.map_node and self.config.map_node != self.config.name:
            # Dynamic directory: the bootstrap file told us where the map
            # node lives; from here on registration + updates ride the wire
            # (reference: AbstractNode.registerWithNetworkMap,
            # AbstractNode.kt:377-411).
            from .services.netmap_service import NetworkMapClient

            map_info = next(
                (n for n in self.network_map_cache.party_nodes
                 if n.legal_identity.name == self.config.map_node), None)
            if map_info is None:
                raise RuntimeError(
                    f"map node {self.config.map_node!r} not in bootstrap map")
            self.netmap_client = NetworkMapClient(
                self.messaging, map_info.address, self.network_map_cache,
                self.identity_service, self.key)
            self.netmap_client.register(self.info)
            self.netmap_client.fetch_and_subscribe()
        # The warm gate must be on the verifier BEFORE checkpoint restore
        # runs: smm.start() replays checkpointed flows, and a restored
        # backlog can flush a >= device_min_sigs batch immediately — with
        # no gate yet installed it would hit the cold device and stall the
        # restart exactly like the pre-warm-up boot did.
        self._warm_verifier_maybe()
        self.smm.start()
        if self.scrubber is not None:
            self.scrubber.start()
        self._started = True
        return self

    def _warm_verifier_maybe(self) -> None:
        """Background-warm a device-backed verifier at boot: lazy backend
        init + first-kernel compile were measured stalling the notary run
        loop ~100 s at the FIRST >= device_min_sigs batch (r5: the
        raft-validating p50 hit 100 s while closed-loop traffic queued
        behind the init). A daemon thread pays that cost during cluster
        spin-up instead; the GIL is released inside device init/compile,
        so the run loop keeps serving. Never blocks and never fails boot —
        a dead tunnel degrades exactly like the cold path did."""
        verifier = self.smm.verifier
        if not getattr(verifier, "name", "").startswith("jax"):
            return
        import threading

        gate = threading.Event()
        # Until the warm-up finishes, the provider routes every batch to
        # the host tier (provider.py device_gate): a real batch arriving
        # mid-init would otherwise block the run loop on the backend lock
        # — the exact stall the warm-up exists to remove.
        verifier.device_gate = gate

        def warm():
            try:
                import jax

                if jax.devices()[0].platform == "cpu":
                    # Host backend: XLA CPU compiles are cheap enough to
                    # pay in-loop (and test processes must not carry a
                    # long-lived compile thread into interpreter exit —
                    # a live thread inside XLA C++ at teardown aborts).
                    gate.set()
                    return
                # The verifier compiles ITS OWN device path (JaxVerifier:
                # the single-chip kernel; MeshVerifier: the sharded
                # graphs) at both pump bucket sizes. On the axon platform
                # these are genuine per-process compiles (~107 s/bucket):
                # the persistent cache is populated but never loads there.
                verifier.warm()
            except Exception:
                logging.getLogger("corda_tpu.node").exception(
                    "verifier warm-up failed (device stays host-gated; "
                    "restart the node to retry device verification)")
            else:
                gate.set()

        self._warm_thread = threading.Thread(
            target=warm, daemon=True,
            name=f"warm-verifier-{self.config.name}")
        self._warm_thread.start()

    def start_flow(self, logic) -> FlowHandle:
        return self.smm.add(logic)

    def run_once(self, timeout: float = 0.05) -> int:
        """One scheduling round: dispatch inbound messages, then apply the
        max-wait micro-batch policy. Returns messages dispatched.

        The whole round runs inside ONE db transaction (db.batch): every
        checkpoint, outbox frame, dedupe record and commit-log write the
        round produces becomes durable in a single commit, and only then
        does the transport ACK the round's inbound messages + wake outbound
        bridges (messaging.flush_round) — one fsync per round instead of
        one per mutation, with the same at-least-once redelivery contract."""
        batch = self.config.batch
        svc = self.smm.async_verify
        wait = timeout
        if self.smm.verify_pending_sigs:
            # Shrink the wait so the flush deadline is honoured.
            deadline = (self.smm.verify_waiting_since
                        + batch.max_wait_ms / 1e3)
            wait = max(0.0, min(timeout, deadline - time.monotonic()))
        if svc is not None and svc.in_flight:
            # A batch is on the feeder thread: come back quickly so its
            # completion (and the flows it resumes) isn't left sitting a
            # full idle timeout behind the device.
            wait = min(wait, 0.002)
        stages = self.smm.metrics.setdefault(
            "round_stage_s", {"lock": 0.0, "pump": 0.0, "raft": 0.0,
                              "services": 0.0, "verify": 0.0,
                              "verify_drain": 0.0, "verify_submit": 0.0,
                              "checkpoint": 0.0, "commit": 0.0, "rounds": 0})
        # Round profiler (obs/telemetry.py ROUND_PHASES): the always-on
        # breakdown that attributes round wall time to named sub-phases —
        # round_stage_s answers "which code block", this answers "which
        # pipeline phase" (and the raft segment is split seal/replicate/
        # apply from the leader's own phase accumulators).
        rp = self.smm.metrics.setdefault(
            "round_phase_s", {"poll": 0.0, "verify_wait": 0.0, "seal": 0.0,
                              "replicate": 0.0, "apply": 0.0, "reply": 0.0,
                              "wall": 0.0, "rounds": 0})
        rm = self.raft_member
        raft_pre = ((rm.phase_s["seal"], rm.phase_s["replicate"],
                     rm.phase_s["apply"]) if rm is not None else None)
        # Pipelined commit plane: executor wall time overlapped under this
        # round (accumulated by the executor thread, read as a delta here).
        # Tracked BESIDE the six phases — see format_breakdown's overlap
        # block — so phase coverage stays a partition of loop wall time.
        overlap_pre = (rm.overlap_s["apply"] if rm is not None else 0.0)
        t = time.perf_counter
        t_pre = t()
        try:
            with self.db.batch():
                t0 = t()
                stages["lock"] += t0 - t_pre
                n = self.messaging.pump(timeout=wait, max_messages=512,
                                        coalesce=batch.coalesce_ms / 1e3)
                t1 = t()
                if self.raft_member is not None:
                    self.raft_member.tick()
                t2 = t()
                self.smm.poll_services()
                t3 = t()
                # Drain completed async verifies BEFORE flush_appends so a
                # raft commit submitted by a verify-resumed notary flow
                # replicates in THIS round's AppendEntries.
                self.smm.drain_async_verifies()
                t3d = t()
                if self.raft_member is not None:
                    # poll_services may have submitted commits; replicate
                    # them in THIS round (one coalesced AppendEntries per
                    # peer).
                    self.raft_member.flush_appends()
                t4 = t()
                self.scheduler.tick()
                pending = self.smm.verify_pending_sigs
                aged = pending and (
                    time.monotonic() - self.smm.verify_waiting_since
                    >= batch.max_wait_ms / 1e3)
                # Deadline-aware coalescing (QoS queueing point 1): an
                # interactive request's SLO deadline entering the guard
                # window flushes the micro-batch NOW instead of waiting
                # out max_wait_ms. False whenever the plane is disarmed.
                rushed = pending and self.smm.verify_deadline_pressure()
                if rushed and not aged and (svc is None
                                            or svc.can_submit()):
                    _qos.ACTIVE.counters["verify_early_flushes"] += 1
                    if _obs.ACTIVE is not None:
                        mark = _obs.now()
                        _obs.record("qos_flush", mark, mark,
                                    attrs={"point": "verify_batch"})
                if svc is not None:
                    # Pipelined: submit and continue. The gate targets the
                    # device crossover (accumulating ACROSS rounds) once
                    # the kernel is warm; a full pipeline keeps
                    # accumulating — bounded by depth, drained above.
                    if pending and svc.can_submit() and (
                            pending >= svc.target_sigs(batch.max_sigs)
                            or aged or rushed):
                        self.smm.submit_pending_verifies()
                elif pending and (pending >= batch.max_sigs or aged
                                  or rushed):
                    self.smm.flush_pending_verifies()
                t5 = t()
                self.smm.flush_checkpoints()
                if self.rpc is not None:
                    # Server-push: stream new change-feed events to RPC
                    # subscribers inside the round (the frames ride the
                    # durable outbox committed with it).
                    self.rpc.push_pending()
                t6 = t()
                # Stage accounting (cheap: 8 clock reads per round) is the
                # attribution artifact for the process-boundary throughput
                # work — exported via node_metrics like every counter.
                stages["pump"] += t1 - t0
                stages["raft"] += (t2 - t1) + (t4 - t3d)
                stages["services"] += t3 - t2
                stages["verify"] += (t3d - t3) + (t5 - t4)
                stages["verify_drain"] += t3d - t3
                stages["verify_submit"] += t5 - t4
                stages["checkpoint"] += t6 - t5
                stages["rounds"] += 1
        except BaseException as exc:
            # The round rolled back: its deferred ACKs must not be sent
            # (senders redeliver) and in-memory flow state is now AHEAD of
            # durable state — the process should be restarted; recovery
            # replays from the last committed round.
            abort = getattr(self.messaging, "abort_round", None)
            if abort is not None:
                abort()
            if isinstance(exc, Exception):
                # Crash dump (flight recorder, latched + never-raising):
                # the last window of metric deltas and spans, captured at
                # the failure, not at the post-restart repro attempt.
                # Shutdown paths (KeyboardInterrupt/SystemExit) are not
                # crashes and dump nothing.
                _tm.flight_trigger("crash", extra={
                    "error": f"{type(exc).__name__}: {exc}",
                    "node": self.config.name})
            raise
        stages["commit"] += t() - t6  # db.batch() exit = the round fsync
        t_end = t()
        rp["rounds"] += 1
        rp["wall"] += t_end - t_pre
        poll = t1 - t_pre
        verify_wait = (t3d - t3) + (t5 - t4)
        apply_s = t3 - t2  # service polling applies committed work
        reply = (t6 - t5) + (t_end - t6)  # checkpoint/push + round fsync
        seal_d = repl_d = 0.0
        if raft_pre is not None:
            seal_d = rm.phase_s["seal"] - raft_pre[0]
            repl_d = rm.phase_s["replicate"] - raft_pre[1]
            raft_apply_d = rm.phase_s["apply"] - raft_pre[2]
            apply_s += raft_apply_d
            # Whatever of the round's raft segment the leader phases did
            # not claim (tick bookkeeping, follower forwarding, election
            # checks) moves replication state — attribute it there rather
            # than inventing an "other" phase.
            repl_d += max(0.0, ((t2 - t1) + (t4 - t3d))
                          - seal_d - repl_d - raft_apply_d)
        rp["poll"] += poll
        rp["verify_wait"] += verify_wait
        rp["seal"] += seal_d
        rp["replicate"] += repl_d
        rp["apply"] += apply_s
        rp["reply"] += reply
        if rm is not None:
            overlap_d = rm.overlap_s["apply"] - overlap_pre
            if overlap_d > 0.0:
                rp["overlap_apply"] = (
                    rp.get("overlap_apply", 0.0) + overlap_d)
                if _tm.ACTIVE is not None:
                    _tm.inc("round_overlap_apply_seconds_total", overlap_d)
        if _tm.ACTIVE is not None:
            _tm.observe_round(t_end - t_pre, {
                "poll": poll, "verify_wait": verify_wait, "seal": seal_d,
                "replicate": repl_d, "apply": apply_s, "reply": reply})
        flush = getattr(self.messaging, "flush_round", None)
        if flush is not None:
            flush()
        self._sample_metrics_maybe()
        self._reshard_tick()
        return n

    # Counters HISTORY (the time-series half of the reference's JMX/Jolokia
    # export, reference: Node.kt:313,163): the run loop snapshots the metric
    # registry on a fixed cadence into a bounded ring served at
    # /api/metrics/history — a scrape-less monitoring bridge.
    METRICS_SAMPLE_S = 5.0
    METRICS_HISTORY_KEEP = 720  # one hour at the 5 s cadence

    _metrics_sampled_at = 0.0

    def _sample_metrics_maybe(self) -> None:
        now = time.monotonic()
        if now - self._metrics_sampled_at < self.METRICS_SAMPLE_S:
            return
        self._metrics_sampled_at = now
        snap = {k: (dict(v) if isinstance(v, dict) else v)
                for k, v in self.smm.metrics.items()}
        snap["ts"] = round(time.time(), 3)
        snap["flows_in_flight"] = self.smm.in_flight_count
        # The formatted round profile travels with every history sample so
        # the time-series shows phase SHARES drifting, not just raw sums.
        snap["round_breakdown"] = _tm.format_breakdown(
            self.smm.metrics.get("round_phase_s"))
        self.metrics_history.append(snap)  # deque(maxlen=KEEP) self-trims
        if _tm.ACTIVE is not None and _tm.ACTIVE.flight is not None:
            _tm.ACTIVE.flight.tick(_tm.ACTIVE.snapshot()["counters"])

    def run_forever(self) -> None:
        while True:
            self.run_once(timeout=0.05)
            self.refresh_netmap_maybe()

    # -- elastic resharding ------------------------------------------------

    RESHARD_FENCE_POLL_S = 0.2

    def _reshard_tick(self) -> None:
        """Advance the elastic-reshard machinery, once per run-loop round.
        Two halves, both no-ops outside a transition: (a) observe the local
        group's APPLIED fence (every RESHARD_FENCE_POLL_S — the fence only
        moves while a plan is live) and re-advertise the epoch'd service
        string once it activates, so clients re-deriving the directory see
        the new map; (b) drive the provider's handoff coordinator (active
        only on the source group's current leader)."""
        prov = self.uniqueness_provider
        if prov is None or not hasattr(prov, "reshard_tick"):
            return
        now = time.monotonic()
        if (self._reshard_plan is not None
                and now - self._fence_checked_at >= self.RESHARD_FENCE_POLL_S):
            self._fence_checked_at = now
            self._observe_fence()
        prov.reshard_tick(self._reshard_plan, now)

    def _observe_fence(self) -> None:
        """Align the advertisement + routing with the group's applied fence
        state. Every member does this from its OWN replicated state (not
        from the plan): a follower that applied the activation re-registers
        even if the coordinator died right after committing it."""
        import json as _json

        raw = self.db.get_setting("shard_fence")
        if not raw:
            return
        fence = _json.loads(raw)
        mode = fence.get("mode")
        if mode not in ("active", "retired"):
            return  # sealed/importing: keep the old advertisement
        epoch = int(fence["epoch"])
        if epoch <= self._shard_epoch_advertised:
            return
        from .services.sharding import (
            SHARD_SERVICE_PREFIX,
            shard_service_string,
        )

        base = tuple(s for s in self.info.advertised_services
                     if not str(s.type).startswith(SHARD_SERVICE_PREFIX))
        if mode == "active":
            base += (ServiceInfo(ServiceType(shard_service_string(
                int(fence["group"]), int(fence["count"]), epoch))),)
        # mode == "retired": the shard string is dropped — the member keeps
        # serving its raft group (so lagging replicas can catch up and
        # in-flight replies drain) but no client routes new work at it.
        self.info = NodeInfo(
            address=self.info.address,
            legal_identity=self.info.legal_identity,
            advertised_services=base,
        )
        path = self.config.network_map
        if path is not None:
            netmap_register(
                path, self.config.name, self.messaging.my_address.host,
                self.messaging.my_address.port, self.identity.owning_key,
                tuple(str(s.type) for s in self.info.advertised_services))
        self._shard_epoch_advertised = epoch
        self.uniqueness_provider.reconfigure(int(fence["count"]), epoch)

    _netmap_refreshed_at = 0.0

    def refresh_netmap_maybe(self, every: float = 1.0) -> None:
        now = time.monotonic()
        if now - self._netmap_refreshed_at >= every:
            self._netmap_refreshed_at = now
            self.refresh_netmap()

    _warm_thread = None

    def stop(self) -> None:
        if self.webserver is not None:
            self.webserver.stop()
        svc = self.smm.async_verify
        if svc is not None and not svc.close(timeout=30.0):
            # Same interpreter-exit hazard as the warm thread below: a
            # feeder blocked inside a wedged device call cannot be joined;
            # report and prefer process death over finalization.
            logging.getLogger("corda_tpu.node").warning(
                "async verify feeder still running after stop(); "
                "interpreter exit may abort — exit this process via "
                "process death, not finalization")
        self.messaging.stop()
        if self.scrubber is not None:
            # Before db.close(): the scrubber holds its own connection, but
            # a pass racing teardown must wind down while the store is
            # still guaranteed to exist.
            self.scrubber.stop()
        self.db.close()
        if self._warm_thread is not None and self._warm_thread.is_alive():
            # An in-process (test/embedded) node must not carry a live
            # compile thread into interpreter exit — XLA C++ aborts when a
            # cancelled pthread unwinds through it. CPU warms finish in
            # seconds, well inside the bound; a REAL-device warm can run
            # minutes (and a wedged tunnel, indefinitely), so the join
            # stays bounded — stop() must never hang — and a timeout is
            # reported loudly: the embedder should prefer process exit
            # (os._exit / child-process nodes, the production topology)
            # over interpreter finalization while the device is warming.
            self._warm_thread.join(timeout=30.0)
            if self._warm_thread.is_alive():
                logging.getLogger("corda_tpu.node").warning(
                    "verifier warm-up still compiling after stop(); "
                    "interpreter exit may abort — exit this process via "
                    "process death, not finalization")


def main(argv: list[str] | None = None) -> int:
    import faulthandler
    import signal

    # Operator diagnostics: `kill -USR1 <pid>` dumps every thread's stack to
    # stderr (the node.log) — the moral equivalent of a JVM thread dump.
    faulthandler.register(signal.SIGUSR1, all_threads=True)
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m corda_tpu.node.node <config.toml>",
              file=sys.stderr)
        return 2
    config = NodeConfig.load(argv[0])
    # Chaos harness: CORDA_TPU_FAULT_PLAN=<plan.toml> arms a deterministic
    # fault plan for this process (per-node rules filter on config.name).
    from ..testing import faults as _faults

    _faults.arm_from_env(config.name)
    # Tracing: CORDA_TPU_TRACE=1 (or a span capacity) arms the per-process
    # SpanRecorder; spans export via /api/trace + the trace_snapshot RPC.
    from ..obs import trace as _obs

    _obs.arm_from_env(config.name)
    # QoS plane: normally armed from [qos] in the config (Node.__init__);
    # CORDA_TPU_QOS arms it env-wise for ad-hoc runs. A no-op when unset.
    _qos.arm_from_env(config.name)
    # Flight recorder (obs/telemetry.py): CORDA_TPU_FLIGHT_DIR=<dir> arms
    # auto-dumps for this process (fsck failure, crash, overload spike).
    # Attached BEFORE the fsck gate so a corrupt boot is itself captured.
    _tm.ensure_flight(node=config.name)
    # Boot fsck: verify the store's integrity frames before serving.
    # Log-only here — corruption found at boot is reported loudly and then
    # handled by the online planes (raft heal / checkpoint quarantine);
    # operators wanting a hard gate run `python -m corda_tpu.tools.fsck
    # <base-dir> --repair` before start.
    try:
        from ..tools.fsck import fsck_paths

        report = fsck_paths(config.base_dir)
        if not report["clean"]:
            logging.getLogger("corda_tpu.node").error(
                "boot fsck: %d corrupt row(s) across %d store(s) — "
                "self-healing will repair what consensus can; run "
                "corda_tpu.tools.fsck --repair for the rest",
                report["corrupt"], report["stores"])
            # Capture the corrupt-boot evidence at the moment it was
            # found (latched; a crash-restart loop dumps once).
            _tm.flight_trigger("fsck_failure", extra={
                k: report[k] for k in ("path", "stores", "clean",
                                       "corrupt", "scanned")})
    except Exception:
        # Never block boot on the checker itself (e.g. a locked store
        # during a crash-restart race) — the online scrubber covers it.
        logging.getLogger("corda_tpu.node").exception("boot fsck failed")
    node = Node(config).start()
    print(f"node {config.name} up at {node.messaging.my_address}", flush=True)
    # Attribution hook: CORDA_TPU_NODE_PROFILE=<dir> dumps a cProfile of
    # the whole run loop to <dir>/<name>.pstats on shutdown (SIGTERM
    # included) — how the process-boundary throughput gap was measured.
    profile_dir = os.environ.get("CORDA_TPU_NODE_PROFILE")
    profiler = None
    if profile_dir:
        import cProfile

        profiler = cProfile.Profile()
        profiler.enable()

        def _dump(signum=None, frame=None):
            profiler.disable()
            path = os.path.join(profile_dir, f"{config.name}.pstats")
            try:
                profiler.dump_stats(path)
            finally:
                if signum is not None:
                    raise SystemExit(0)

        signal.signal(signal.SIGTERM, _dump)
    try:
        node.run_forever()
    except (KeyboardInterrupt, SystemExit):
        node.stop()
    finally:
        if profiler is not None:
            _dump()
    return 0


if __name__ == "__main__":
    sys.exit(main())
