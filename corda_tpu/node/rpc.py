"""RPC: the client-facing node API over the messaging transport.

Capability match for the reference's RPC tier (reference:
node/src/main/kotlin/net/corda/node/services/messaging/CordaRPCOps.kt:62-117
— the ops interface; RPCDispatcher.kt:33-60 — server-side dispatch;
client/src/main/kotlin/net/corda/client/CordaRPCClient.kt:29-60 — the client;
node/.../services/RPCUserService.kt — user/password auth from config).

Shape: requests ride the normal messaging transport on topic "platform.rpc"
as whitelisted codec payloads; the dispatcher authenticates, looks the method
up on NodeRpcOps (never arbitrary attributes), and replies to the sender's
address. Streams (the reference's Observables) map to polling methods with
explicit cursors — idiomatic for a request/reply transport and crash-safe
(a reconnecting client re-polls from its last cursor).

The client is deliberately node-free: it opens its own TcpMessaging endpoint,
so any process that can reach the node's socket can drive it.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any

from ..crypto.hashes import SecureHash
from ..flows.api import flow_registry
from ..obs import telemetry as _tm
from ..obs import trace as _obs
from ..qos import context as _qos
from ..serialization.codec import deserialize, register, serialize
from ..testing import faults as _faults
from .messaging.api import Message, MessagingService, TopicSession
from .services import integrity as _integrity

# Codec-whitelist imports: every type that can cross the RPC boundary must be
# REGISTERED in the client process too, and registration happens at module
# import. A standalone RpcClient (no Node constructed) would otherwise fail
# to deserialize replies containing NodeInfo, StateAndRef, SignedTransaction…
from ..contracts import structures as _structures  # noqa: F401
from ..transactions import signed as _signed  # noqa: F401
from .services import api as _services_api  # noqa: F401

RPC_TOPIC = "platform.rpc"


@register
@dataclass(frozen=True)
class RpcRequest:
    request_id: bytes
    user: str
    password: str
    method: str
    args: tuple = ()


@register
@dataclass(frozen=True)
class RpcReply:
    request_id: bytes
    ok: bool
    value: Any = None
    error: str | None = None


@register
@dataclass(frozen=True)
class FlowHandleInfo:
    """What start_flow returns over the wire."""

    run_id: bytes


@register
@dataclass(frozen=True)
class RpcPushEvent:
    """Server-push frame for a change subscription (the reference marshals
    rx Observables to per-client queues, RPCDispatcher.kt:33-60; here the
    stream rides the durable messaging transport as pushed frames with
    ABSOLUTE cursors, so a reconnecting client resumes from its last seen
    cursor without loss)."""

    subscription_id: bytes
    cursor: int         # absolute cursor AFTER `events`
    events: tuple       # (kind, run_id[, path]) tuples from the change log


@register
@dataclass(frozen=True)
class RpcUser:
    """reference: RPCUserService.kt — username/password/permissions."""

    username: str
    password: str
    permissions: tuple[str, ...] = ()  # flow names; ("ALL",) = everything

    def may_start(self, flow_name: str) -> bool:
        return "ALL" in self.permissions or flow_name in self.permissions


class NodeRpcOps:
    """The dispatchable surface (CordaRPCOps.kt:62-117 capability). Every
    public method here is callable over RPC — nothing else is."""

    def __init__(self, node):
        self._node = node

    # -- flows -------------------------------------------------------------

    def start_flow_dynamic(self, flow_name: str, args: tuple) -> FlowHandleInfo:
        logic = flow_registry.create(flow_name, tuple(args))
        handle = self._node.smm.add(logic)
        return FlowHandleInfo(run_id=handle.run_id)

    def flow_result(self, run_id: bytes):
        """(done, value) — poll until done; raises the flow's error."""
        fsm = self._node.smm.flows.get(run_id)
        if fsm is not None:
            if not fsm.future.done:
                return (False, None)
            return (True, fsm.future.result())
        future = self._node.smm.recent_results.get(run_id)
        if future is None:
            raise KeyError(f"unknown flow {run_id.hex()}")
        return (True, future.result())

    def state_machines_snapshot(self) -> tuple:
        return tuple(self._node.smm.flows.keys())

    def state_machine_changes(self, cursor: int) -> tuple:
        """(new_cursor, events since cursor) — the polling form of the
        reference's stateMachinesAndUpdates observable. Cursors are absolute
        indices into a bounded event log; evicted history is simply absent."""
        return self._node.smm.changes.since(cursor)

    # -- ledger ------------------------------------------------------------

    def vault_snapshot(self) -> tuple:
        return tuple(self._node.services.vault_service.current_vault.states)

    def vault_page(self, after_txhash: bytes | None = None,
                   after_index: int = 0, page_size: int = 256) -> tuple:
        """One keyset page of unconsumed states: (states, cursor) where
        cursor is (txhash_bytes, index) to pass back as after_* for the
        next page, or None at the end. The paginated sibling of
        vault_snapshot — a million-state vault streams page by page
        instead of one giant frame."""
        from .services.vault import VaultQuery

        after = None
        if after_txhash is not None:
            after = (bytes(after_txhash), int(after_index))
        page = self._node.services.vault_service.query(
            VaultQuery(after=after, page_size=int(page_size)))
        return (page.states, page.next_cursor)

    def vault_balances(self) -> dict:
        """Per-currency unconsumed totals — an O(1) aggregate read on the
        indexed engine, never a vault materialization."""
        return dict(self._node.services.vault_service.balances())

    def verified_transaction(self, tx_id: SecureHash):
        return self._node.services.storage_service.validated_transactions \
            .get_transaction(tx_id)

    def state_machine_recorded_transaction_mapping(self) -> tuple:
        """Snapshot of the flow-run → tx provenance log (reference:
        CordaRPCOps.kt:86). The observable half rides the push stream as
        ("tx_recorded", run_id, tx_id_bytes) change events — subscribe via
        subscribe_changes for live updates, poll this for the full join."""
        mapping = self._node.services.storage_service \
            .state_machine_recorded_transaction_mapping
        return tuple(mapping.mappings()) if mapping is not None else ()

    # -- network -----------------------------------------------------------

    def network_map_snapshot(self) -> tuple:
        return tuple(self._node.services.network_map_cache.party_nodes)

    def node_identity(self):
        return self._node.identity

    # -- observability (MonitoringService.kt:11 capability: the metric
    # registry, exported here over RPC instead of JMX) ---------------------

    def node_metrics(self) -> dict:
        smm = self._node.smm
        # Self-describing verification stamps (round-4 verdict: trend lines
        # silently changed meaning because nothing recorded WHICH verifier /
        # kernel backend produced a number).
        from ..ops import last_backend_if_loaded

        kernel_backend = last_backend_if_loaded()
        av_stats = (smm.async_verify.stats()
                    if smm.async_verify is not None else None)
        return dict(smm.metrics) | {
            "flows_in_flight": smm.in_flight_count,
            "verify_pending_sigs": smm.verify_pending_sigs,
            "verifier": getattr(smm.verifier, "name", None),
            "kernel_backend": kernel_backend,
            # Size-crossover routing (JaxVerifier/MeshVerifier): how many
            # batches actually went to the device vs the host tier.
            "verify_device_batches": getattr(
                smm.verifier, "device_batches", None),
            "verify_host_batches": getattr(
                smm.verifier, "host_batches", None),
            # Boot-warm gate state: True once the device kernel is warm,
            # False while warm-up is in flight (batches host-route until
            # then), None when no gate was installed (cpu verifier, or a
            # process that never warms).
            "verify_device_ready": (
                smm.verifier.device_gate.is_set()
                if getattr(smm.verifier, "device_gate", None) is not None
                else None),
            # The size crossover currently in force (the adaptive tuner
            # moves it at runtime); None for verifiers with no device tier.
            "verify_device_min_sigs": getattr(
                smm.verifier, "device_min_sigs", None),
            # The EFFECTIVE crossover: AdaptiveCrossover's learned value
            # previously lived only in memory — stamped so artifacts show
            # why traffic routed where it did. Falls back to the verifier's
            # live value when no tuner is attached (same number today,
            # since the tuner rewrites the verifier in place).
            "verify_effective_min_sigs": (
                (av_stats or {}).get(
                    "effective_min_sigs",
                    getattr(smm.verifier, "device_min_sigs", None))),
            "verify_static_min_sigs": (
                (av_stats or {}).get("static_min_sigs")),
            # Async pipeline counters (crypto/async_verify.py): submitted/
            # in-flight/completed batches, queue wait vs device wall, and
            # the adaptive crossover state; None in synchronous mode.
            "async_verify": av_stats,
            # Sidecar client stamps (node/verify_client.py): batches/sigs
            # shipped to the host's shared verify server, fallbacks,
            # degrade gate state; None when no sidecar is configured.
            "sidecar": (smm.verifier.sidecar_stats()
                        if hasattr(smm.verifier, "sidecar_stats")
                        else None),
            # Commit-pipeline stamps (services/raft.py): group-commit
            # entries/batch, pipelined-replication frames, reply coalescing,
            # replication RTT; None on non-raft nodes.
            "raft": (self._node.raft_member.stamp()
                     if getattr(self._node, "raft_member", None) is not None
                     else None),
            # Sharded-notary coordinator stamps (services/sharding.py):
            # fast-path vs cross-shard counts, aborts, reserve retries;
            # None when this node is not a shard member.
            "sharding": (self._node.uniqueness_provider.stamp()
                         if hasattr(getattr(self._node,
                                            "uniqueness_provider", None),
                                    "stamp")
                         else None),
            # Transport burst stamps (messaging/tcp.py): outbox executemany
            # bursts + bridge writev flushes; None on non-TCP fakes.
            "transport": (self._node.messaging.transport_stats()
                          if hasattr(self._node.messaging, "transport_stats")
                          else None),
            # Per-flow-name completion timings (count/total_ms/max_ms) —
            # the per-flow half of the reference's JMX metrics export.
            "flow_timings": {k: dict(v)
                             for k, v in smm.flow_timings.items()},
            # Armed fault-injection counters (testing/faults.py): fired
            # "point:action" counts, or None when no plan is armed — lets a
            # chaos harness audit what a node actually injected.
            "faults": (_faults.ACTIVE.injected()
                       if _faults.ACTIVE is not None else None),
            # Tracing recorder stamps (obs/trace.py): recorded/buffered/
            # dropped span counts, or None while disarmed.
            "obs": (_obs.ACTIVE.stats()
                    if _obs.ACTIVE is not None else None),
            # Durability plane stamps (services/integrity.py): process-wide
            # quarantine/shed counters plus this node's online-scrubber
            # scan/error counts when one is armed.
            "durability": _integrity.stats(
                getattr(self._node, "scrubber", None)),
            # QoS plane stamps (qos/context.py): per-lane flow counts,
            # anti-starvation picks, early flushes — plus the admission
            # controller's admitted/shed counters when one is attached to
            # the notary service. None while disarmed.
            "qos": (_qos.ACTIVE.stats()
                    if _qos.ACTIVE is not None else None),
            "admission": (
                self._node.notary_service.admission.stats()
                if getattr(getattr(self._node, "notary_service", None),
                           "admission", None) is not None else None),
            # Device-tier degrade bookkeeping (crypto/provider.py
            # degrade_device): demotions and re-probe outcomes.
            "verify_device_degrades": getattr(smm.verifier, "degraded", None),
            "verify_device_reprobes_ok": getattr(
                smm.verifier, "reprobes_ok", None),
            "verify_device_reprobes_failed": getattr(
                smm.verifier, "reprobes_failed", None),
            # Round profiler (obs/telemetry.py): the always-on per-phase
            # attribution of round wall time — the block that explains a
            # first_bottleneck of "rounds". None before the first round.
            "round_breakdown": _tm.format_breakdown(
                smm.metrics.get("round_phase_s")),
            # Process-global telemetry registry counters (the histogram
            # halves export via /metrics and telemetry_snapshot — counters
            # alone keep this stamp grep-sized). None only if a test
            # disarmed the always-on registry.
            "telemetry": ((_tm.snapshot() or {}).get("counters")
                          if _tm.ACTIVE is not None else None),
        }

    def telemetry_snapshot(self) -> dict:
        """The full telemetry registry (counters + histograms) for the
        driver-side cluster collector (obs/export.py collect_cluster) —
        the RPC twin of GET /metrics, JSON instead of exposition text so
        the collector merges exact sparse buckets, not parsed ones."""
        return {
            "node": self._node.config.name,
            "armed": _tm.ACTIVE is not None,
            "snapshot": _tm.snapshot(),
            "flight": (_tm.ACTIVE.flight.stats()
                       if _tm.ACTIVE is not None
                       and _tm.ACTIVE.flight is not None else None),
        }

    def trace_snapshot(self) -> dict:
        """This node's span buffer (obs/trace.py) for the driver-side trace
        collector — the RPC twin of GET /api/trace, so the loadtest can
        gather spans from cluster members that run without a webserver."""
        rec = _obs.ACTIVE
        return {
            "node": self._node.config.name,
            "armed": rec is not None,
            "spans": rec.snapshot() if rec is not None else [],
            "stats": rec.stats() if rec is not None else None,
        }


class RpcDispatcher:
    """Server side: authenticate, dispatch, reply (RPCDispatcher.kt:33-60).

    Also owns PUSH subscriptions: a client subscribes to the state-machine
    change feed once, and the node run loop pushes new events to the
    client's address as they appear (push_pending) — the reference's
    Observable-over-queues capability, with cursor-resume instead of
    handle counters. Subscriptions expire unless renewed (a vanished
    client must not grow an outbox forever).
    """

    SUBSCRIPTION_TTL_S = 120.0

    def __init__(self, node, users: tuple[RpcUser, ...]):
        self.ops = NodeRpcOps(node)
        self.users = {u.username: u for u in users}
        self._node = node
        self._messaging = node.messaging
        # subscription_id -> [sender_address, cursor, expires_at]
        self._subscriptions: dict[bytes, list] = {}
        self._messaging.add_message_handler(RPC_TOPIC, 0, self._on_request)

    def _on_request(self, message: Message) -> None:
        try:
            req = deserialize(message.data)
        except Exception:
            return
        if not isinstance(req, RpcRequest):
            return
        if req.method == "subscribe_changes":
            reply = self._handle_subscribe(req, message.sender)
        else:
            reply = self._handle(req)
        self._messaging.send(TopicSession(RPC_TOPIC, 1),
                             serialize(reply).bytes, message.sender)

    def _handle_subscribe(self, req: RpcRequest, sender) -> RpcReply:
        """subscribe_changes(subscription_id, cursor) — register (or renew/
        resume: same id re-subscribing keeps streaming from the given
        cursor, which is how a reconnecting client resumes without loss)."""
        user = self.users.get(req.user)
        if user is None or user.password != req.password:
            return RpcReply(req.request_id, False,
                            error="authentication failed")
        try:
            subscription_id, cursor = req.args
            subscription_id = bytes(subscription_id)
            cursor = int(cursor)
        except Exception:
            return RpcReply(req.request_id, False,
                            error="subscribe_changes(subscription_id, cursor)")
        head = len(self._node.smm.changes)
        # A cursor AHEAD of our head means the client outlived a node
        # restart (the change log reset): snap to head so the stream
        # resumes instead of stalling until the old cursor is re-reached.
        # The client snaps its own cursor from the returned head too.
        self._subscriptions[subscription_id] = [
            sender, min(cursor, head),
            time.monotonic() + self.SUBSCRIPTION_TTL_S]
        return RpcReply(req.request_id, True, value=head)

    def push_pending(self) -> int:
        """Push new change-feed events to every live subscription; called
        by the node run loop each round. Returns frames pushed."""
        if not self._subscriptions:
            return 0
        now = time.monotonic()
        pushed = 0
        for sid in list(self._subscriptions):
            entry = self._subscriptions[sid]
            sender, cursor, expires_at = entry
            if now > expires_at:
                del self._subscriptions[sid]
                continue
            new_cursor, events = self._node.smm.changes.since(cursor)
            if not events:
                continue
            frame = RpcPushEvent(sid, new_cursor, tuple(events))
            self._messaging.send(TopicSession(RPC_TOPIC, 2),
                                 serialize(frame).bytes, sender)
            entry[1] = new_cursor
            pushed += 1
        return pushed

    def _handle(self, req: RpcRequest) -> RpcReply:
        user = self.users.get(req.user)
        if user is None or user.password != req.password:
            return RpcReply(req.request_id, False, error="authentication failed")
        if req.method.startswith("_") or not hasattr(NodeRpcOps, req.method):
            return RpcReply(req.request_id, False,
                            error=f"no such method {req.method!r}")
        if req.method == "start_flow_dynamic" and not user.may_start(
                req.args[0] if req.args else ""):
            return RpcReply(req.request_id, False,
                            error=f"user {req.user!r} may not start "
                                  f"{req.args[0] if req.args else '?'}")
        try:
            value = getattr(self.ops, req.method)(*req.args)
            return RpcReply(req.request_id, True, value=value)
        except Exception as e:
            return RpcReply(req.request_id, False,
                            error=f"{type(e).__name__}: {e}")


class RpcError(Exception):
    pass


class RpcClient:
    """Client proxy (CordaRPCClient.kt:29-60 capability): opens its own
    transport endpoint and round-trips requests to the node's address."""

    def __init__(self, node_address, user: str, password: str,
                 host: str = "127.0.0.1", timeout: float = 15.0):
        from .messaging.tcp import TcpMessaging

        self._node_address = node_address
        self._user, self._password = user, password
        self.timeout = timeout
        self._messaging = TcpMessaging(host, 0).start()
        self._replies: dict[bytes, RpcReply] = {}
        self._decode_errors: list[str] = []
        self._push_callbacks: dict[bytes, Any] = {}
        self._push_cursor: dict[bytes, int] = {}
        # subscription_id -> count of events lost to server-side eviction
        # (the push stream is lossless only within the server's bounded
        # retention window; holes are detected and counted, never silent).
        self.push_gaps: dict[bytes, int] = {}
        self._messaging.add_message_handler(RPC_TOPIC, 1, self._on_reply)
        self._messaging.add_message_handler(RPC_TOPIC, 2, self._on_push)

    def _on_reply(self, message: Message) -> None:
        try:
            reply = deserialize(message.data)
        except Exception as e:
            # The request_id is inside the undecodable payload, so the
            # matching call() cannot be resolved — but it must NOT time out
            # silently: the usual cause is a reply type whose codec
            # registration module was never imported in THIS process, and
            # that is a caller bug worth a loud message.
            self._decode_errors.append(f"{type(e).__name__}: {e}")
            return
        if isinstance(reply, RpcReply):
            self._replies[reply.request_id] = reply

    def call(self, method: str, *args):
        request_id = os.urandom(12)
        req = RpcRequest(request_id, self._user, self._password, method,
                         tuple(args))
        # Only decode failures observed DURING this call are attributed to
        # it: a previous call's late undecodable reply must not poison an
        # unrelated method.
        self._decode_errors.clear()
        self._messaging.send(TopicSession(RPC_TOPIC, 0),
                             serialize(req).bytes, self._node_address)
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            self._messaging.pump(timeout=0.05)
            reply = self._replies.pop(request_id, None)
            if reply is not None:
                if not reply.ok:
                    raise RpcError(reply.error)
                return reply.value
        # A decode error seen during the call is most likely OUR reply (a
        # value type whose codec registration module was never imported in
        # this process) — but it could also be a previous call's late
        # arrival, so it must not abort a call whose own reply may still
        # decode; it is attached to the timeout instead of being swallowed.
        msg = f"rpc {method} timed out after {self.timeout}s"
        if self._decode_errors:
            errors, self._decode_errors = self._decode_errors, []
            msg += ("; undecodable replies arrived during the call (is the "
                    "value's codec registration module imported in this "
                    "process?): " + "; ".join(errors))
        raise RpcError(msg)

    # -- push subscriptions -----------------------------------------------

    def _on_push(self, message: Message) -> None:
        try:
            frame = deserialize(message.data)
        except Exception as e:
            self._decode_errors.append(f"{type(e).__name__}: {e}")
            return
        if not isinstance(frame, RpcPushEvent):
            return
        callback = self._push_callbacks.get(frame.subscription_id)
        if callback is None:
            return
        # Frames carry the ABSOLUTE cursor after their events; the
        # at-least-once transport may redeliver, so trim anything at or
        # below our last seen cursor instead of double-delivering.
        last = self._push_cursor.get(frame.subscription_id, 0)
        if frame.cursor <= last:
            return
        start = frame.cursor - len(frame.events)
        if start > last:
            # Events between `last` and `start` were evicted server-side
            # before we caught up (resume is lossless only within the
            # server's bounded retention window). Never silently: count
            # the hole and log it so a monitoring UI can say "feed
            # incomplete" instead of showing stale truth.
            self.push_gaps[frame.subscription_id] = (
                self.push_gaps.get(frame.subscription_id, 0)
                + (start - last))
            import logging

            logging.getLogger("corda_tpu.rpc").warning(
                "push subscription %s lost %d evicted events",
                frame.subscription_id.hex()[:8], start - last)
        events = frame.events[max(0, last - start):]
        self._push_cursor[frame.subscription_id] = frame.cursor
        callback(tuple(events), frame.cursor)

    def subscribe_changes(self, callback, subscription_id: bytes | None = None,
                          cursor: int | None = None) -> bytes:
        """Server-push subscription to the node's state-machine change feed
        (flow add/remove/progress events). `callback(events, cursor)` fires
        during any transport pump (a call() or poll_push()). Re-invoke with
        the SAME id after a reconnect to resume from the last seen cursor —
        lossless within the server's bounded retention window; larger holes
        are detected and counted in `push_gaps`, never skipped silently.
        Re-invoke periodically (< the server's 120 s TTL) to keep the
        subscription alive."""
        sid = subscription_id or os.urandom(12)
        self._push_callbacks[sid] = callback
        if cursor is None:
            cursor = self._push_cursor.get(sid, 0)
        self._push_cursor.setdefault(sid, cursor)
        head = self.call("subscribe_changes", sid, cursor)
        if isinstance(head, int) and head < self._push_cursor[sid]:
            # Our cursor is beyond the server's head: the node restarted
            # and its change log reset. Snap down so the resumed stream's
            # frames are not dropped as duplicates (the server snapped its
            # stored cursor the same way).
            self._push_cursor[sid] = head
        return sid

    def poll_push(self, timeout: float = 0.05) -> None:
        """Give pushed frames a chance to arrive outside of call()s."""
        self._messaging.pump(timeout=timeout)

    # -- convenience wrappers ---------------------------------------------

    def start_flow(self, flow_name: str, *args) -> FlowHandleInfo:
        return self.call("start_flow_dynamic", flow_name, tuple(args))

    def wait_for_flow(self, handle: FlowHandleInfo, timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            done, value = self.call("flow_result", handle.run_id)
            if done:
                return value
            time.sleep(0.05)
        raise RpcError("flow did not finish in time")

    def close(self) -> None:
        self._messaging.stop()
