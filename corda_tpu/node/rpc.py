"""RPC: the client-facing node API over the messaging transport.

Capability match for the reference's RPC tier (reference:
node/src/main/kotlin/net/corda/node/services/messaging/CordaRPCOps.kt:62-117
— the ops interface; RPCDispatcher.kt:33-60 — server-side dispatch;
client/src/main/kotlin/net/corda/client/CordaRPCClient.kt:29-60 — the client;
node/.../services/RPCUserService.kt — user/password auth from config).

Shape: requests ride the normal messaging transport on topic "platform.rpc"
as whitelisted codec payloads; the dispatcher authenticates, looks the method
up on NodeRpcOps (never arbitrary attributes), and replies to the sender's
address. Streams (the reference's Observables) map to polling methods with
explicit cursors — idiomatic for a request/reply transport and crash-safe
(a reconnecting client re-polls from its last cursor).

The client is deliberately node-free: it opens its own TcpMessaging endpoint,
so any process that can reach the node's socket can drive it.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any

from ..crypto.hashes import SecureHash
from ..flows.api import flow_registry
from ..serialization.codec import deserialize, register, serialize
from .messaging.api import Message, MessagingService, TopicSession

# Codec-whitelist imports: every type that can cross the RPC boundary must be
# REGISTERED in the client process too, and registration happens at module
# import. A standalone RpcClient (no Node constructed) would otherwise fail
# to deserialize replies containing NodeInfo, StateAndRef, SignedTransaction…
from ..contracts import structures as _structures  # noqa: F401
from ..transactions import signed as _signed  # noqa: F401
from .services import api as _services_api  # noqa: F401

RPC_TOPIC = "platform.rpc"


@register
@dataclass(frozen=True)
class RpcRequest:
    request_id: bytes
    user: str
    password: str
    method: str
    args: tuple = ()


@register
@dataclass(frozen=True)
class RpcReply:
    request_id: bytes
    ok: bool
    value: Any = None
    error: str | None = None


@register
@dataclass(frozen=True)
class FlowHandleInfo:
    """What start_flow returns over the wire."""

    run_id: bytes


@register
@dataclass(frozen=True)
class RpcUser:
    """reference: RPCUserService.kt — username/password/permissions."""

    username: str
    password: str
    permissions: tuple[str, ...] = ()  # flow names; ("ALL",) = everything

    def may_start(self, flow_name: str) -> bool:
        return "ALL" in self.permissions or flow_name in self.permissions


class NodeRpcOps:
    """The dispatchable surface (CordaRPCOps.kt:62-117 capability). Every
    public method here is callable over RPC — nothing else is."""

    def __init__(self, node):
        self._node = node

    # -- flows -------------------------------------------------------------

    def start_flow_dynamic(self, flow_name: str, args: tuple) -> FlowHandleInfo:
        logic = flow_registry.create(flow_name, tuple(args))
        handle = self._node.smm.add(logic)
        return FlowHandleInfo(run_id=handle.run_id)

    def flow_result(self, run_id: bytes):
        """(done, value) — poll until done; raises the flow's error."""
        fsm = self._node.smm.flows.get(run_id)
        if fsm is not None:
            if not fsm.future.done:
                return (False, None)
            return (True, fsm.future.result())
        future = self._node.smm.recent_results.get(run_id)
        if future is None:
            raise KeyError(f"unknown flow {run_id.hex()}")
        return (True, future.result())

    def state_machines_snapshot(self) -> tuple:
        return tuple(self._node.smm.flows.keys())

    def state_machine_changes(self, cursor: int) -> tuple:
        """(new_cursor, events since cursor) — the polling form of the
        reference's stateMachinesAndUpdates observable. Cursors are absolute
        indices into a bounded event log; evicted history is simply absent."""
        return self._node.smm.changes.since(cursor)

    # -- ledger ------------------------------------------------------------

    def vault_snapshot(self) -> tuple:
        return tuple(self._node.services.vault_service.current_vault.states)

    def verified_transaction(self, tx_id: SecureHash):
        return self._node.services.storage_service.validated_transactions \
            .get_transaction(tx_id)

    # -- network -----------------------------------------------------------

    def network_map_snapshot(self) -> tuple:
        return tuple(self._node.services.network_map_cache.party_nodes)

    def node_identity(self):
        return self._node.identity

    # -- observability (MonitoringService.kt:11 capability: the metric
    # registry, exported here over RPC instead of JMX) ---------------------

    def node_metrics(self) -> dict:
        smm = self._node.smm
        return dict(smm.metrics) | {
            "flows_in_flight": smm.in_flight_count,
            "verify_pending_sigs": smm.verify_pending_sigs,
        }


class RpcDispatcher:
    """Server side: authenticate, dispatch, reply (RPCDispatcher.kt:33-60)."""

    def __init__(self, node, users: tuple[RpcUser, ...]):
        self.ops = NodeRpcOps(node)
        self.users = {u.username: u for u in users}
        self._messaging = node.messaging
        self._messaging.add_message_handler(RPC_TOPIC, 0, self._on_request)

    def _on_request(self, message: Message) -> None:
        try:
            req = deserialize(message.data)
        except Exception:
            return
        if not isinstance(req, RpcRequest):
            return
        reply = self._handle(req)
        self._messaging.send(TopicSession(RPC_TOPIC, 1),
                             serialize(reply).bytes, message.sender)

    def _handle(self, req: RpcRequest) -> RpcReply:
        user = self.users.get(req.user)
        if user is None or user.password != req.password:
            return RpcReply(req.request_id, False, error="authentication failed")
        if req.method.startswith("_") or not hasattr(NodeRpcOps, req.method):
            return RpcReply(req.request_id, False,
                            error=f"no such method {req.method!r}")
        if req.method == "start_flow_dynamic" and not user.may_start(
                req.args[0] if req.args else ""):
            return RpcReply(req.request_id, False,
                            error=f"user {req.user!r} may not start "
                                  f"{req.args[0] if req.args else '?'}")
        try:
            value = getattr(self.ops, req.method)(*req.args)
            return RpcReply(req.request_id, True, value=value)
        except Exception as e:
            return RpcReply(req.request_id, False,
                            error=f"{type(e).__name__}: {e}")


class RpcError(Exception):
    pass


class RpcClient:
    """Client proxy (CordaRPCClient.kt:29-60 capability): opens its own
    transport endpoint and round-trips requests to the node's address."""

    def __init__(self, node_address, user: str, password: str,
                 host: str = "127.0.0.1", timeout: float = 15.0):
        from .messaging.tcp import TcpMessaging

        self._node_address = node_address
        self._user, self._password = user, password
        self.timeout = timeout
        self._messaging = TcpMessaging(host, 0).start()
        self._replies: dict[bytes, RpcReply] = {}
        self._messaging.add_message_handler(RPC_TOPIC, 1, self._on_reply)

    def _on_reply(self, message: Message) -> None:
        try:
            reply = deserialize(message.data)
        except Exception:
            return
        if isinstance(reply, RpcReply):
            self._replies[reply.request_id] = reply

    def call(self, method: str, *args):
        request_id = os.urandom(12)
        req = RpcRequest(request_id, self._user, self._password, method,
                         tuple(args))
        self._messaging.send(TopicSession(RPC_TOPIC, 0),
                             serialize(req).bytes, self._node_address)
        deadline = time.monotonic() + self.timeout
        while time.monotonic() < deadline:
            self._messaging.pump(timeout=0.05)
            reply = self._replies.pop(request_id, None)
            if reply is not None:
                if not reply.ok:
                    raise RpcError(reply.error)
                return reply.value
        raise RpcError(f"rpc {method} timed out after {self.timeout}s")

    # -- convenience wrappers ---------------------------------------------

    def start_flow(self, flow_name: str, *args) -> FlowHandleInfo:
        return self.call("start_flow_dynamic", flow_name, tuple(args))

    def wait_for_flow(self, handle: FlowHandleInfo, timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            done, value = self.call("flow_result", handle.run_id)
            if done:
                return value
            time.sleep(0.05)
        raise RpcError("flow did not finish in time")

    def close(self) -> None:
        self._messaging.stop()
