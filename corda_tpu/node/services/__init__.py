"""Service interfaces (L4) and in-memory/persistent implementations (L5)."""

from .api import (  # noqa: F401
    IdentityService,
    KeyManagementService,
    NetworkMapCache,
    NodeInfo,
    ServiceHub,
    ServiceInfo,
    ServiceType,
    StorageService,
    UniquenessConflict,
    UniquenessException,
    UniquenessProvider,
    VaultService,
)
