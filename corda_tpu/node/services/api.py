"""Service interfaces — the ServiceHub surface.

Capability match for the reference's core node-services API (reference:
core/src/main/kotlin/net/corda/core/node/ServiceHub.kt:22-77 and
core/src/main/kotlin/net/corda/core/node/services/Services.kt,
UniquenessProvider.kt, NetworkMapCache.kt, IdentityService.kt,
ServiceType.kt, NodeInfo.kt): every flow and service reaches the node's
capabilities through this registry.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Sequence

from ...contracts.structures import StateAndRef, StateRef, Timestamp, TransactionState
from ...crypto.composite import CompositeKey
from ...crypto.hashes import SecureHash
from ...crypto.keys import DigitalSignature, KeyPair, PublicKey
from ...crypto.party import Party
from ...serialization.codec import register
from ...utils.excheckpoint import register_flow_exception


# ---------------------------------------------------------------------------
# Service descriptors (reference: ServiceType.kt:35-60, NodeInfo.kt)
# ---------------------------------------------------------------------------


@register
@dataclass(frozen=True)
class ServiceType:
    """Hierarchical dotted service identifier (reference: ServiceType.kt)."""

    id: str

    def is_sub_type_of(self, parent: "ServiceType") -> bool:
        return self.id == parent.id or self.id.startswith(parent.id + ".")

    def get_sub_type(self, sub: str) -> "ServiceType":
        return ServiceType(f"{self.id}.{sub}")

    def __str__(self) -> str:
        return self.id


CORDA_SERVICE = ServiceType("corda")
NOTARY_TYPE = CORDA_SERVICE.get_sub_type("notary")
SIMPLE_NOTARY = NOTARY_TYPE.get_sub_type("simple")
VALIDATING_NOTARY = NOTARY_TYPE.get_sub_type("validating")
RAFT_VALIDATING_NOTARY = VALIDATING_NOTARY.get_sub_type("raft")
NETWORK_MAP_TYPE = CORDA_SERVICE.get_sub_type("network_map")


@register
@dataclass(frozen=True)
class ServiceInfo:
    """An advertised service: type plus optional cluster identity name
    (reference: ServiceInfo in ServiceType.kt)."""

    type: ServiceType
    name: str | None = None


@register
@dataclass(frozen=True)
class PhysicalLocation:
    """Approximate geography for visualisation (reference:
    core/.../node/PhysicalLocationStructures.kt)."""

    latitude: float | None = None
    longitude: float | None = None
    description: str = ""


@register
@dataclass(frozen=True)
class NodeInfo:
    """Everything the network map knows about a node (reference: NodeInfo.kt):
    its transport address, legal identity, advertised services."""

    address: Any  # a MessageRecipient understood by the messaging layer
    legal_identity: Party
    advertised_services: tuple[ServiceInfo, ...] = ()
    physical_location: PhysicalLocation | None = None

    @property
    def notary_identity(self) -> Party:
        return self.legal_identity


# ---------------------------------------------------------------------------
# Vault (reference: Services.kt:41-200)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Vault:
    """An immutable snapshot of unconsumed states (reference: Services.kt:41)."""

    states: tuple[StateAndRef, ...]

    @dataclass(frozen=True)
    class Update:
        """Delta produced by a transaction hitting the vault
        (reference: Services.kt:58-78)."""

        consumed: frozenset
        produced: frozenset

        @property
        def is_empty(self) -> bool:
            return not self.consumed and not self.produced

        def __add__(self, rhs: "Vault.Update") -> "Vault.Update":
            combined_produced = (self.produced - rhs.consumed) | rhs.produced
            return Vault.Update(
                consumed=self.consumed | (rhs.consumed - self.produced),
                produced=combined_produced,
            )


NO_UPDATE = Vault.Update(frozenset(), frozenset())


class VaultService:
    """Tracks unconsumed states relevant to the node (reference:
    Services.kt:95-200).

    The query/selection surface (query, iter_unconsumed, select_coins,
    balances) has in-memory default implementations here so every engine
    answers the same API; the indexed engine (services/vault.py)
    overrides them with sqlite pushdowns. Callers should prefer these
    over materializing current_vault — a million-state vault must never
    be copied to answer a page or pick coins."""

    @property
    def current_vault(self) -> Vault:
        raise NotImplementedError

    def notify_all(self, txns: Iterable) -> Vault:
        """Feed observed (verified) transactions into the vault."""
        raise NotImplementedError

    def notify(self, tx) -> Vault:
        return self.notify_all([tx])

    def subscribe(self, observer: Callable[[Vault.Update], None]) -> None:
        raise NotImplementedError

    def states_of_type(self, cls: type) -> list[StateAndRef]:
        return [s for s in self.current_vault.states if isinstance(s.state.data, cls)]

    # -- paginated query surface (engine-shared API) -----------------------

    @property
    def softlocks(self):
        """The engine's soft-lock table, created on first selection."""
        sl = self.__dict__.get("_softlocks")
        if sl is None:
            from .vault import SoftLockManager

            sl = self._softlocks = SoftLockManager()
        return sl

    def iter_unconsumed(self, of_type: type | None = None, batch: int = 512):
        """Iterate unconsumed states without materializing a snapshot."""
        for sar in self.current_vault.states:
            if of_type is None or isinstance(sar.state.data, of_type):
                yield sar

    def unconsumed_states(self, of_type: type | None = None) -> list:
        """Compatibility shim: a full typed listing via the iterator."""
        return list(self.iter_unconsumed(of_type))

    def query(self, q) -> Any:
        """Answer one VaultQuery page. Default: python-side predicate
        evaluation over the iterator with the same (ref_txhash,
        ref_index) keyset order as the indexed engine, so pagination
        cursors mean the same thing on both."""
        from ...obs import telemetry as _tm
        from ...obs import trace as _obs
        from .vault import (
            VaultPage,
            _participant_leaves,
            _sort_key,
            coin_of,
            record_vault_stage,
        )

        t0 = _obs.now() if _obs.ACTIVE is not None else 0.0
        _tm.inc("vault_queries_total")
        after = None
        if q.after is not None:
            after = (bytes(q.after[0]), int(q.after[1]))
        want_leaves = None
        if q.participant is not None:
            want_leaves = set(_participant_leaves(q.participant))
            if not want_leaves:
                return VaultPage((), None)
        page = max(1, int(q.page_size))
        out: list[StateAndRef] = []
        for sar in sorted(self.iter_unconsumed(q.state_type), key=_sort_key):
            if after is not None and _sort_key(sar) <= after:
                continue
            if (q.currency is not None or q.min_amount is not None
                    or q.max_amount is not None):
                currency, amount = coin_of(sar.state.data)
                if q.currency is not None and currency != q.currency:
                    continue
                if q.min_amount is not None and (
                        amount is None or amount < q.min_amount):
                    continue
                if q.max_amount is not None and (
                        amount is None or amount > q.max_amount):
                    continue
            if want_leaves is not None and not any(
                    set(_participant_leaves(p)) & want_leaves
                    for p in sar.state.data.participants):
                continue
            out.append(sar)
            if len(out) > page:
                break
        more = len(out) > page
        out = out[:page]
        next_cursor = _sort_key(out[-1]) if more and out else None
        record_vault_stage(t0, attrs={"rows": len(out), "op": "query"})
        return VaultPage(tuple(out), next_cursor)

    def select_coins(self, currency: str, quantity: int,
                     holder: bytes = b"", ttl_s: float | None = None) -> list:
        """Soft-locked coin selection, largest-first. Default engine:
        scan + sort candidates in memory; same reservation semantics and
        amount-DESC order as the indexed engine's index walk. On
        insufficient funds the partial set is returned unlocked (the
        asset's generate_spend raises InsufficientBalanceException)."""
        from ...obs import telemetry as _tm
        from ...obs import trace as _obs
        from .vault import _sort_key, coin_of, record_vault_stage

        t0 = _obs.now() if _obs.ACTIVE is not None else 0.0
        _tm.inc("vault_queries_total")
        locks = self.softlocks
        expired = locks.sweep()
        if expired:
            _tm.inc("vault_softlock_expired_total", expired)
        holder = bytes(holder) or b"anon"
        candidates = []
        for sar in self.iter_unconsumed():
            c, amount = coin_of(sar.state.data)
            if c == currency:
                candidates.append((-amount, _sort_key(sar), sar))
        candidates.sort(key=lambda t: t[:2])
        gathered: list[StateAndRef] = []
        covered = 0
        for neg_amount, _key, sar in candidates:
            if not locks.try_lock(sar.ref, holder, ttl_s):
                _tm.inc("vault_selection_conflicts_total")
                continue
            gathered.append(sar)
            covered += -neg_amount
            if covered >= quantity:
                break
        if covered < quantity:
            locks.release([sar.ref for sar in gathered], holder)
        record_vault_stage(t0, attrs={"rows": len(gathered), "op": "select"})
        return gathered

    def release_coins(self, refs: Iterable[StateRef],
                      holder: bytes = b"") -> None:
        """Drop this holder's reservations (a flow that selected but
        will not spend must give its coins back before the TTL)."""
        self.softlocks.release(refs, bytes(holder) or b"anon")

    def balances(self) -> dict[str, int]:
        """Per-currency unconsumed totals. Default: one pass over the
        iterator; the indexed engine answers from its aggregate table."""
        from .vault import coin_of

        out: dict[str, int] = {}
        for sar in self.iter_unconsumed():
            currency, amount = coin_of(sar.state.data)
            if currency is not None:
                out[currency] = out.get(currency, 0) + amount
        return {c: q for c, q in out.items() if q != 0}


# ---------------------------------------------------------------------------
# Identity, keys, storage (reference: Services.kt:206-260, IdentityService.kt)
# ---------------------------------------------------------------------------


class IdentityService:
    """Key → Party lookups (reference: core/.../services/IdentityService.kt)."""

    def register_identity(self, party: Party) -> None:
        raise NotImplementedError

    def party_from_key(self, key: CompositeKey) -> Party | None:
        raise NotImplementedError

    def party_from_name(self, name: str) -> Party | None:
        raise NotImplementedError


class KeyManagementService:
    """The node's signing keys (reference: Services.kt:206-224)."""

    @property
    def keys(self) -> dict[PublicKey, KeyPair]:
        raise NotImplementedError

    def fresh_key(self) -> KeyPair:
        raise NotImplementedError

    def sign(self, content: bytes, with_key: PublicKey) -> DigitalSignature.WithKey:
        raise NotImplementedError


class AttachmentStorage:
    """Content-addressed attachment blobs (reference:
    core/.../services/AttachmentStorage in Services.kt:226+)."""

    def open_attachment(self, id: SecureHash):
        raise NotImplementedError

    def import_attachment(self, data: bytes) -> SecureHash:
        raise NotImplementedError


class TransactionStorage:
    """Validated-transaction map (reference: core/.../services/
    TransactionStorage in Services.kt)."""

    def add_transaction(self, stx) -> None:
        raise NotImplementedError

    def get_transaction(self, id: SecureHash):
        raise NotImplementedError

    def subscribe(self, observer: Callable) -> None:
        raise NotImplementedError


@register
@dataclass(frozen=True)
class StateMachineTransactionMapping:
    """Which flow produced/recorded which transaction (reference:
    core/.../node/services/StateMachineRecordedTransactionMappingStorage.kt
    and the StateMachineTransactionMapping pair in Services.kt) — the join
    the explorer's transaction view uses to attribute ledger activity to
    the protocol run that caused it."""

    run_id: bytes
    tx_id: SecureHash


class TransactionMappingStorage:
    """Flow-run → transaction provenance log (reference:
    StateMachineRecordedTransactionMappingStorage.kt). Append-only and
    deduplicated on (run_id, tx_id): checkpoint-replayed flows re-record
    their transactions, which must not duplicate history or re-notify."""

    def add_mapping(self, run_id: bytes, tx_id: SecureHash) -> None:
        raise NotImplementedError

    def mappings(self) -> list[StateMachineTransactionMapping]:
        """Every recorded mapping in insertion order."""
        raise NotImplementedError

    def subscribe(self, observer: Callable) -> None:
        """observer(mapping) fires once per FRESH mapping."""
        raise NotImplementedError


@dataclass
class StorageService:
    """Bundle of storage sub-services (reference: Services.kt:226-259)."""

    validated_transactions: TransactionStorage
    attachments: AttachmentStorage
    state_machine_recorded_transaction_mapping: (
        TransactionMappingStorage | None) = None


# ---------------------------------------------------------------------------
# Uniqueness (reference: UniquenessProvider.kt:13-32)
# ---------------------------------------------------------------------------


@register
@dataclass(frozen=True)
class ConsumingTx:
    """Who consumed an input and where (reference: UniquenessProvider.kt:24-30)."""

    id: SecureHash
    input_index: int
    requesting_party: Party


@register
@dataclass(frozen=True)
class UniquenessConflict:
    """The double-spend evidence returned on conflict
    (reference: UniquenessProvider.kt:22)."""

    state_history: dict  # StateRef -> ConsumingTx


class UniquenessUnavailableException(Exception):
    """The uniqueness provider could not DECIDE in time (consensus quorum /
    leadership unavailable). Retriable, and says nothing about the
    transaction — the typed sibling of UniquenessException so callers never
    confuse "degraded service" with "double spend". Concrete providers
    subclass (raft.CommitTimeoutException)."""


@register_flow_exception
class UniquenessException(Exception):
    """Keeps its structured conflict through checkpoint replay."""

    def __init__(self, error: UniquenessConflict):
        super().__init__(f"Uniqueness conflict: {error}")
        self.error = error

    def __checkpoint_payload__(self):
        return self.error

    @classmethod
    def __from_checkpoint__(cls, message, payload):
        return cls(payload)


class UniquenessProvider:
    """First-committer-wins input commit log (reference:
    UniquenessProvider.kt:13-20)."""

    def commit(
        self,
        states: Sequence[StateRef],
        tx_id: SecureHash,
        caller_identity: Party,
    ) -> None:
        """Atomically claim all states for tx_id or raise UniquenessException."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Network map cache (reference: NetworkMapCache.kt)
# ---------------------------------------------------------------------------


class NetworkMapCache:
    """Local directory of known nodes."""

    @property
    def party_nodes(self) -> list[NodeInfo]:
        raise NotImplementedError

    @property
    def notary_nodes(self) -> list[NodeInfo]:
        return [
            n
            for n in self.party_nodes
            if any(s.type.is_sub_type_of(NOTARY_TYPE) for s in n.advertised_services)
        ]

    def get_node_by_legal_identity(self, party: Party) -> NodeInfo | None:
        for n in self.party_nodes:
            if n.legal_identity == party:
                return n
        return None

    def get_nodes_with_service(self, service_type: ServiceType) -> list[NodeInfo]:
        return [
            n
            for n in self.party_nodes
            if any(s.type.is_sub_type_of(service_type) for s in n.advertised_services)
        ]

    def get_any_notary(self) -> Party | None:
        nodes = self.notary_nodes
        return nodes[0].notary_identity if nodes else None

    def add_node(self, node: NodeInfo) -> None:
        raise NotImplementedError

    def remove_node(self, node: NodeInfo) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# The hub (reference: ServiceHub.kt:22-77)
# ---------------------------------------------------------------------------


@dataclass
class ServiceHub:
    """The service registry handed to flows and services."""

    identity_service: IdentityService
    key_management_service: KeyManagementService
    storage_service: StorageService
    vault_service: VaultService
    network_map_cache: NetworkMapCache
    clock: Any = None
    my_info: NodeInfo | None = None

    def load_state(self, ref: StateRef) -> TransactionState | None:
        """Resolve a StateRef via validated-transaction storage
        (ServiceHub.kt:59-67)."""
        stx = self.storage_service.validated_transactions.get_transaction(ref.txhash)
        if stx is None:
            return None
        return stx.tx.outputs[ref.index]

    def record_transactions(self, txs, run_id: bytes | None = None) -> None:
        """Store + vault-notify observed transactions (ServiceHub.kt:38-46).

        Idempotent: transactions already in durable storage are skipped, so
        checkpoint-replayed flows re-recording a dependency cannot resurrect
        vault states that a later transaction already consumed.

        `run_id` (when the caller is a flow — FlowLogic.record_transactions
        passes its own) lands each tx in the provenance log, the reference's
        StateMachineRecordedTransactionMappingStorage capability. Mapped for
        EVERY tx passed, not just fresh ones: a flow that records an
        already-known dependency still touched it, and the mapping store
        dedupes (run_id, tx_id) itself."""
        storage = self.storage_service.validated_transactions
        fresh = [stx for stx in txs if storage.get_transaction(stx.id) is None]
        for stx in fresh:
            storage.add_transaction(stx)
        mapping = self.storage_service.state_machine_recorded_transaction_mapping
        if mapping is not None and run_id is not None:
            for stx in txs:
                mapping.add_mapping(run_id, stx.id)
        if fresh:
            self.vault_service.notify_all(fresh)

    @property
    def legal_identity_key(self) -> KeyPair:
        assert self.my_info is not None
        key = self.my_info.legal_identity.owning_key.single_key
        return self.key_management_service.keys[key]

    @property
    def my_identity(self) -> Party:
        assert self.my_info is not None
        return self.my_info.legal_identity
