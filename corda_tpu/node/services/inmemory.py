"""In-memory service implementations.

Capability matches: InMemoryIdentityService (reference:
node/src/main/kotlin/net/corda/node/services/identity/InMemoryIdentityService.kt),
E2ETestKeyManagementService (node/.../keys/E2ETestKeyManagementService.kt),
in-memory transaction/attachment storage, NodeVaultService UTXO tracking
(node/.../vault/NodeVaultService.kt:39), InMemoryNetworkMapCache
(node/.../network/InMemoryNetworkMapCache.kt), InMemoryUniquenessProvider
(node/.../transactions/InMemoryUniquenessProvider.kt:14).

These are the MockNetwork-tier services; persistent (sqlite) twins live in
persistence.py.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Iterable, Sequence

from ...contracts.structures import StateAndRef, StateRef
from ...crypto.composite import CompositeKey
from ...crypto.hashes import SecureHash
from ...crypto.keys import DigitalSignature, KeyPair, PublicKey
from ...crypto.party import Party
from .api import (
    AttachmentStorage,
    ConsumingTx,
    IdentityService,
    KeyManagementService,
    NetworkMapCache,
    NodeInfo,
    StateMachineTransactionMapping,
    TransactionMappingStorage,
    TransactionStorage,
    UniquenessConflict,
    UniquenessException,
    UniquenessProvider,
    Vault,
    VaultService,
)


class InMemoryIdentityService(IdentityService):
    def __init__(self):
        self._by_key: dict[CompositeKey, Party] = {}
        self._by_name: dict[str, Party] = {}

    def register_identity(self, party: Party) -> None:
        self._by_key[party.owning_key] = party
        self._by_name[party.name] = party

    def party_from_key(self, key: CompositeKey) -> Party | None:
        direct = self._by_key.get(key)
        if direct is not None:
            return direct
        # A single raw key also identifies parties whose composite contains it.
        for owning, party in self._by_key.items():
            if owning == key or owning.keys == key.keys:
                return party
        return None

    def party_from_name(self, name: str) -> Party | None:
        return self._by_name.get(name)


class SimpleKeyManagementService(KeyManagementService):
    """Keys held in memory; fresh keys generated on demand (reference:
    E2ETestKeyManagementService.kt)."""

    def __init__(self, initial_keys: Iterable[KeyPair] = ()):
        self._keys: dict[PublicKey, KeyPair] = {kp.public: kp for kp in initial_keys}

    @property
    def keys(self) -> dict[PublicKey, KeyPair]:
        return dict(self._keys)

    def add_key(self, kp: KeyPair) -> None:
        self._keys[kp.public] = kp

    def fresh_key(self) -> KeyPair:
        kp = KeyPair.generate()
        self._keys[kp.public] = kp
        return kp

    def sign(self, content: bytes, with_key: PublicKey) -> DigitalSignature.WithKey:
        kp = self._keys.get(with_key)
        if kp is None:
            raise KeyError(f"No private key known for {with_key}")
        return kp.sign(content)


class InMemoryTransactionStorage(TransactionStorage):
    def __init__(self):
        self._txs: dict[SecureHash, object] = {}
        self._observers: list[Callable] = []

    def add_transaction(self, stx) -> None:
        if stx.id in self._txs:
            return
        self._txs[stx.id] = stx
        for obs in list(self._observers):
            obs(stx)

    def get_transaction(self, id: SecureHash):
        return self._txs.get(id)

    def all_transactions(self):
        return list(self._txs.values())  # dicts preserve insertion order

    def subscribe(self, observer: Callable) -> None:
        self._observers.append(observer)

    def __len__(self):
        return len(self._txs)


class InMemoryTransactionMappingStorage(TransactionMappingStorage):
    """Flow-run → tx provenance log (reference:
    node/.../services/transactions/InMemoryStateMachineRecordedTransaction
    MappingStorage capability, via the Services.kt interface)."""

    def __init__(self):
        self._mappings: list[StateMachineTransactionMapping] = []
        self._seen: set[tuple[bytes, SecureHash]] = set()
        self._observers: list[Callable] = []

    def add_mapping(self, run_id: bytes, tx_id: SecureHash) -> None:
        key = (bytes(run_id), tx_id)
        if key in self._seen:
            return
        self._seen.add(key)
        mapping = StateMachineTransactionMapping(bytes(run_id), tx_id)
        self._mappings.append(mapping)
        for obs in list(self._observers):
            obs(mapping)

    def mappings(self) -> list[StateMachineTransactionMapping]:
        return list(self._mappings)

    def subscribe(self, observer: Callable) -> None:
        self._observers.append(observer)


@dataclass(frozen=True)
class _InMemoryAttachment:
    id: SecureHash
    data: bytes

    def open(self) -> bytes:
        return self.data


class InMemoryAttachmentStorage(AttachmentStorage):
    """Content-addressed blobs (reference: NodeAttachmentService.kt, minus disk)."""

    def __init__(self):
        self._blobs: dict[SecureHash, bytes] = {}

    def import_attachment(self, data: bytes) -> SecureHash:
        att_id = SecureHash.sha256(data)
        self._blobs.setdefault(att_id, data)
        return att_id

    def open_attachment(self, id: SecureHash):
        data = self._blobs.get(id)
        return None if data is None else _InMemoryAttachment(id, data)


class NodeVaultService(VaultService):
    """UTXO tracking with relevancy filtering and update stream (reference:
    NodeVaultService.kt:39-120)."""

    def __init__(self, our_keys: Callable[[], set[PublicKey]]):
        self._our_keys = our_keys
        self._unconsumed: dict[StateRef, StateAndRef] = {}
        # Per-concrete-type secondary index: typed queries (the
        # coin-selection entry point) stop copying + isinstance-filtering
        # the whole vault. Each inner dict shares the outer insertion
        # order, so a single-type lookup returns exactly the subsequence
        # the old full scan produced.
        self._by_type: dict[type, dict[StateRef, StateAndRef]] = {}
        self._observers: list[Callable[[Vault.Update], None]] = []

    @property
    def current_vault(self) -> Vault:
        return Vault(tuple(self._unconsumed.values()))

    def unconsumed_states(self, of_type: type | None = None) -> list:
        """Typed vault query (reference: VaultService statesOfType — the
        coin-selection entry point)."""
        return list(self.iter_unconsumed(of_type))

    def iter_unconsumed(self, of_type: type | None = None, batch: int = 512):
        if of_type is None:
            yield from self._unconsumed.values()
            return
        matching = [stored for stored in self._by_type
                    if issubclass(stored, of_type)]
        if len(matching) == 1:
            yield from self._by_type[matching[0]].values()
        elif matching:
            # Several stored concrete types satisfy the query (an
            # interface/base-class lookup): fall back to the ordered
            # global scan so interleaving matches the pre-index listing
            # exactly.
            for sar in self._unconsumed.values():
                if isinstance(sar.state.data, of_type):
                    yield sar

    def _is_relevant(self, state) -> bool:
        ours = self._our_keys()
        return any(
            bool(set(participant.keys) & ours) for participant in state.data.participants
        )

    def notify_all(self, txns: Iterable) -> Vault:
        net = None
        for stx in txns:
            wtx = stx.tx if hasattr(stx, "tx") else stx
            consumed = frozenset(
                self._unconsumed[ref] for ref in wtx.inputs if ref in self._unconsumed
            )
            produced = frozenset(
                wtx.out_ref(i)
                for i, out in enumerate(wtx.outputs)
                if self._is_relevant(out)
            )
            update = Vault.Update(consumed=consumed, produced=produced)
            if update.is_empty:
                continue
            for sar in consumed:
                del self._unconsumed[sar.ref]
                bucket = self._by_type.get(type(sar.state.data))
                if bucket is not None:
                    bucket.pop(sar.ref, None)
                    if not bucket:
                        del self._by_type[type(sar.state.data)]
            for sar in produced:
                self._unconsumed[sar.ref] = sar
                self._by_type.setdefault(type(sar.state.data),
                                         {})[sar.ref] = sar
            locks = self.__dict__.get("_softlocks")
            if locks is not None:
                locks.release([sar.ref for sar in consumed])
            net = update if net is None else net + update
            for obs in list(self._observers):
                obs(update)
        return self.current_vault

    def subscribe(self, observer: Callable[[Vault.Update], None]) -> None:
        self._observers.append(observer)


class InMemoryNetworkMapCache(NetworkMapCache):
    def __init__(self):
        self._nodes: list[NodeInfo] = []
        self._observers: list[Callable] = []

    @property
    def party_nodes(self) -> list[NodeInfo]:
        return list(self._nodes)

    def add_node(self, node: NodeInfo) -> None:
        self._nodes = [n for n in self._nodes if n.legal_identity != node.legal_identity]
        self._nodes.append(node)
        for obs in list(self._observers):
            obs("add", node)

    def remove_node(self, node: NodeInfo) -> None:
        self._nodes = [n for n in self._nodes if n.legal_identity != node.legal_identity]
        for obs in list(self._observers):
            obs("remove", node)

    def subscribe(self, observer: Callable) -> None:
        self._observers.append(observer)


class InMemoryUniquenessProvider(UniquenessProvider):
    """First-committer-wins commit log (reference:
    InMemoryUniquenessProvider.kt:14-40)."""

    def __init__(self):
        self._committed: dict[StateRef, ConsumingTx] = {}

    def commit(
        self, states: Sequence[StateRef], tx_id: SecureHash, caller_identity: Party
    ) -> None:
        conflicts = {
            ref: self._committed[ref]
            for ref in states
            if ref in self._committed and self._committed[ref].id != tx_id
        }
        if conflicts:
            raise UniquenessException(UniquenessConflict(dict(conflicts)))
        for i, ref in enumerate(states):
            self._committed.setdefault(ref, ConsumingTx(tx_id, i, caller_identity))

    @property
    def committed_count(self) -> int:
        return len(self._committed)
