"""Durability plane: per-record CRC32C framing, online scrub, quarantine.

The crash-recovery contract (persistence.py docstring) assumes the bytes
sqlite returns are the bytes it stored.  For a uniqueness-consensus
service that assumption is the whole ballgame: a silently bit-flipped
committed-states row is a double-spend, a flipped raft entry is a
diverged replica — strictly worse than any outage the chaos plans can
produce.  This module is the single source of truth for the integrity
frame every durable table carries:

  table             crc covers
  ----------------  --------------------------------------------------
  raft_log          idx ‖ term ‖ blob        (``log_crc``)
  checkpoints       run_id ‖ blob            (``checkpoint_crc``)
  committed_states  state_ref ‖ consuming    (``committed_crc``)
  reserved_states   state_ref ‖ tx_id ‖ f64(expires_at) (``reserved_crc``)

The checksum rides a separate nullable ``crc`` column so every stored
blob stays byte-identical to the pre-durability format — the follower
append path inserts the leader's wire blob verbatim and the blob-mirror
equality the commit pipeline leans on survives unchanged.  ``crc IS
NULL`` marks a legacy row: existing databases upgrade in place via
:func:`ensure_integrity_schema` (pragma-checked ``ALTER TABLE``) and the
scrubber/fsck backfill checksums opportunistically.

Detection has three tiers, cheapest first:

  * inline — the raft replication/apply read paths and the checkpoint
    restore path verify rows they were about to trust anyway;
  * online — :class:`Scrubber`, a low-priority thread walking every
    table at a bounded row rate on its own sqlite connection;
  * boot — ``python -m corda_tpu.tools.fsck`` (tools/fsck.py), the
    offline scan/repair built from the same helpers.

Repair routes corrupt *replaceable* state (checkpoints) into the
``quarantine`` table — never silently dropped, never allowed to poison
the SMM replay loop — and turns corrupt *replicated* state into a
lagging follower (raft.py ``_heal_corrupt_entry``).  Ledger rows
(committed/reserved) are irreplaceable locally: corruption there is
counted and surfaced, repair is a peer resync (InstallSnapshot), never a
local delete.

Everything here is stdlib-only and import-light: faults/bench/fsck load
it from bare CLI processes.
"""

from __future__ import annotations

import struct
import threading
import time
from typing import Any

__all__ = [
    "crc32c",
    "log_crc",
    "checkpoint_crc",
    "committed_crc",
    "committed_crc_many",
    "reserved_crc",
    "is_disk_full",
    "ensure_integrity_schema",
    "quarantine_row",
    "COUNTERS",
    "bump",
    "stats",
    "Scrubber",
    "INTEGRITY_TABLES",
]

# -- CRC32C (Castagnoli) ------------------------------------------------------
#
# zlib.crc32 is CRC32/IEEE; the Castagnoli polynomial (0x1EDC6F41) is the
# storage-stack standard (iSCSI, ext4, Btrfs) with strictly better error
# detection for short records.  Not in the stdlib, so: one 256-entry
# table, byte-at-a-time.  Rows here are small (a raft command blob is
# hundreds of bytes, a checkpoint a few KB), so the pure-Python loop is
# well under the sqlite fsync it rides next to.

_CRC32C_POLY = 0x82F63B78  # reflected 0x1EDC6F41


def _make_table() -> tuple:
    table = []
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        table.append(c)
    return tuple(table)


_TABLE = _make_table()


def crc32c(data: bytes, crc: int = 0) -> int:
    """CRC32C of *data* (optionally continuing a running checksum)."""
    c = crc ^ 0xFFFFFFFF
    table = _TABLE
    for b in data:
        c = table[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


def log_crc(idx: int, term: int, blob: bytes) -> int:
    """Raft-log row checksum: covers position AND payload, so a row copied
    to the wrong index (torn page, bad sector remap) fails just like a
    flipped payload bit."""
    return crc32c(blob, crc32c(struct.pack("<qq", idx, term)))


def checkpoint_crc(run_id: str, blob: bytes) -> int:
    return crc32c(blob, crc32c(run_id.encode("utf-8")))


def committed_crc(state_ref: bytes, consuming: bytes) -> int:
    return crc32c(consuming, crc32c(state_ref))


# Native batch core (native/_ccommit.c), loaded lazily on first batch:
# None = not yet tried, False = unavailable (no compiler / NO_NATIVE).
_ccommit = None


def _load_ccommit():
    global _ccommit
    if _ccommit is None:
        try:
            from ...native import load_ccommit

            _ccommit = load_ccommit() or False
        except Exception:
            _ccommit = False
    return _ccommit


def committed_crc_many(pairs) -> list:
    """``[committed_crc(ref, consuming), ...]`` for a whole columnar
    commit batch. Uses the native _ccommit core when built (bit-identical
    CRC32C, GIL released across the batch — the pure-Python per-byte loop
    is fine next to an fsync but hostile inside a multi-thousand-row
    batch); falls back to the Python loop otherwise."""
    native = _load_ccommit()
    if native is not False and pairs:
        try:
            return native.committed_crc_many(
                pairs if isinstance(pairs, list) else list(pairs))
        # lint: allow(no-silent-except) malformed batch falls through to the Python loop, which raises the real per-pair error instead of an opaque native one
        except Exception:
            pass
    return [committed_crc(ref, con) for ref, con in pairs]


def reserved_crc(state_ref: bytes, tx_id: bytes, expires_at: float) -> int:
    return crc32c(struct.pack("<d", expires_at),
                  crc32c(tx_id, crc32c(state_ref)))


def is_disk_full(exc: BaseException) -> bool:
    """True for sqlite's disk-exhaustion OperationalError (and the
    injected ``disk.full`` fault, which raises the same message)."""
    msg = str(exc).lower()
    return "disk is full" in msg or "disk full" in msg


# -- schema upgrade -----------------------------------------------------------

# table -> key column (for quarantine/backfill row addressing).
INTEGRITY_TABLES = {
    "raft_log": "idx",
    "checkpoints": "run_id",
    "committed_states": "state_ref",
    "reserved_states": "state_ref",
}

_QUARANTINE_SCHEMA = """
CREATE TABLE IF NOT EXISTS quarantine (
    qid            INTEGER PRIMARY KEY AUTOINCREMENT,
    kind           TEXT NOT NULL,
    key            BLOB,
    blob           BLOB,
    reason         TEXT,
    quarantined_at REAL
);
"""


def ensure_integrity_schema(conn) -> None:
    """Idempotent in-place upgrade: add the nullable ``crc`` column to
    every integrity-framed table that exists and lacks it (sqlite has no
    ADD COLUMN IF NOT EXISTS), and create the quarantine table.  Rows
    predating the upgrade keep ``crc IS NULL`` — the legacy marker the
    scrubber backfills — so a pre-durability database opens cleanly."""
    for table in INTEGRITY_TABLES:
        cols = [r[1] for r in conn.execute(
            f"PRAGMA table_info({table})").fetchall()]
        if cols and "crc" not in cols:
            conn.execute(f"ALTER TABLE {table} ADD COLUMN crc INTEGER")
    conn.executescript(_QUARANTINE_SCHEMA)


def quarantine_row(conn, kind: str, key, blob, reason: str) -> None:
    """Move one corrupt row's payload into the quarantine table (caller
    deletes the source row in the same transaction and commits)."""
    conn.execute(
        "INSERT INTO quarantine (kind, key, blob, reason, quarantined_at) "
        "VALUES (?, ?, ?, ?, ?)",
        (kind, key if isinstance(key, (bytes, type(None))) else str(key),
         blob, reason, time.time()))


# -- process-wide counters ----------------------------------------------------
#
# Same idiom as faults.ACTIVE: detection sites that have no natural home
# object (checkpoint storage, notary shed path) count here; node_metrics
# exports a snapshot under the "durability" key.  Raft members keep their
# own per-member counters in RaftMember.metrics (they ride the stamp).

COUNTERS: dict[str, int] = {
    "checkpoints_quarantined": 0,
    "disk_full_sheds": 0,
}
_COUNTER_LOCK = threading.Lock()


def bump(key: str, n: int = 1) -> None:
    with _COUNTER_LOCK:
        COUNTERS[key] = COUNTERS.get(key, 0) + n


def stats(scrubber: "Scrubber | None" = None) -> dict:
    """node_metrics "durability" snapshot: process counters plus the
    node's scrubber counters when one is running (plain JSON types)."""
    with _COUNTER_LOCK:
        out: dict[str, Any] = dict(COUNTERS)
    if scrubber is not None:
        out.update(scrubber.stats())
    return out


# -- row verification (shared by scrubber and fsck) ---------------------------


def _row_crc(table: str, row) -> int:
    """Recompute the checksum for one (key..., crc) row of *table* as
    selected by :data:`_SCAN_SQL`."""
    if table == "raft_log":
        return log_crc(int(row[0]), int(row[1]), bytes(row[2]))
    if table == "checkpoints":
        # run_id is a bytes key; the checksum binds its hex form (the
        # same normalization DBCheckpointStorage uses at write time).
        rid = row[0]
        rid = (bytes(rid).hex() if isinstance(rid, (bytes, memoryview))
               else str(rid))
        return checkpoint_crc(rid, bytes(row[1]))
    if table == "committed_states":
        return committed_crc(bytes(row[0]), bytes(row[1]))
    return reserved_crc(bytes(row[0]), bytes(row[1]), float(row[2]))


# table -> (select with rowid pagination, key column index, crc index)
_SCAN_SQL = {
    "raft_log": ("SELECT idx, term, blob, crc, rowid FROM raft_log "
                 "WHERE rowid > ? ORDER BY rowid LIMIT ?", 3),
    "checkpoints": ("SELECT run_id, blob, crc, rowid FROM checkpoints "
                    "WHERE rowid > ? ORDER BY rowid LIMIT ?", 2),
    "committed_states": (
        "SELECT state_ref, consuming, crc, rowid FROM committed_states "
        "WHERE rowid > ? ORDER BY rowid LIMIT ?", 2),
    "reserved_states": (
        "SELECT state_ref, tx_id, expires_at, crc, rowid "
        "FROM reserved_states WHERE rowid > ? ORDER BY rowid LIMIT ?", 3),
}

_BACKFILL_SQL = {
    "raft_log": "UPDATE raft_log SET crc=? WHERE rowid=? AND crc IS NULL",
    "checkpoints":
        "UPDATE checkpoints SET crc=? WHERE rowid=? AND crc IS NULL",
    "committed_states":
        "UPDATE committed_states SET crc=? WHERE rowid=? AND crc IS NULL",
    "reserved_states":
        "UPDATE reserved_states SET crc=? WHERE rowid=? AND crc IS NULL",
}


def _table_exists(conn, table: str) -> bool:
    return conn.execute(
        "SELECT 1 FROM sqlite_master WHERE type='table' AND name=?",
        (table,)).fetchone() is not None


def scan_table(conn, table: str, *, repair: bool = False,
               chunk: int = 256, throttle=None) -> dict:
    """Walk one table verifying checksums. Returns ``{"scanned", "corrupt",
    "legacy", "backfilled", "corrupt_keys"}``.

    ``repair`` backfills legacy rows and, for ``checkpoints`` only,
    quarantines corrupt rows (replicated/ledger tables are never repaired
    here — raft heals through consensus, ledger rows resync from peers).
    ``throttle`` is called once per chunk with the chunk's row count (the
    scrubber's rate bound); None scans flat out (fsck)."""
    out = {"scanned": 0, "corrupt": 0, "legacy": 0, "backfilled": 0,
           "corrupt_keys": []}
    if not _table_exists(conn, table):
        return out
    sql, crc_idx = _SCAN_SQL[table]
    last_rowid = 0
    while True:
        rows = conn.execute(sql, (last_rowid, chunk)).fetchall()
        if not rows:
            break
        dirty = False
        for row in rows:
            last_rowid = row[-1]
            out["scanned"] += 1
            stored = row[crc_idx]
            want = _row_crc(table, row)
            if stored is None:
                out["legacy"] += 1
                if repair:
                    conn.execute(_BACKFILL_SQL[table], (want, row[-1]))
                    out["backfilled"] += 1
                    dirty = True
            elif int(stored) != want:
                out["corrupt"] += 1
                key = row[0] if table != "raft_log" else int(row[0])
                out["corrupt_keys"].append(
                    key.hex() if isinstance(key, (bytes, memoryview))
                    else key)
                if repair and table == "checkpoints":
                    quarantine_row(conn, "checkpoint", str(row[0]),
                                   bytes(row[1]), "crc mismatch (scrub)")
                    conn.execute("DELETE FROM checkpoints WHERE rowid=?",
                                 (row[-1],))
                    bump("checkpoints_quarantined")
                    dirty = True
        if dirty:
            conn.commit()
        if throttle is not None:
            throttle(len(rows))
    return out


class Scrubber:
    """Low-priority online scrub: a daemon thread walking every
    integrity-framed table of one node database at a bounded row rate on
    its OWN sqlite connection (WAL readers never block the node's
    writer), backfilling legacy checksums and quarantining corrupt
    checkpoints as it goes.  Counters surface via node_metrics
    ("durability" key); each full pass records a ``scrub`` span when
    tracing is armed."""

    def __init__(self, db_path, rows_per_s: float = 500.0,
                 interval_s: float = 5.0, node_name: str = ""):
        self.db_path = str(db_path)
        self.rows_per_s = max(1.0, float(rows_per_s))
        self.interval_s = max(0.1, float(interval_s))
        self.node_name = node_name
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self.counters = {"integrity_scans": 0, "integrity_errors": 0,
                         "crc_backfilled": 0, "scrub_passes": 0}

    # -- lifecycle --------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name=f"scrub-{self.node_name}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def stats(self) -> dict:
        with self._lock:
            return dict(self.counters)

    # -- scanning ---------------------------------------------------------

    def run_pass(self, repair: bool = True) -> dict:
        """One full scan of every table; also callable synchronously
        (tests, fsck-style gates) with the thread stopped."""
        import sqlite3

        from ...obs import trace as _obs

        t0 = _obs.now() if _obs.ACTIVE is not None else 0.0
        budget = [0.0]

        def throttle(rows: int) -> None:
            # Bounded rate: sleep off the time this chunk "cost" at the
            # configured rows/s, minus what scanning actually took.
            budget[0] += rows / self.rows_per_s
            if budget[0] > 0.05 and not self._stop.is_set():
                time.sleep(min(budget[0], 0.25))
                budget[0] = 0.0

        totals = {"scanned": 0, "corrupt": 0, "legacy": 0, "backfilled": 0}
        conn = sqlite3.connect(self.db_path, timeout=5.0)
        try:
            for table in INTEGRITY_TABLES:
                if self._stop.is_set():
                    break
                res = scan_table(conn, table, repair=repair,
                                 throttle=throttle)
                for k in totals:
                    totals[k] += res[k]
        finally:
            conn.close()
        with self._lock:
            self.counters["integrity_scans"] += totals["scanned"]
            self.counters["integrity_errors"] += totals["corrupt"]
            self.counters["crc_backfilled"] += totals["backfilled"]
            self.counters["scrub_passes"] += 1
        if _obs.ACTIVE is not None:
            _obs.record("scrub", t0, _obs.now(),
                        attrs={"node": self.node_name, **totals})
        return totals

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.run_pass(repair=True)
            except Exception:
                # A scrub pass racing a table rebuild (snapshot install,
                # reshard purge) can lose benignly; the next pass rescans.
                # Counted so a persistently failing scrubber is visible in
                # node_metrics instead of silently scanning nothing.
                with self._lock:
                    self.counters["scrub_pass_failures"] = \
                        self.counters.get("scrub_pass_failures", 0) + 1
            self._stop.wait(self.interval_s)
