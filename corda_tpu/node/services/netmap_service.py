"""The network map directory service — dynamic registration over the wire.

Capability match for the reference's NetworkMapService (reference:
node/src/main/kotlin/net/corda/node/services/network/NetworkMapService.kt:
37-60 and PersistentNetworkMapService.kt): one designated node runs the
directory; peers push SIGNED registrations (add/remove with a monotonically
increasing serial so replayed or out-of-order updates are rejected), fetch
the current map, and subscribe for pushed updates.

The static netmap FILE (corda_tpu/node/config.py) remains the bootstrap
mechanism — a node needs the map service's own address from somewhere; this
service takes over from there, exactly as the reference bootstraps the map
node from config.

Wire shape (topic "platform.netmap"):
  RegistrationRequest(signed NodeRegistration)  -> RegistrationResponse
  FetchMapRequest                               -> FetchMapResponse(nodes)
  SubscribeRequest                              -> (pushed) MapUpdate per change
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from ...crypto.party import Party
from ...crypto.signed_data import SignedData
from ...serialization.codec import deserialize, register, serialize
from ..messaging.api import Message, MessagingService, TopicSession
from .api import NodeInfo

NETMAP_TOPIC = "platform.netmap"

ADD = "add"
REMOVE = "remove"


@register
@dataclass(frozen=True)
class NodeRegistration:
    """What a node signs to join/leave the map (NetworkMapService.kt
    NodeRegistration): its info, a serial for ordering, add/remove."""

    node_info: NodeInfo
    serial: int
    kind: str  # ADD | REMOVE


@register
@dataclass(frozen=True)
class RegistrationRequest:
    registration: SignedData  # over a serialized NodeRegistration
    reply_to: Any  # transport address


@register
@dataclass(frozen=True)
class RegistrationResponse:
    success: bool
    error: str | None = None


@register
@dataclass(frozen=True)
class FetchMapRequest:
    reply_to: Any
    subscribe: bool = False


@register
@dataclass(frozen=True)
class FetchMapResponse:
    nodes: tuple = ()


@register
@dataclass(frozen=True)
class MapUpdate:
    kind: str
    node_info: NodeInfo


class NetworkMapService:
    """Server side, hosted by the map node."""

    def __init__(self, messaging: MessagingService):
        self._messaging = messaging
        self._nodes: dict[str, NodeInfo] = {}  # party name -> info
        self._serials: dict[str, int] = {}
        self._subscribers: list[Any] = []
        messaging.add_message_handler(NETMAP_TOPIC, 0, self._on_message)

    def _on_message(self, message: Message) -> None:
        try:
            payload = deserialize(message.data)
        except Exception:
            return
        if isinstance(payload, RegistrationRequest):
            response = self._register(payload)
            self._send(payload.reply_to, response)
        elif isinstance(payload, FetchMapRequest):
            self._send(payload.reply_to,
                       FetchMapResponse(tuple(self._nodes.values())))
            if payload.subscribe and payload.reply_to not in self._subscribers:
                self._subscribers.append(payload.reply_to)

    def _register(self, request: RegistrationRequest) -> RegistrationResponse:
        try:
            # verified() authenticates: the registration must be signed by
            # the registering identity's own key (NetworkMapService.kt
            # processRegistrationChangeRequest capability).
            reg = request.registration.verified()
            if not isinstance(reg, NodeRegistration):
                return RegistrationResponse(False, "not a NodeRegistration")
            identity = reg.node_info.legal_identity
            signer = request.registration.sig.by
            if signer not in identity.owning_key.keys:
                return RegistrationResponse(
                    False, "registration not signed by the node's identity")
            name = identity.name
            if reg.serial <= self._serials.get(name, -1):
                return RegistrationResponse(
                    False, f"stale serial {reg.serial}")
            self._serials[name] = reg.serial
            if reg.kind == ADD:
                self._nodes[name] = reg.node_info
            elif reg.kind == REMOVE:
                self._nodes.pop(name, None)
            else:
                return RegistrationResponse(False, f"bad kind {reg.kind!r}")
            update = MapUpdate(reg.kind, reg.node_info)
            for sub in list(self._subscribers):
                self._send(sub, update)
            return RegistrationResponse(True)
        except Exception as e:
            return RegistrationResponse(False, f"{type(e).__name__}: {e}")

    def _send(self, to, payload) -> None:
        self._messaging.send(TopicSession(NETMAP_TOPIC, 1),
                             serialize(payload).bytes, to)

    @property
    def node_count(self) -> int:
        return len(self._nodes)

    def get_node(self, name: str) -> NodeInfo | None:
        return self._nodes.get(name)

    def serial_of(self, name: str) -> int:
        return self._serials.get(name, -1)


class NetworkMapClient:
    """Client side: register this node, fetch/subscribe, and feed the local
    NetworkMapCache + identity service from pushed updates."""

    def __init__(self, messaging: MessagingService, map_address,
                 network_map_cache, identity_service, key_pair):
        self._messaging = messaging
        self._map_address = map_address
        self._cache = network_map_cache
        self._identities = identity_service
        self._key = key_pair
        self._serial = 0
        self.registered = False
        self.fetched = False
        messaging.add_message_handler(NETMAP_TOPIC, 1, self._on_message)

    def register(self, node_info: NodeInfo, kind: str = ADD) -> None:
        self._serial += 1
        reg = NodeRegistration(node_info, self._serial, kind)
        blob = serialize(reg)
        signed = SignedData(blob, self._key.sign(blob.bytes))
        self._messaging.send(
            TopicSession(NETMAP_TOPIC, 0),
            serialize(RegistrationRequest(signed,
                                          self._messaging.my_address)).bytes,
            self._map_address)

    def fetch_and_subscribe(self) -> None:
        self._messaging.send(
            TopicSession(NETMAP_TOPIC, 0),
            serialize(FetchMapRequest(self._messaging.my_address,
                                      subscribe=True)).bytes,
            self._map_address)

    def _on_message(self, message: Message) -> None:
        try:
            payload = deserialize(message.data)
        except Exception:
            return
        if isinstance(payload, RegistrationResponse):
            if payload.success:
                self.registered = True
        elif isinstance(payload, FetchMapResponse):
            for info in payload.nodes:
                self._apply(ADD, info)
            self.fetched = True
        elif isinstance(payload, MapUpdate):
            self._apply(payload.kind, payload.node_info)

    def _apply(self, kind: str, info: NodeInfo) -> None:
        if kind == ADD:
            self._identities.register_identity(info.legal_identity)
            self._cache.add_node(info)
        else:
            self._cache.remove_node(info)
