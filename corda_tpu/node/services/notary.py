"""Notary services: node-side assembly of the uniqueness-consensus service.

Capability match for the reference's notary service classes (reference:
node/src/main/kotlin/net/corda/node/services/transactions/NotaryService.kt:17-26,
SimpleNotaryService.kt, ValidatingNotaryService.kt): each registers a flow
factory so that a client's NotaryClientFlow session spawns the right service
flow, wired to this node's TimestampChecker and UniquenessProvider.

The service object is a checkpoint token (SerializeAsToken equivalent), so
in-flight notarisation flows survive node restarts.

Pipeline parallelism on the validating path: the service flow suspends at
two pump seams — verify_signatures_batched (the verify micro-batch, served
by the async feeder thread when batch.async_verify is on) and the Raft
commit ServiceRequest (commit_async). The node round drains completed
verifies BEFORE flushing AppendEntries, so tx N's replication overlaps
tx N+1's device verify without this module doing anything special; keep
new service-side work behind those same seams or it re-serialises the
round (see ARCHITECTURE.md "Async verify pipeline").

The commit seam is also the group-commit seam (ARCHITECTURE.md "Commit
pipeline"): every notary flow whose commit_async submits during one
poll_services pass rides ONE PutAllBatch log entry on the raft leader —
conflict isolation stays per-request (a double-spend in the batch rejects
alone, its siblings commit), so nothing here needs to sort or segregate
requests before committing. Keep commits going through commit_async one
request at a time; batching is the consensus layer's job.
"""

from __future__ import annotations

from ...contracts.structures import DEFAULT_TIMESTAMP_TOLERANCE_MICROS
from ...crypto.keys import DigitalSignature, KeyPair
from ...crypto.party import Party
from ...flows.notary import NotaryServiceFlow, ValidatingNotaryFlow
from ...serialization.tokens import SerializeAsToken
from ...utils.clock import Clock
from ..statemachine import StateMachineManager
from .api import ServiceHub, UniquenessProvider


class TimestampChecker:
    """Validity window check for transaction timestamps (reference:
    core/.../node/services/TimestampChecker.kt:12-26)."""

    def __init__(self, clock: Clock | None = None,
                 tolerance_micros: int | None = None):
        if tolerance_micros is None:
            tolerance_micros = DEFAULT_TIMESTAMP_TOLERANCE_MICROS
        self.clock = clock or Clock()
        self.tolerance_micros = tolerance_micros

    def is_valid(self, timestamp) -> bool:
        now = self.clock.now_micros()
        if timestamp.before is not None and now - timestamp.before > self.tolerance_micros:
            return False
        if timestamp.after is not None and timestamp.after - now > self.tolerance_micros:
            return False
        return True


class NotaryServiceBase(SerializeAsToken):
    """Common wiring: flow factory registration + signing."""

    flow_class = NotaryServiceFlow

    def __init__(
        self,
        smm: StateMachineManager,
        services: ServiceHub,
        notary_identity: Party,
        notary_key: KeyPair,
        uniqueness_provider: UniquenessProvider,
        timestamp_checker: TimestampChecker | None = None,
    ):
        self.services = services
        self.notary_identity = notary_identity
        self._notary_key = notary_key
        self.uniqueness_provider = uniqueness_provider
        self.timestamp_checker = timestamp_checker or TimestampChecker(
            getattr(services, "clock", None) or Clock()
        )
        smm.token_context.register(self)
        smm.register_flow_initiator(
            "NotaryClientFlow", lambda party: self.flow_class(party, self)
        )

    @property
    def token_name(self) -> str:
        return f"notary:{self.notary_identity.name}"

    def sign(self, data: bytes) -> DigitalSignature.WithKey:
        return self._notary_key.sign(data)


class SimpleNotaryService(NotaryServiceBase):
    """Non-validating (reference: SimpleNotaryService.kt:11-21)."""

    flow_class = NotaryServiceFlow


class ValidatingNotaryService(NotaryServiceBase):
    """Fully validating (reference: ValidatingNotaryService.kt:11-21)."""

    flow_class = ValidatingNotaryFlow


def rebuild_notary_service(old: NotaryServiceBase, node) -> NotaryServiceBase:
    """Re-wire a notary service onto a restarted node, keeping the durable
    uniqueness provider (MockNode.restart support)."""
    return type(old)(
        node.smm,
        node.services,
        node.identity,
        node.key,
        old.uniqueness_provider,
        old.timestamp_checker,
    )
