"""Durable (sqlite) service implementations — the node's persistence tier.

Capability match for the reference's DB-backed stores (reference:
node/src/main/kotlin/net/corda/node/services/persistence/DBCheckpointStorage.kt:17-57,
DBTransactionStorage.kt, node/.../transactions/PersistentUniquenessProvider.kt:19-82,
node/.../utilities/JDBCHashMap.kt) re-based on sqlite: one file per node, WAL
mode, every mutation committed before the call returns, so a node process can
be killed at any point and a fresh process over the same file resumes — the
crash-recovery contract the checkpoint/replay suite exercises.

Values are stored as canonical-codec blobs (corda_tpu/serialization/codec.py),
the same format used for wire messages and Merkle leaves; the codec whitelist
applies to whatever is read back from disk.
"""

from __future__ import annotations

import os
import sqlite3
import threading
from typing import Callable, Sequence

from ...crypto.hashes import SecureHash
from ...crypto.keys import KeyPair
from ...crypto.party import Party
from ...obs import trace as _obs
from ...serialization.codec import deserialize, serialize
from ...testing import faults as _faults
from ..statemachine import CheckpointStorage
from . import integrity as _integrity
from .api import (
    AttachmentStorage,
    ConsumingTx,
    StateMachineTransactionMapping,
    TransactionMappingStorage,
    TransactionStorage,
    UniquenessConflict,
    UniquenessException,
    UniquenessProvider,
)


def _sqlite_serialized() -> bool:
    """Is the sqlite C library in serialized mode (safe to share one
    connection across threads)? DB-API threadsafety 3 says yes directly;
    Python < 3.11 hardcodes the module attribute at 1 regardless of how
    the library was compiled, so fall back to asking the library itself
    (SQLITE_THREADSAFE=1 is serialized mode)."""
    if sqlite3.threadsafety == 3:
        return True
    conn = sqlite3.connect(":memory:")
    try:
        return conn.execute(
            "SELECT 1 FROM pragma_compile_options"
            " WHERE compile_options = 'THREADSAFE=1'").fetchone() is not None
    finally:
        conn.close()


class NodeDatabase:
    """One sqlite file holding every durable table of a node.

    The reference wires all stores through one H2 database per node
    (AbstractNode.kt:191, initialiseDatabasePersistence); the sqlite twin
    keeps that single-file property so "copy the file" == "copy the node".
    """

    _SCHEMA = """
    CREATE TABLE IF NOT EXISTS checkpoints (
        run_id BLOB PRIMARY KEY,
        blob   BLOB NOT NULL
    );
    CREATE TABLE IF NOT EXISTS transactions (
        tx_id BLOB PRIMARY KEY,
        blob  BLOB NOT NULL
    );
    CREATE TABLE IF NOT EXISTS tx_mappings (
        run_id BLOB NOT NULL,
        tx_id  BLOB NOT NULL,
        PRIMARY KEY (run_id, tx_id)
    );
    CREATE TABLE IF NOT EXISTS attachments (
        att_id BLOB PRIMARY KEY,
        data   BLOB NOT NULL
    );
    CREATE TABLE IF NOT EXISTS committed_states (
        state_ref   BLOB PRIMARY KEY,
        consuming   BLOB NOT NULL
    );
    CREATE TABLE IF NOT EXISTS node_identity (
        singleton INTEGER PRIMARY KEY CHECK (singleton = 1),
        name      TEXT NOT NULL,
        seed      BLOB NOT NULL
    );
    CREATE TABLE IF NOT EXISTS dedupe (
        message_id BLOB PRIMARY KEY
    );
    CREATE TABLE IF NOT EXISTS settings (
        key   TEXT PRIMARY KEY,
        value TEXT NOT NULL
    );
    CREATE TABLE IF NOT EXISTS outbox (
        seq        INTEGER PRIMARY KEY AUTOINCREMENT,
        peer       TEXT NOT NULL,
        unique_id  BLOB NOT NULL,
        blob       BLOB NOT NULL
    );
    CREATE INDEX IF NOT EXISTS outbox_peer ON outbox (peer, seq);
    """

    def __init__(self, path: str | os.PathLike):
        self.path = str(path)
        # Shared across the node thread and the transport's bridge threads:
        # the sqlite C library serializes statement execution (serialized
        # mode asserted below); `lock` additionally scopes multi-statement
        # transactions (e.g. the uniqueness commit) to one thread at a time.
        assert _sqlite_serialized(), "need a serialized (threadsafe) sqlite"
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self.lock = threading.RLock()
        self._conn.execute("PRAGMA journal_mode=WAL")
        self._conn.execute("PRAGMA synchronous=NORMAL")
        self._conn.executescript(self._SCHEMA)
        # Durability plane: add the nullable crc column to integrity-framed
        # tables (in-place upgrade — legacy rows keep crc NULL until the
        # scrubber backfills) and create the quarantine table.
        _integrity.ensure_integrity_schema(self._conn)
        self._conn.commit()
        self._batch_depth = 0  # node-thread round batching (see batch())
        self._batch_thread: int | None = None  # owning thread id
        self._batch_failed = False
        self._aux_conn: sqlite3.Connection | None = None
        self.aux_lock = threading.Lock()

    @property
    def conn(self) -> sqlite3.Connection:
        return self._conn

    @property
    def aux_conn(self) -> sqlite3.Connection:
        """A SECOND connection for transport bridge threads. While the node
        thread holds a round transaction open on `conn` (batch()), a bridge
        thread committing on the same connection would flush the half-built
        round; the aux connection gives bridges their own transaction scope
        (WAL handles the concurrency; busy_timeout rides out round commits).
        Reads on this connection see only COMMITTED rows — an outbox frame
        becomes sendable only once the round that produced it is durable."""
        if self._aux_conn is None:
            aux = sqlite3.connect(self.path, check_same_thread=False)
            aux.execute("PRAGMA busy_timeout=5000")
            self._aux_conn = aux
        return self._aux_conn

    @property
    def in_batch(self) -> bool:
        return (self._batch_depth > 0
                and self._batch_thread == threading.get_ident())

    def commit(self) -> None:
        """Commit now — unless the CALLING thread holds an open round batch,
        in which case the write becomes durable atomically with the whole
        round at batch() exit. Other threads (webserver uploads) keep the
        commit-before-return guarantee: batch() holds db.lock for the round,
        so a foreign thread's write+commit (done under db.lock) can never
        interleave into a half-built round transaction."""
        if self.in_batch:
            return
        self._conn.commit()

    def batch(self):
        """Context manager: coalesce every store mutation issued on the node
        thread into ONE sqlite transaction (one fsync instead of one per
        checkpoint/outbox/dedupe write). The crash contract strengthens:
        a round's checkpoint updates, outbound frames and dedupe records
        commit atomically, and inbound ACKs are sent only after that commit
        (TcpMessaging.flush_round), so a crash anywhere inside a round
        redelivers cleanly. A round that RAISES rolls back as a unit —
        committing a half-round would make dedupe records durable without
        the checkpoints they belong with. Holds db.lock for the round
        (re-entrant on the node thread); re-entrant."""
        import contextlib

        @contextlib.contextmanager
        def _batch():
            # lint: allow(no-blocking-under-lock) db.lock IS the single-writer I/O serialization lock — holding it across the round's commit/rollback is the design (one fsync per round)
            with self.lock:
                if self._batch_depth == 0:
                    self._batch_thread = threading.get_ident()
                    self._batch_failed = False
                self._batch_depth += 1
                try:
                    yield self
                except BaseException:
                    self._batch_failed = True
                    raise
                finally:
                    self._batch_depth -= 1
                    if self._batch_depth == 0:
                        self._batch_thread = None
                        try:
                            if self._batch_failed:
                                self._conn.rollback()
                            else:
                                self._conn.commit()
                        except sqlite3.ProgrammingError:
                            # close() raced the round (node.stop() from
                            # another thread): equivalent to a crash mid-
                            # round — the recovery contract (replay +
                            # redelivery) covers it.
                            pass

        return _batch()

    def close(self) -> None:
        # The aux connection is shared with bridge threads (outbox replay /
        # ack). Closing a sqlite connection while another thread is inside
        # an execute on it is a C-level crash, not an exception — so take
        # the same aux_lock every aux user holds; after close they get a
        # python-level ProgrammingError, which the bridge loop treats as
        # node shutdown.
        if self._aux_conn is not None:
            with self.aux_lock:
                self._aux_conn.close()
        self._conn.close()

    def get_setting(self, key: str) -> str | None:
        row = self._conn.execute(
            "SELECT value FROM settings WHERE key = ?", (key,)).fetchone()
        return None if row is None else row[0]

    def set_setting(self, key: str, value: str) -> None:
        self._conn.execute(
            "INSERT OR REPLACE INTO settings (key, value) VALUES (?, ?)",
            (key, value))
        self.commit()

    # -- node identity (reference: AbstractNode.kt:494-527 keypair on disk) --

    def load_or_create_identity(self, name: str,
                                seed: bytes | None = None) -> KeyPair:
        row = self._conn.execute(
            "SELECT name, seed FROM node_identity WHERE singleton = 1"
        ).fetchone()
        if row is not None:
            stored_name, stored_seed = row
            if stored_name != name:
                raise ValueError(
                    f"database belongs to node {stored_name!r}, not {name!r}")
            return KeyPair.generate(bytes(stored_seed))
        seed = seed if seed is not None else os.urandom(32)
        self._conn.execute(
            "INSERT INTO node_identity (singleton, name, seed) VALUES (1, ?, ?)",
            (name, seed))
        self._conn.commit()
        return KeyPair.generate(seed)


class DBCheckpointStorage(CheckpointStorage):
    """Checkpoint blobs keyed by run id (reference: DBCheckpointStorage.kt:17-57).
    Every update commits before returning — kill-safe at any step."""

    def __init__(self, db: NodeDatabase):
        self._db = db

    def update_checkpoint(self, run_id: bytes, blob: bytes) -> None:
        self._db.conn.execute(
            "INSERT OR REPLACE INTO checkpoints (run_id, blob, crc) "
            "VALUES (?, ?, ?)",
            (run_id, blob,
             _integrity.checkpoint_crc(bytes(run_id).hex(), blob)))
        self._db.commit()

    def remove_checkpoint(self, run_id: bytes) -> None:
        self._db.conn.execute(
            "DELETE FROM checkpoints WHERE run_id = ?", (run_id,))
        self._db.commit()

    def checkpoints(self) -> list[bytes]:
        return [blob for _rid, blob in self.items()]

    def items(self) -> list[tuple[bytes, bytes]]:
        """(run_id, blob) pairs, checksum-verified: a corrupt row is
        quarantined HERE — before it can poison the SMM replay loop — and
        its flow restores as failed-by-absence (the run id is simply not
        in the returned set). Legacy rows (crc NULL) pass through
        unverified until the scrubber backfills them."""
        with self._db.lock:
            rows = self._db.conn.execute(
                "SELECT run_id, blob, crc FROM checkpoints ORDER BY run_id"
            ).fetchall()
        out = []
        for run_id, blob, crc in rows:
            run_id, blob = bytes(run_id), bytes(blob)
            if _faults.ACTIVE is not None:
                blob = _faults.fire_disk_corrupt(blob)
            if crc is not None and _integrity.checkpoint_crc(
                    run_id.hex(), blob) != int(crc):
                self.quarantine(run_id, blob, "checkpoint crc mismatch")
                continue
            out.append((run_id, blob))
        return out

    def quarantine(self, run_id: bytes, blob: bytes, reason: str) -> None:
        """Move one corrupt/undecodable checkpoint into the quarantine
        table (counted, never silently dropped) so restore can proceed
        without it."""
        t0 = _obs.now() if _obs.ACTIVE is not None else 0.0
        with self._db.lock:
            _integrity.quarantine_row(
                self._db.conn, "checkpoint", bytes(run_id), blob, reason)
            self._db.conn.execute(
                "DELETE FROM checkpoints WHERE run_id = ?", (bytes(run_id),))
            self._db.commit()
        _integrity.bump("checkpoints_quarantined")
        if _obs.ACTIVE is not None:
            _obs.record("repair", t0, _obs.now(),
                        attrs={"kind": "checkpoint", "reason": reason})

    def __len__(self):
        (n,) = self._db.conn.execute(
            "SELECT COUNT(*) FROM checkpoints").fetchone()
        return n


class DBTransactionStorage(TransactionStorage):
    """Validated-transaction map (reference: DBTransactionStorage.kt) with the
    same observer stream as the in-memory twin."""

    def __init__(self, db: NodeDatabase):
        self._db = db
        self._observers: list[Callable] = []

    def add_transaction(self, stx) -> None:
        cur = self._db.conn.execute(
            "INSERT OR IGNORE INTO transactions (tx_id, blob) VALUES (?, ?)",
            (stx.id.bytes, serialize(stx).bytes))
        self._db.commit()
        if cur.rowcount:
            for obs in list(self._observers):
                obs(stx)

    def get_transaction(self, id: SecureHash):
        row = self._db.conn.execute(
            "SELECT blob FROM transactions WHERE tx_id = ?", (id.bytes,)
        ).fetchone()
        return None if row is None else deserialize(bytes(row[0]))

    def all_transactions(self):
        """Every stored transaction in insertion order (vault rebuild after
        a restart replays these through notify_all)."""
        rows = self._db.conn.execute(
            "SELECT blob FROM transactions ORDER BY rowid").fetchall()
        return [deserialize(bytes(r[0])) for r in rows]

    def stream_since(self, after_rowid: int = 0, batch: int = 512):
        """Yield (rowid, stx) for transactions stored after ``after_rowid``,
        fetched in bounded keyset pages — the vault-rebuild path that never
        materializes the ledger (a million-tx history streams through
        ``batch`` rows of memory at a time)."""
        cursor = int(after_rowid)
        while True:
            rows = self._db.conn.execute(
                "SELECT rowid, blob FROM transactions WHERE rowid > ? "
                "ORDER BY rowid LIMIT ?", (cursor, int(batch))).fetchall()
            if not rows:
                return
            for rowid, blob in rows:
                yield int(rowid), deserialize(bytes(blob))
            cursor = int(rows[-1][0])

    def subscribe(self, observer: Callable) -> None:
        self._observers.append(observer)

    def __len__(self):
        (n,) = self._db.conn.execute(
            "SELECT COUNT(*) FROM transactions").fetchone()
        return n


class DBTransactionMappingStorage(TransactionMappingStorage):
    """Durable flow-run → tx provenance log (reference:
    node/.../persistence per-node DB tier of StateMachineRecordedTransaction
    MappingStorage.kt). Writes ride the node thread's round batch like every
    other store mutation; (run_id, tx_id) is the primary key, so checkpoint
    replay re-records are no-ops and observers fire once per fresh row."""

    def __init__(self, db: NodeDatabase):
        self._db = db
        self._observers: list[Callable] = []

    def add_mapping(self, run_id: bytes, tx_id: SecureHash) -> None:
        cur = self._db.conn.execute(
            "INSERT OR IGNORE INTO tx_mappings (run_id, tx_id) VALUES (?, ?)",
            (bytes(run_id), tx_id.bytes))
        self._db.commit()
        if cur.rowcount:
            mapping = StateMachineTransactionMapping(bytes(run_id), tx_id)
            for obs in list(self._observers):
                obs(mapping)

    def mappings(self) -> list[StateMachineTransactionMapping]:
        rows = self._db.conn.execute(
            "SELECT run_id, tx_id FROM tx_mappings ORDER BY rowid").fetchall()
        return [StateMachineTransactionMapping(
            bytes(r), SecureHash(bytes(t))) for r, t in rows]

    def subscribe(self, observer: Callable) -> None:
        self._observers.append(observer)

    def __len__(self):
        (n,) = self._db.conn.execute(
            "SELECT COUNT(*) FROM tx_mappings").fetchone()
        return n


class _DBAttachment:
    def __init__(self, id: SecureHash, data: bytes):
        self.id = id
        self.data = data

    def open(self) -> bytes:
        return self.data


class DBAttachmentStorage(AttachmentStorage):
    """Content-addressed blobs (reference: NodeAttachmentService.kt — files on
    disk there; one table here, same id = sha256(content) contract)."""

    def __init__(self, db: NodeDatabase):
        self._db = db

    def import_attachment(self, data: bytes) -> SecureHash:
        att_id = SecureHash.sha256(data)
        # db.lock: this is reachable from the webserver's HTTP thread; the
        # lock (held by the node thread for each round transaction) keeps a
        # foreign thread's insert+commit from interleaving into a half-built
        # round, and commit() below is immediate for non-round threads.
        with self._db.lock:
            self._db.conn.execute(
                "INSERT OR IGNORE INTO attachments (att_id, data) VALUES (?, ?)",
                (att_id.bytes, data))
            self._db.commit()
        return att_id

    def open_attachment(self, id: SecureHash):
        row = self._db.conn.execute(
            "SELECT data FROM attachments WHERE att_id = ?", (id.bytes,)
        ).fetchone()
        return None if row is None else _DBAttachment(id, bytes(row[0]))


class PersistentUniquenessProvider(UniquenessProvider):
    """Durable first-committer-wins commit log (reference:
    PersistentUniquenessProvider.kt:19-82). The whole commit is one sqlite
    transaction: either every input is claimed or none is."""

    def __init__(self, db: NodeDatabase):
        self._db = db

    def commit(self, states: Sequence, tx_id: SecureHash,
               caller_identity: Party) -> None:
        if _faults.ACTIVE is not None:
            _faults.fire_disk_full()  # disk.full: sheds at the notarise path
        with self._db.lock:  # check-then-insert must be atomic vs other threads
            conn = self._db.conn
            conflicts = {}
            for ref in states:
                row = conn.execute(
                    "SELECT consuming FROM committed_states WHERE state_ref = ?",
                    (serialize(ref).bytes,)).fetchone()
                if row is not None:
                    consuming = deserialize(bytes(row[0]))
                    if consuming.id != tx_id:
                        conflicts[ref] = consuming
            if conflicts:
                raise UniquenessException(UniquenessConflict(dict(conflicts)))
            inserted: list[bytes] = []
            try:
                for i, ref in enumerate(states):
                    ref_blob = serialize(ref).bytes
                    consuming_blob = serialize(
                        ConsumingTx(tx_id, i, caller_identity)).bytes
                    before = conn.total_changes
                    conn.execute(
                        "INSERT OR IGNORE INTO committed_states "
                        "(state_ref, consuming, crc) VALUES (?, ?, ?)",
                        (ref_blob, consuming_blob,
                         _integrity.committed_crc(ref_blob, consuming_blob)))
                    if conn.total_changes > before:
                        inserted.append(ref_blob)
                self._db.commit()
            except sqlite3.OperationalError:
                # Disk exhausted mid-claim: the all-or-nothing contract
                # must hold even inside a round batch (where rollback would
                # discard unrelated writes) — compensate by deleting only
                # the rows THIS call inserted, then let the caller shed.
                for ref_blob in inserted:
                    conn.execute(
                        "DELETE FROM committed_states WHERE state_ref = ?",
                        (ref_blob,))
                raise

    @property
    def committed_count(self) -> int:
        (n,) = self._db.conn.execute(
            "SELECT COUNT(*) FROM committed_states").fetchone()
        return n
